"""Fused-XLA execution path tests: device results must match the host
executor (tolerance for float32 device accumulation)."""

import numpy as np
import pytest

from hyperspace_tpu import constants as C
from hyperspace_tpu.columnar import io as cio
from hyperspace_tpu.columnar.table import ColumnBatch
from hyperspace_tpu.plan import col, lit, Avg, Count, Max, Min, Sum


@pytest.fixture()
def df(tmp_session, tmp_path):
    rng = np.random.default_rng(5)
    n = 5000
    data = {
        "d": rng.integers(8000, 10000, n).astype(int).tolist(),
        "x": rng.uniform(0, 100, n).tolist(),
        "y": rng.uniform(0, 1, n).tolist(),
    }
    cio.write_parquet(ColumnBatch.from_pydict(data), str(tmp_path / "t" / "p.parquet"))
    return tmp_session.read.parquet(str(tmp_path / "t"))


def q(d):
    return (
        d.filter((col("d") >= 8500) & (col("d") < 9500) & (col("y") < 0.5))
        .select("d", "x", "y")
        .agg(
            Sum(col("x") * col("y")).alias("s"),
            Count(lit(1)).alias("n"),
            Min(col("x")).alias("mn"),
            Max(col("x")).alias("mx"),
            Avg(col("x")).alias("avg"),
        )
    )


class TestTpuExec:
    def test_matches_host(self, df):
        session = df.session
        host = q(df).to_pydict()
        session.set_conf(C.EXEC_TPU_ENABLED, True)
        dev = q(df).to_pydict()
        assert dev["n"] == host["n"]
        assert abs(dev["s"][0] - host["s"][0]) / abs(host["s"][0]) < 1e-4
        assert abs(dev["mn"][0] - host["mn"][0]) < 1e-4
        assert abs(dev["mx"][0] - host["mx"][0]) < 1e-4
        assert abs(dev["avg"][0] - host["avg"][0]) / abs(host["avg"][0]) < 1e-4

    def test_kernel_cache_reused(self, df):
        from hyperspace_tpu.plan import tpu_exec

        session = df.session
        session.set_conf(C.EXEC_TPU_ENABLED, True)
        tpu_exec._KERNEL_CACHE.clear()
        q(df).collect()
        assert len(tpu_exec._KERNEL_CACHE) == 1
        q(df).collect()  # same structure -> no new kernel
        assert len(tpu_exec._KERNEL_CACHE) == 1

    def test_unsupported_falls_back(self, tmp_session, tmp_path):
        # string column in batch -> host path, still correct
        cio.write_parquet(
            ColumnBatch.from_pydict({"a": [1, 2, 3], "s": ["x", "y", "x"]}),
            str(tmp_path / "t2" / "p.parquet"),
        )
        d = tmp_session.read.parquet(str(tmp_path / "t2"))
        tmp_session.set_conf(C.EXEC_TPU_ENABLED, True)
        out = d.filter(col("a") > 1).select("a", "s").agg(Count(lit(1)).alias("n")).to_pydict()
        assert out["n"] == [2]

    def test_grouped_falls_back(self, df):
        session = df.session
        session.set_conf(C.EXEC_TPU_ENABLED, True)
        out = df.group_by("d").agg(Count(lit(1)).alias("n")).collect()
        assert out.num_rows > 0

    def test_graft_entry(self):
        import __graft_entry__ as g

        fn, args = g.entry()
        matched, out = fn(*args)
        assert int(matched) > 0
        assert len(out) == 2 and float(np.asarray(out[1])) > 0

    def test_dryrun_multichip(self):
        import __graft_entry__ as g

        g.dryrun_multichip(8)


class TestTpuExecEdgeCases:
    """Regression tests for device/host semantic parity edge cases."""

    def test_zero_match_returns_null(self, df):
        session = df.session
        session.set_conf(C.EXEC_TPU_ENABLED, True)
        out = (
            df.filter(col("d") > 10**6)
            .agg(Min(col("x")).alias("mn"), Count(lit(1)).alias("n"))
            .to_pydict()
        )
        assert out == {"mn": [None], "n": [0]}

    def test_filter_above_project_falls_back_correctly(self, df):
        session = df.session
        session.set_conf(C.EXEC_TPU_ENABLED, True)
        q2 = (
            df.select((col("x") * 2).alias("z"))
            .filter(col("z") > 100)
            .agg(Sum(col("z")).alias("s"), Count(lit(1)).alias("n"))
        )
        dev = q2.to_pydict()
        session.set_conf(C.EXEC_TPU_ENABLED, False)
        host = q2.to_pydict()
        assert dev["n"] == host["n"]
        assert abs(dev["s"][0] - host["s"][0]) / abs(host["s"][0]) < 1e-9

    def test_int_min_max_exact_above_2_24(self, tmp_session, tmp_path):
        vals = [20_000_001, 20_000_005, 20_000_003]
        cio.write_parquet(
            ColumnBatch.from_pydict({"a": vals}),
            str(tmp_path / "big" / "p.parquet"),
        )
        d = tmp_session.read.parquet(str(tmp_path / "big"))
        tmp_session.set_conf(C.EXEC_TPU_ENABLED, True)
        out = d.agg(Min(col("a")).alias("mn"), Max(col("a")).alias("mx")).to_pydict()
        assert out == {"mn": [20_000_001], "mx": [20_000_005]}

    def test_int_sum_uses_host_path(self, tmp_session, tmp_path):
        # int sums can wrap in 32-bit on device -> must route to host
        n = 10_000
        cio.write_parquet(
            ColumnBatch.from_pydict({"a": [1_000_000] * n}),
            str(tmp_path / "s" / "p.parquet"),
        )
        d = tmp_session.read.parquet(str(tmp_path / "s"))
        tmp_session.set_conf(C.EXEC_TPU_ENABLED, True)
        out = d.agg(Sum(col("a")).alias("s")).to_pydict()
        assert out["s"] == [10_000_000_000]  # > 2**31: exact only on host

    def test_int64_min_sentinel_not_corrupted(self, tmp_session, tmp_path):
        cio.write_parquet(
            ColumnBatch.from_pydict({"a": [-(2**63), 5]}),
            str(tmp_path / "m" / "p.parquet"),
        )
        d = tmp_session.read.parquet(str(tmp_path / "m"))
        tmp_session.set_conf(C.EXEC_TPU_ENABLED, True)
        out = d.agg(Min(col("a")).alias("mn")).to_pydict()
        assert out["mn"] == [-(2**63)]  # guard must reject, host is exact


    def test_int_avg_uses_host_path(self, tmp_session, tmp_path):
        n = 10_000
        cio.write_parquet(
            ColumnBatch.from_pydict({"a": [1_000_000] * n}),
            str(tmp_path / "avg" / "p.parquet"),
        )
        d = tmp_session.read.parquet(str(tmp_path / "avg"))
        tmp_session.set_conf(C.EXEC_TPU_ENABLED, True)
        out = d.agg(Avg(col("a")).alias("m")).to_pydict()
        assert out["m"] == [1_000_000.0]  # int32 device accumulator would wrap


class TestPallasTierWired:
    def test_pallas_path_matches_generic(self, tmp_session, tmp_path, monkeypatch):
        """filter -> sum(a*b)+count must route to the Pallas kernel when
        forced (interpreter off-TPU) and produce the same answer."""
        from hyperspace_tpu.plan import tpu_exec

        monkeypatch.setenv("HYPERSPACE_FORCE_PALLAS", "1")
        tpu_exec._KERNEL_CACHE.clear()
        rng = np.random.default_rng(9)
        n = 3000
        cio.write_parquet(
            ColumnBatch.from_pydict(
                {
                    "d": rng.integers(0, 100, n).astype(int).tolist(),
                    "x": rng.uniform(0, 10, n).tolist(),
                    "y": rng.uniform(0, 1, n).tolist(),
                }
            ),
            str(tmp_path / "pw" / "p.parquet"),
        )
        d = tmp_session.read.parquet(str(tmp_path / "pw"))
        tmp_session.set_conf(C.EXEC_TPU_ENABLED, True)
        qq = (
            d.filter((col("d") >= 20) & (col("d") < 50))
            .agg(Sum(col("x") * col("y")).alias("s"), Count(lit(1)).alias("n"))
        )
        dev = qq.to_pydict()
        monkeypatch.delenv("HYPERSPACE_FORCE_PALLAS")
        tmp_session.set_conf(C.EXEC_TPU_ENABLED, False)
        host = qq.to_pydict()
        tpu_exec._KERNEL_CACHE.clear()
        assert dev["n"] == host["n"]
        assert abs(dev["s"][0] - host["s"][0]) / abs(host["s"][0]) < 1e-4



class TestGroupedDeviceExec:
    def test_grouped_matches_host(self, tmp_session, tmp_path):
        rng = np.random.default_rng(31)
        n = 8000
        cio.write_parquet(
            ColumnBatch.from_pydict(
                {
                    "g": rng.choice(["a", "b", "c"], n).tolist(),
                    "k": rng.integers(0, 50, n).astype(int).tolist(),
                    "x": rng.uniform(0, 10, n).tolist(),
                }
            ),
            str(tmp_path / "g" / "p.parquet"),
        )
        d = tmp_session.read.parquet(str(tmp_path / "g"))
        q = lambda: (
            d.filter(col("k") < 25)
            .select("g", "x")
            .group_by("g")
            .agg(
                Sum(col("x")).alias("s"),
                Count(lit(1)).alias("n"),
                Min(col("x")).alias("mn"),
                Avg(col("x")).alias("a"),
            )
            .sort("g")
        )
        host = q().to_pydict()
        tmp_session.set_conf(C.EXEC_TPU_ENABLED, True)
        dev = q().to_pydict()
        tmp_session.set_conf(C.EXEC_TPU_ENABLED, False)
        assert dev["g"] == host["g"]
        assert dev["n"] == host["n"]
        assert np.allclose(dev["s"], host["s"], rtol=1e-4)
        assert np.allclose(dev["mn"], host["mn"], rtol=1e-5)
        assert np.allclose(dev["a"], host["a"], rtol=1e-4)

    def test_grouped_empty_groups_dropped(self, tmp_session, tmp_path):
        cio.write_parquet(
            ColumnBatch.from_pydict({"g": [1, 2, 3], "x": [1.0, 2.0, 3.0]}),
            str(tmp_path / "ge" / "p.parquet"),
        )
        d = tmp_session.read.parquet(str(tmp_path / "ge"))
        tmp_session.set_conf(C.EXEC_TPU_ENABLED, True)
        out = (
            d.filter(col("x") > 1.5)
            .select("g", "x")
            .group_by("g")
            .agg(Sum(col("x")).alias("s"))
            .sort("g")
            .to_pydict()
        )
        tmp_session.set_conf(C.EXEC_TPU_ENABLED, False)
        assert out == {"g": [2, 3], "s": [2.0, 3.0]}

    def test_grouped_string_agg_falls_back(self, tmp_session, tmp_path):
        # Min over a string column cannot ship; host path must serve it
        cio.write_parquet(
            ColumnBatch.from_pydict({"g": [1, 1], "s": ["b", "a"]}),
            str(tmp_path / "gs" / "p.parquet"),
        )
        d = tmp_session.read.parquet(str(tmp_path / "gs"))
        tmp_session.set_conf(C.EXEC_TPU_ENABLED, True)
        out = d.group_by("g").agg(Min(col("s")).alias("mn")).to_pydict()
        tmp_session.set_conf(C.EXEC_TPU_ENABLED, False)
        assert out == {"g": [1], "mn": ["a"]}


    def test_aliased_group_key_falls_back(self, tmp_session, tmp_path):
        """A group key produced by a renaming projection must route to the
        host path, not crash the device path (regression)."""
        cio.write_parquet(
            ColumnBatch.from_pydict({"k": [1, 1, 2], "x": [1.0, 2.0, 3.0]}),
            str(tmp_path / "ag" / "p.parquet"),
        )
        d = tmp_session.read.parquet(str(tmp_path / "ag"))
        tmp_session.set_conf(C.EXEC_TPU_ENABLED, True)
        out = (
            d.select(col("k").alias("g"), col("x"))
            .group_by("g")
            .agg(Sum(col("x")).alias("s"))
            .sort("g")
            .to_pydict()
        )
        tmp_session.set_conf(C.EXEC_TPU_ENABLED, False)
        assert out == {"g": [1, 2], "s": [3.0, 3.0]}

    def test_q1_shape_uses_grouped_kernel(self, tmp_session, tmp_path):
        from hyperspace_tpu.plan import tpu_exec

        rng = np.random.default_rng(13)
        n = 4000
        cio.write_parquet(
            ColumnBatch.from_pydict(
                {
                    "f": rng.choice(["A", "B"], n).tolist(),
                    "q": rng.uniform(1, 50, n).tolist(),
                    "dt": rng.integers(0, 100, n).astype(int).tolist(),
                }
            ),
            str(tmp_path / "q1" / "p.parquet"),
        )
        d = tmp_session.read.parquet(str(tmp_path / "q1"))
        tmp_session.set_conf(C.EXEC_TPU_ENABLED, True)
        tpu_exec._KERNEL_CACHE.clear()
        out = (
            d.filter(col("dt") <= 80)
            .select("f", "q")
            .group_by("f")
            .agg(Sum(col("q")).alias("s"))
            .sort("f")
            .to_pydict()
        )
        tmp_session.set_conf(C.EXEC_TPU_ENABLED, False)
        assert any(
            isinstance(k, tuple) and k and k[0] == "grouped"
            for k in tpu_exec._KERNEL_CACHE
        ), "grouped device kernel must fire for the Q1 shape"
        assert out["f"] == ["A", "B"]



class TestMeshExecution:
    """Fragments execute over the 8-device mesh when conf requests it."""

    def _data(self, tmp_session, tmp_path, name="mesh"):
        rng = np.random.default_rng(41)
        n = 9000
        cio.write_parquet(
            ColumnBatch.from_pydict(
                {
                    "g": rng.choice(["a", "b", "c"], n).tolist(),
                    "k": rng.integers(0, 50, n).astype(int).tolist(),
                    "x": rng.uniform(0, 10, n).tolist(),
                }
            ),
            str(tmp_path / name / "p.parquet"),
        )
        return tmp_session.read.parquet(str(tmp_path / name))

    def test_global_aggregate_on_mesh(self, tmp_session, tmp_path):
        from hyperspace_tpu.plan import tpu_exec

        d = self._data(tmp_session, tmp_path)
        q = lambda: (
            d.filter(col("k") < 25)
            .select("x", "k")
            .agg(Sum(col("x")).alias("s"), Count(lit(1)).alias("n"),
                 Min(col("x")).alias("mn"), Max(col("x")).alias("mx"))
        )
        host = q().to_pydict()
        tmp_session.set_conf(C.EXEC_TPU_ENABLED, True)
        tmp_session.set_conf("hyperspace.tpu.exec.meshDevices", 8)
        tpu_exec._KERNEL_CACHE.clear()
        dev = q().to_pydict()
        tmp_session.set_conf(C.EXEC_TPU_ENABLED, False)
        tmp_session.set_conf("hyperspace.tpu.exec.meshDevices", 0)
        assert any(isinstance(k, tuple) and k and k[0] == "mesh" for k in tpu_exec._KERNEL_CACHE)
        assert dev["n"] == host["n"]
        assert abs(dev["s"][0] - host["s"][0]) / abs(host["s"][0]) < 1e-4
        assert abs(dev["mn"][0] - host["mn"][0]) < 1e-4
        assert abs(dev["mx"][0] - host["mx"][0]) < 1e-4

    def test_grouped_aggregate_on_mesh(self, tmp_session, tmp_path):
        from hyperspace_tpu.plan import tpu_exec

        d = self._data(tmp_session, tmp_path, "mesh2")
        q = lambda: (
            d.filter(col("k") < 40)
            .select("g", "x")
            .group_by("g")
            .agg(Sum(col("x")).alias("s"), Count(lit(1)).alias("n"),
                 Avg(col("x")).alias("a"))
            .sort("g")
        )
        host = q().to_pydict()
        tmp_session.set_conf(C.EXEC_TPU_ENABLED, True)
        tmp_session.set_conf("hyperspace.tpu.exec.meshDevices", 8)
        tpu_exec._KERNEL_CACHE.clear()
        dev = q().to_pydict()
        tmp_session.set_conf(C.EXEC_TPU_ENABLED, False)
        tmp_session.set_conf("hyperspace.tpu.exec.meshDevices", 0)
        assert any(isinstance(k, tuple) and k and k[0] == "mesh" for k in tpu_exec._KERNEL_CACHE)
        assert dev["g"] == host["g"] and dev["n"] == host["n"]
        assert np.allclose(dev["s"], host["s"], rtol=1e-4)
        assert np.allclose(dev["a"], host["a"], rtol=1e-4)

    def test_mesh_int_sum_and_avg_exact(self, tmp_session, tmp_path):
        """Int SUM/AVG over the mesh: per-shard 8-bit chunk sums psum'd and
        recombined on the host — exact where an f32 psum would round (the
        Q1-shaped mesh gap closed in round 3)."""
        from hyperspace_tpu.plan import tpu_exec

        rng = np.random.default_rng(7)
        n = 9000
        qty = rng.integers(16_000_000, 17_000_000, n)
        cio.write_parquet(
            ColumnBatch.from_pydict(
                {
                    "g": rng.choice(["a", "b", "c"], n).tolist(),
                    "k": rng.integers(0, 50, n).astype(int).tolist(),
                    "qty": qty.astype(int).tolist(),
                }
            ),
            str(tmp_path / "meshint" / "p.parquet"),
        )
        d = tmp_session.read.parquet(str(tmp_path / "meshint"))
        q = lambda: (
            d.filter(col("k") < 40)
            .select("g", "qty")
            .group_by("g")
            .agg(Sum(col("qty")).alias("s"), Avg(col("qty")).alias("a"),
                 Count(lit(1)).alias("n"))
            .sort("g")
        )
        host = q().to_pydict()
        tmp_session.set_conf(C.EXEC_TPU_ENABLED, True)
        tmp_session.set_conf("hyperspace.tpu.exec.meshDevices", 8)
        tpu_exec._KERNEL_CACHE.clear()
        dev = q().to_pydict()
        tmp_session.set_conf(C.EXEC_TPU_ENABLED, False)
        tmp_session.set_conf("hyperspace.tpu.exec.meshDevices", 0)
        assert any(
            isinstance(k, tuple) and k and k[0] == "mesh"
            for k in tpu_exec._KERNEL_CACHE
        )
        assert dev["g"] == host["g"] and dev["n"] == host["n"]
        assert dev["s"] == host["s"]  # exact int64 equality, not approx
        assert dev["a"] == host["a"]  # f64(exact sum)/count on both tiers

    def test_mesh_zero_match_global(self, tmp_session, tmp_path):
        d = self._data(tmp_session, tmp_path, "mesh3")
        tmp_session.set_conf(C.EXEC_TPU_ENABLED, True)
        tmp_session.set_conf("hyperspace.tpu.exec.meshDevices", 8)
        out = d.filter(col("k") > 10**6).agg(
            Min(col("x")).alias("mn"), Count(lit(1)).alias("n")
        ).to_pydict()
        tmp_session.set_conf(C.EXEC_TPU_ENABLED, False)
        tmp_session.set_conf("hyperspace.tpu.exec.meshDevices", 0)
        assert out == {"mn": [None], "n": [0]}


class TestHungBackendWatchdog:
    """A hung backend init (e.g. a remote-TPU tunnel that never grants a
    device) must degrade the TPU/mesh path to the host executor, not freeze
    the user's query (regression: _mesh_for called bare jax.devices())."""

    def test_query_completes_with_blocking_backend(self, df, monkeypatch):
        import threading
        import time

        import jax

        from hyperspace_tpu.utils import backend as B

        session = df.session
        expected = q(df).to_pydict()

        hang = threading.Event()  # never set: probe blocks forever

        def blocking_backend():
            hang.wait()
            return "tpu"

        monkeypatch.setattr(jax, "default_backend", blocking_backend)
        monkeypatch.setenv("HYPERSPACE_BACKEND_TIMEOUT", "0.2")
        B._reset_for_testing()
        try:
            session.set_conf(C.EXEC_TPU_ENABLED, True)
            session.set_conf(C.EXEC_MESH_DEVICES, 8)
            t0 = time.time()
            got = q(df).to_pydict()
            first = time.time() - t0
            assert first < 5.0
            assert got["n"] == expected["n"]
            assert got["s"][0] == pytest.approx(expected["s"][0], rel=1e-6)
            # later queries must not re-pay the timeout while the probe hangs
            t1 = time.time()
            q(df).to_pydict()
            assert time.time() - t1 < first + 1.0
            assert B.safe_backend() is None
            assert B.safe_device_count() == 0
        finally:
            hang.set()  # unblock the daemon probe thread
            monkeypatch.undo()
            B._reset_for_testing()

    def test_probe_recovers_after_reset(self):
        from hyperspace_tpu.utils import backend as B

        B._reset_for_testing()
        assert B.safe_backend() == "cpu"  # conftest forces the cpu platform
        assert B.safe_device_count() == 8


class TestStringPredicatesOnDevice:
    """String equality/membership predicates ship as dictionary codes."""

    @pytest.fixture()
    def sdf(self, tmp_session, tmp_path):
        rng = np.random.default_rng(8)
        n = 4000
        data = {
            "cat": rng.choice(["a", "b", "c", "d"], n).tolist(),
            "x": rng.uniform(0, 100, n).tolist(),
        }
        cio.write_parquet(ColumnBatch.from_pydict(data), str(tmp_path / "s" / "p.parquet"))
        return tmp_session.read.parquet(str(tmp_path / "s"))

    def _check(self, df, q):
        session = df.session
        session.set_conf(C.EXEC_TPU_ENABLED, False)
        host = q(df).to_pydict()
        session.set_conf(C.EXEC_TPU_ENABLED, True)
        from hyperspace_tpu.plan import tpu_exec

        before = len(tpu_exec._KERNEL_CACHE)
        dev = q(df).to_pydict()
        session.set_conf(C.EXEC_TPU_ENABLED, False)
        assert len(tpu_exec._KERNEL_CACHE) >= before  # device path engaged
        for k in host:
            assert len(host[k]) == len(dev[k])
            for a, b in zip(host[k], dev[k]):
                if isinstance(b, float):
                    assert a == pytest.approx(b, rel=1e-5)
                else:
                    assert a == b
        return dev

    def test_eq_string(self, sdf):
        q = lambda d: d.filter(col("cat") == "b").agg(
            Sum(col("x")).alias("s"), Count(lit(1)).alias("n")
        )
        self._check(sdf, q)

    def test_ne_and_in_string(self, sdf):
        q = lambda d: d.filter(
            (col("cat") != "a") & col("cat").isin(["b", "c", "zzz"])
        ).agg(Sum(col("x")).alias("s"), Count(lit(1)).alias("n"))
        self._check(sdf, q)

    def test_missing_value_folds_to_empty(self, sdf):
        q = lambda d: d.filter(col("cat") == "nope").agg(Count(lit(1)).alias("n"))
        out = self._check(sdf, q)
        assert out["n"] == [0]

    def test_grouped_with_string_pred(self, sdf):
        q = lambda d: (
            d.filter(col("cat") != "d")
            .group_by("cat")
            .agg(Sum(col("x")).alias("s"), Count(lit(1)).alias("n"))
        )
        self._check(sdf, q)


class TestIntSumOnDevice:
    def test_int_sum_exact(self, tmp_session, tmp_path):
        """Int SUM must be exact on device (chunked accumulation), including
        values above 2^24 where f32 would round."""
        rng = np.random.default_rng(4)
        n = 30000
        vals = rng.integers(-(2**30), 2**30, n)
        data = {"v": vals.tolist(), "g": rng.integers(0, 5, n).tolist()}
        cio.write_parquet(ColumnBatch.from_pydict(data), str(tmp_path / "t" / "p.parquet"))
        df = tmp_session.read.parquet(str(tmp_path / "t"))

        q_global = lambda d: d.filter(col("v") != 12345).agg(Sum(col("v")).alias("s"))
        q_grouped = lambda d: d.group_by("g").agg(Sum(col("v")).alias("s"))

        tmp_session.set_conf(C.EXEC_TPU_ENABLED, False)
        host_g = q_global(df).to_pydict()
        host_gr = q_grouped(df).to_pydict()
        tmp_session.set_conf(C.EXEC_TPU_ENABLED, True)
        dev_g = q_global(df).to_pydict()
        dev_gr = q_grouped(df).to_pydict()
        tmp_session.set_conf(C.EXEC_TPU_ENABLED, False)
        assert dev_g["s"] == host_g["s"]  # exact int64 equality
        assert sorted(zip(dev_gr["g"], dev_gr["s"])) == sorted(
            zip(host_gr["g"], host_gr["s"])
        )

    def test_int_avg_exact_on_device(self, tmp_session, tmp_path):
        """Int AVG accumulates via the exact chunked sums and divides on the
        host — values above 2^24 where an f32 sum would round visibly."""
        rng = np.random.default_rng(44)
        n = 30000
        vals = rng.integers(16_000_000, 17_000_000, n)
        data = {"v": vals.tolist(), "g": rng.integers(0, 5, n).tolist()}
        cio.write_parquet(ColumnBatch.from_pydict(data), str(tmp_path / "a" / "p.parquet"))
        df = tmp_session.read.parquet(str(tmp_path / "a"))
        from hyperspace_tpu.plan import tpu_exec

        q_global = lambda d: d.filter(col("v") >= 0).agg(Avg(col("v")).alias("m"))
        q_grouped = lambda d: d.group_by("g").agg(Avg(col("v")).alias("m"))
        tmp_session.set_conf(C.EXEC_TPU_ENABLED, False)
        host_g = q_global(df).to_pydict()
        host_gr = q_grouped(df).to_pydict()
        tpu_exec._KERNEL_CACHE.clear()
        tmp_session.set_conf(C.EXEC_TPU_ENABLED, True)
        dev_g = q_global(df).to_pydict()
        dev_gr = q_grouped(df).to_pydict()
        tmp_session.set_conf(C.EXEC_TPU_ENABLED, False)
        assert len(tpu_exec._KERNEL_CACHE) > 0  # the device path actually ran
        assert dev_g["m"] == host_g["m"]  # exact: f64(exact sum)/count
        assert sorted(zip(dev_gr["g"], dev_gr["m"])) == sorted(
            zip(host_gr["g"], host_gr["m"])
        )


class TestLiteralMagnitudeScreen:
    def test_big_literal_declines_without_latching_breaker(
        self, tmp_session, tmp_path
    ):
        """An int literal beyond 2^31 against a downcast int64 column is an
        unsupported shape: it must decline to the host path BEFORE tracing,
        leaving the circuit breaker untouched (strict mode would otherwise
        raise on the benign overflow)."""
        from hyperspace_tpu.utils import backend

        cio.write_parquet(
            ColumnBatch.from_pydict({"v": [1, 2, 3, 4], "x": [1.0, 2.0, 3.0, 4.0]}),
            str(tmp_path / "lit" / "p.parquet"),
        )
        df = tmp_session.read.parquet(str(tmp_path / "lit"))
        tmp_session.set_conf(C.EXEC_TPU_ENABLED, True)
        out = (
            df.filter(col("v") < 5_000_000_000)
            .agg(Sum(col("x")).alias("s"), Count(lit(1)).alias("n"))
            .to_pydict()
        )
        tmp_session.set_conf(C.EXEC_TPU_ENABLED, False)
        assert out["n"] == [4] and out["s"] == [10.0]
        assert backend.device_healthy()  # breaker must not have latched


class TestDeviceTopK:
    @pytest.mark.parametrize("asc", [True, False])
    def test_matches_host(self, tmp_session, tmp_path, asc):
        rng = np.random.default_rng(2)
        n = 20000
        data = {
            "k": rng.integers(-(2**31), 2**31 - 1, n).astype(np.int32).tolist(),
            "v": rng.uniform(size=n).tolist(),
        }
        cio.write_parquet(ColumnBatch.from_pydict(data), str(tmp_path / "t" / "p.parquet"))
        df = tmp_session.read.parquet(str(tmp_path / "t"))
        q = lambda d: d.sort("k", ascending=asc).limit(25)
        tmp_session.set_conf(C.EXEC_TPU_ENABLED, False)
        host = q(df).to_pydict()
        from hyperspace_tpu.plan import tpu_exec

        tpu_exec._TOPK_CACHE.clear()
        tmp_session.set_conf(C.EXEC_TPU_ENABLED, True)
        dev = q(df).to_pydict()
        tmp_session.set_conf(C.EXEC_TPU_ENABLED, False)
        assert len(tpu_exec._TOPK_CACHE) == 1  # the device kernel ran
        assert dev == host

    def test_float32_keys_and_ties(self, tmp_session, tmp_path):
        n = 8192
        # heavy ties: tie order must match the host's stable sort
        data = {
            "k": ([1.5, -2.5, 0.0, 3.25] * (n // 4)),
            "i": list(range(n)),
        }
        import numpy as _np

        batch = ColumnBatch.from_pydict(data)
        cio.write_parquet(batch, str(tmp_path / "t" / "p.parquet"))
        df = tmp_session.read.parquet(str(tmp_path / "t"))
        q = lambda d: d.sort("k", ascending=False).limit(12)
        tmp_session.set_conf(C.EXEC_TPU_ENABLED, False)
        host = q(df).to_pydict()
        tmp_session.set_conf(C.EXEC_TPU_ENABLED, True)
        dev = q(df).to_pydict()
        tmp_session.set_conf(C.EXEC_TPU_ENABLED, False)
        assert dev == host


class TestDeviceGeneralSort:
    """ORDER BY without LIMIT on device: multi-key, descending, full-range
    int64, and exact f64 keys — output bit-identical to the host lexsort,
    tie order included."""

    def _roundtrip(self, tmp_session, tmp_path, name, data, orders):
        cio.write_parquet(
            ColumnBatch.from_pydict(data), str(tmp_path / name / "p.parquet")
        )
        df = tmp_session.read.parquet(str(tmp_path / name))
        q = lambda d: d.sort(*[o[0] for o in orders], ascending=[o[1] for o in orders])
        tmp_session.set_conf(C.EXEC_TPU_ENABLED, False)
        host = q(df).to_pydict()
        from hyperspace_tpu.plan import tpu_exec

        tpu_exec._SORT_CACHE.clear()
        tmp_session.set_conf(C.EXEC_TPU_ENABLED, True)
        dev = q(df).to_pydict()
        tmp_session.set_conf(C.EXEC_TPU_ENABLED, False)
        assert len(tpu_exec._SORT_CACHE) == 1  # the device sort actually ran
        assert dev == host  # bit-identical rows AND order

    def test_multikey_mixed_direction(self, tmp_session, tmp_path):
        rng = np.random.default_rng(31)
        n = 8000
        self._roundtrip(
            tmp_session,
            tmp_path,
            "ms",
            {
                "a": rng.integers(0, 40, n).tolist(),  # heavy ties
                "b": rng.integers(-(2**40), 2**40, n).tolist(),  # wide int64
                "v": rng.uniform(size=n).tolist(),
            },
            [("a", True), ("b", False)],
        )

    def test_f64_keys_exact(self, tmp_session, tmp_path):
        rng = np.random.default_rng(37)
        n = 8000
        # near-tie f64 values that collapse in f32: the three-word split
        # must still order them exactly
        base = rng.uniform(0, 1, n)
        vals = np.round(base, 2) + rng.integers(0, 3, n) * 1e-12
        self._roundtrip(
            tmp_session,
            tmp_path,
            "f64",
            {"x": vals.tolist(), "i": list(range(n))},
            [("x", False)],
        )

    def test_f64_non_representable_falls_back(self, tmp_session, tmp_path):
        """Keys needing more than 76 bits decline to the host (exactness
        gate), and the result is still the host-exact ordering."""
        from hyperspace_tpu.plan import tpu_exec

        n = 5000
        rng = np.random.default_rng(41)
        # full-mantissa randomness: hi+mid+lo == x holds for most doubles
        # (52 < 72 encodable bits) but subnormal-residue cases may decline;
        # either way the RESULT must equal the host sort
        vals = rng.uniform(1e300, 1.1e300, n)
        cio.write_parquet(
            ColumnBatch.from_pydict({"x": vals.tolist()}),
            str(tmp_path / "f64b" / "p.parquet"),
        )
        df = tmp_session.read.parquet(str(tmp_path / "f64b"))
        tmp_session.set_conf(C.EXEC_TPU_ENABLED, False)
        host = df.sort("x").to_pydict()
        tmp_session.set_conf(C.EXEC_TPU_ENABLED, True)
        dev = df.sort("x").to_pydict()
        tmp_session.set_conf(C.EXEC_TPU_ENABLED, False)
        assert dev == host

    def test_string_key_falls_back(self, tmp_session, tmp_path):
        from hyperspace_tpu.plan import tpu_exec

        rng = np.random.default_rng(43)
        n = 6000
        cio.write_parquet(
            ColumnBatch.from_pydict(
                {"s": rng.choice(["aa", "bb", "cc"], n).tolist(), "i": list(range(n))}
            ),
            str(tmp_path / "str" / "p.parquet"),
        )
        df = tmp_session.read.parquet(str(tmp_path / "str"))
        tmp_session.set_conf(C.EXEC_TPU_ENABLED, False)
        host = df.sort("s").to_pydict()
        tpu_exec._SORT_CACHE.clear()
        tmp_session.set_conf(C.EXEC_TPU_ENABLED, True)
        dev = df.sort("s").to_pydict()
        tmp_session.set_conf(C.EXEC_TPU_ENABLED, False)
        assert len(tpu_exec._SORT_CACHE) == 0  # declined: host factorization
        assert dev == host


class TestWideInt64Predicates:
    """Full-range int64 columns ship as (hi, lo) word pairs when referenced
    only in literal comparisons; the two-word compare is exact."""

    def test_wide_filter_matches_host(self, tmp_session, tmp_path):
        rng = np.random.default_rng(6)
        n = 8000
        wide = rng.integers(-(2**62), 2**62, n)
        # plant exact boundary values
        wide[0], wide[1], wide[2] = 2**40 + 7, -(2**40) - 7, 2**31  # > int32
        data = {
            "w": wide.tolist(),
            "x": rng.uniform(0, 10, n).tolist(),
        }
        cio.write_parquet(ColumnBatch.from_pydict(data), str(tmp_path / "t" / "p.parquet"))
        df = tmp_session.read.parquet(str(tmp_path / "t"))
        queries = [
            lambda d: d.filter(col("w") == 2**40 + 7).agg(Count(lit(1)).alias("n")),
            lambda d: d.filter(col("w") > 0).agg(Count(lit(1)).alias("n"), Sum(col("x")).alias("s")),
            lambda d: d.filter((col("w") >= -(2**40) - 7) & (col("w") <= 2**31)).agg(
                Count(lit(1)).alias("n")
            ),
            lambda d: d.filter(col("w") != 2**31).agg(Count(lit(1)).alias("n")),
        ]
        from hyperspace_tpu.plan import tpu_exec

        for q in queries:
            tmp_session.set_conf(C.EXEC_TPU_ENABLED, False)
            host = q(df).to_pydict()
            tmp_session.set_conf(C.EXEC_TPU_ENABLED, True)
            before = len(tpu_exec._KERNEL_CACHE)
            dev = q(df).to_pydict()
            tmp_session.set_conf(C.EXEC_TPU_ENABLED, False)
            assert len(tpu_exec._KERNEL_CACHE) > before  # device path engaged
            assert dev["n"] == host["n"]
            if "s" in host:
                assert dev["s"][0] == pytest.approx(host["s"][0], rel=1e-5)

    def test_wide_in_aggregate_falls_back(self, tmp_session, tmp_path):
        """A wide column feeding an aggregate cannot ship; the host path
        answers (sum stays exact int64)."""
        data = {"w": [2**40, 2**41, -(2**40)], "g": [1, 1, 2]}
        cio.write_parquet(ColumnBatch.from_pydict(data), str(tmp_path / "t" / "p.parquet"))
        df = tmp_session.read.parquet(str(tmp_path / "t"))
        q = lambda d: d.group_by("g").agg(Sum(col("w")).alias("s"))
        tmp_session.set_conf(C.EXEC_TPU_ENABLED, True)
        out = q(df).to_pydict()
        tmp_session.set_conf(C.EXEC_TPU_ENABLED, False)
        assert sorted(zip(out["g"], out["s"])) == [(1, 2**40 + 2**41), (2, -(2**40))]


class TestWide64PropertySweep:
    def test_random_comparisons_match_numpy(self):
        """Randomized two-word compares across the int64 domain must agree
        with numpy exactly (including extremes and word boundaries)."""
        import numpy as np
        import jax.numpy as jnp

        from hyperspace_tpu.plan import expr as X
        from hyperspace_tpu.plan.tpu_exec import Wide64
        from hyperspace_tpu.ops.hashing import split64_np

        rng = np.random.default_rng(12)
        specials = np.array(
            [0, 1, -1, 2**31, -(2**31), 2**31 - 1, 2**32, -(2**32),
             2**62, -(2**62), 2**63 - 1, -(2**63)], dtype=np.int64,
        )
        vals = np.concatenate(
            [rng.integers(-(2**63), 2**63 - 1, 2000, dtype=np.int64), specials]
        )
        lo, hi = split64_np(vals)
        w = Wide64(jnp.asarray(hi), jnp.asarray(lo.view(np.uint32)))
        lits = np.concatenate(
            [rng.integers(-(2**63), 2**63 - 1, 40, dtype=np.int64), specials]
        )
        ops = {
            X.Eq: np.equal, X.Ne: np.not_equal, X.Lt: np.less,
            X.Le: np.less_equal, X.Gt: np.greater, X.Ge: np.greater_equal,
        }
        for lit in lits[:20]:
            for kind, npop in ops.items():
                got = np.asarray(w.compare(kind, int(lit)))
                np.testing.assert_array_equal(
                    got, npop(vals, lit), err_msg=f"{kind} vs {lit}"
                )


class TestDeviceCircuitBreaker:
    def test_device_failure_degrades_to_host(self, df, monkeypatch):
        """A device kernel blowing up mid-query (dropped tunnel) must fall
        back to the host executor and latch the device tier off — queries
        keep answering correctly."""
        from hyperspace_tpu.plan import tpu_exec
        from hyperspace_tpu.utils import backend as B

        session = df.session
        expected = q(df).to_pydict()
        monkeypatch.delenv("HYPERSPACE_DEVICE_STRICT", raising=False)

        def boom(*a, **k):
            raise RuntimeError("tunnel dropped")

        monkeypatch.setattr(tpu_exec, "_try_execute_tpu_inner", boom)
        try:
            session.set_conf(C.EXEC_TPU_ENABLED, True)
            got = q(df).to_pydict()
            assert not B.device_healthy()
            assert got["n"] == expected["n"]
            # subsequent queries skip the device tier entirely, still correct
            got2 = q(df).to_pydict()
            assert got2["n"] == expected["n"]
        finally:
            session.set_conf(C.EXEC_TPU_ENABLED, False)
            B._reset_for_testing()
        assert B.device_healthy()

    def test_strict_mode_reraises(self, df, monkeypatch):
        from hyperspace_tpu.plan import tpu_exec
        from hyperspace_tpu.utils import backend as B

        session = df.session
        monkeypatch.setenv("HYPERSPACE_DEVICE_STRICT", "1")

        def boom(*a, **k):
            raise RuntimeError("bug in device path")

        monkeypatch.setattr(tpu_exec, "_try_execute_tpu_inner", boom)
        session.set_conf(C.EXEC_TPU_ENABLED, True)
        try:
            with pytest.raises(RuntimeError, match="bug in device path"):
                q(df).to_pydict()
        finally:
            session.set_conf(C.EXEC_TPU_ENABLED, False)
            B._reset_for_testing()


class TestHierarchicalMesh:
    """Multi-slice (dcn x ici) topology: aggregates psum over the axis
    pair — on hardware XLA reduces within a slice over ICI and only
    per-group partials cross DCN. The 8 virtual devices arrange as 2x4."""

    def _data(self, tmp_session, tmp_path):
        rng = np.random.default_rng(43)
        n = 9000
        cio.write_parquet(
            ColumnBatch.from_pydict(
                {
                    "g": rng.choice(["a", "b", "c"], n).tolist(),
                    "k": rng.integers(0, 50, n).astype(int).tolist(),
                    "q": rng.integers(1, 1000, n).astype(int).tolist(),
                    "x": rng.uniform(0, 10, n).tolist(),
                }
            ),
            str(tmp_path / "hier" / "p.parquet"),
        )
        return tmp_session.read.parquet(str(tmp_path / "hier"))

    def _with_hier_mesh(self, session, slices=2):
        session.set_conf(C.EXEC_TPU_ENABLED, True)
        session.set_conf("hyperspace.tpu.exec.meshDevices", 8)
        session.set_conf("hyperspace.tpu.exec.meshSlices", slices)

    def _reset(self, session):
        session.set_conf(C.EXEC_TPU_ENABLED, False)
        session.set_conf("hyperspace.tpu.exec.meshDevices", 0)
        session.set_conf("hyperspace.tpu.exec.meshSlices", 1)

    def test_active_mesh_is_hierarchical(self, tmp_session):
        from hyperspace_tpu.parallel.mesh import active_mesh

        self._with_hier_mesh(tmp_session)
        try:
            mesh = active_mesh(tmp_session)
        finally:
            self._reset(tmp_session)
        assert mesh is not None
        assert tuple(mesh.axis_names) == ("dcn", "ici")
        assert mesh.shape["dcn"] == 2 and mesh.shape["ici"] == 4

    def test_grouped_int_sums_exact_on_hier_mesh(self, tmp_session, tmp_path):
        from hyperspace_tpu.plan import tpu_exec

        d = self._data(tmp_session, tmp_path)
        q = lambda: (
            d.filter(col("k") < 40)
            .select("g", "q", "x")
            .group_by("g")
            .agg(
                Sum(col("q")).alias("sq"),
                Avg(col("q")).alias("aq"),
                Sum(col("x")).alias("sx"),
                Count(lit(1)).alias("n"),
            )
            .sort("g")
        )
        host = q().to_pydict()
        self._with_hier_mesh(tmp_session)
        tpu_exec._KERNEL_CACHE.clear()
        try:
            dev = q().to_pydict()
        finally:
            self._reset(tmp_session)
        # the hierarchical kernel actually built (topology in the cache key)
        assert any(
            isinstance(k, tuple) and k and k[0] == "mesh"
            and (("dcn", 2), ("ici", 4)) in k
            for k in tpu_exec._KERNEL_CACHE
        )
        assert dev["g"] == host["g"]
        assert dev["sq"] == host["sq"]  # exact chunked int sums
        assert dev["n"] == host["n"]
        for a, b in zip(dev["aq"], host["aq"]):
            assert abs(a - b) <= 1e-12 * max(1.0, abs(b))
        for a, b in zip(dev["sx"], host["sx"]):
            assert abs(a - b) <= 1e-6 * max(1.0, abs(b))

    def test_build_partitions_per_slice(self, tmp_session, tmp_path):
        """Index builds on a hierarchical mesh split rows across the slices
        and exchange on each slice's own 1-D submesh (all_to_all never
        crosses DCN), producing one sorted run per slice per bucket — and
        queries over the multi-run layout stay correct."""
        from hyperspace_tpu import CoveringIndexConfig, Hyperspace

        d = self._data(tmp_session, tmp_path)
        hs = Hyperspace(tmp_session)
        self._with_hier_mesh(tmp_session)
        try:
            hs.create_index(d, CoveringIndexConfig("hm", ["k"], ["x"]))
            files = [f.name for f in hs.get_index("hm").index_data_files()]
            import re

            seqs = {
                m.group(1)
                for m in (re.search(r"-b\d+-(\d+s\d+)\.", f) for f in files)
                if m
            }
            # two slices -> per-slice runs in the s<slice> sub-namespace
            # (distinct from any host-fallback "-<seq>" run of the same seq)
            assert seqs == {"0s0", "0s1"}, files
            tmp_session.enable_hyperspace()
            got = (
                tmp_session.read.parquet(str(tmp_path / "hier"))
                .filter(col("k") == 7)
                .select("k", "x")
                .agg(Sum(col("x")).alias("s"), Count(lit(1)).alias("n"))
                .to_pydict()
            )
            tmp_session.disable_hyperspace()
        finally:
            self._reset(tmp_session)
        raw = (
            self._data(tmp_session, tmp_path)
            .filter(col("k") == 7)
            .select("k", "x")
            .agg(Sum(col("x")).alias("s"), Count(lit(1)).alias("n"))
            .to_pydict()
        )
        assert got["n"] == raw["n"]
        # float sums on the mesh tier carry the documented f32 tolerance
        assert abs(got["s"][0] - raw["s"][0]) <= 1e-4 * max(1.0, abs(raw["s"][0]))

    def test_slices_must_divide_devices(self, tmp_session):
        from hyperspace_tpu.exceptions import HyperspaceError

        tmp_session.set_conf("hyperspace.tpu.exec.meshDevices", 8)
        tmp_session.set_conf("hyperspace.tpu.exec.meshSlices", 3)
        with pytest.raises(HyperspaceError, match="must divide"):
            tmp_session.conf.exec_mesh_slices
        tmp_session.set_conf("hyperspace.tpu.exec.meshSlices", 1)
        tmp_session.set_conf("hyperspace.tpu.exec.meshDevices", 0)
