"""Pipelined executor + parallel IO + cross-query kernel cache.

Covers the PR-2 tentpole guarantees:
- parallel multi-file reads are bitwise identical to serial reads,
- the chunk iterator streams every row in file order under any thread count,
- the decoded-chunk and device caches stay coherent under concurrent readers
  (thread-pool IO makes cache thread-safety load-bearing),
- pipelined execution is bit-identical to the serial (HYPERSPACE_PIPELINE=0)
  monolithic path on the TPC-H bench queries,
- a warm kernel cache serves repeat queries with zero retraces (hit counter
  up, no compile span in the trace).
"""

import os
import threading

import numpy as np
import pytest

from hyperspace_tpu import HyperspaceSession
from hyperspace_tpu import constants as C
from hyperspace_tpu.columnar import io as cio
from hyperspace_tpu.columnar.table import ColumnBatch
from hyperspace_tpu.plan import Avg, Count, Max, Min, Sum, col, lit
from hyperspace_tpu.telemetry.metrics import REGISTRY


def _write_multifile(root, n_files=5, rows=2000, seed=0):
    rng = np.random.default_rng(seed)
    paths = []
    for i in range(n_files):
        n = rows + i * 100
        data = {
            "k": rng.integers(0, 40, n).tolist(),
            "flag": rng.choice(["A", "B", "C"], n).tolist(),
            "x": rng.uniform(0, 100, n).tolist(),
            "q": rng.integers(1, 50, n).tolist(),
            "d": rng.integers(8000, 10000, n).astype("int32").tolist(),
        }
        p = os.path.join(root, "t", f"part-{i}.parquet")
        cio.write_parquet(ColumnBatch.from_pydict(data), p)
        paths.append(p)
    return paths


def _bits(pydict):
    return repr(
        {
            k: [x.hex() if isinstance(x, float) else x for x in v]
            for k, v in pydict.items()
        }
    )


class TestParallelIO:
    def test_parallel_read_matches_serial(self, tmp_path, monkeypatch):
        paths = _write_multifile(str(tmp_path))
        monkeypatch.setenv("HYPERSPACE_IO_THREADS", "1")
        serial = cio.read_parquet(paths)
        monkeypatch.setenv("HYPERSPACE_IO_THREADS", "4")
        parallel = cio.read_parquet(paths)
        assert _bits(serial.to_pydict()) == _bits(parallel.to_pydict())

    def test_chunk_iterator_covers_in_order(self, tmp_path, monkeypatch):
        paths = _write_multifile(str(tmp_path))
        monkeypatch.setenv("HYPERSPACE_STREAM_CHUNK_MB", "0.01")
        monkeypatch.setenv("HYPERSPACE_IO_THREADS", "4")
        whole = cio.read_parquet(paths, ["k", "x"])
        chunks = list(cio.iter_chunks(paths, ["k", "x"]))
        assert [c.index for c in chunks] == list(range(len(chunks)))
        assert len(chunks) >= 2  # small target: several groups
        cat = ColumnBatch.concat([c.batch for c in chunks])
        assert _bits(whole.to_pydict()) == _bits(cat.to_pydict())
        # serial (overlap off) yields the identical stream
        serial = list(cio.iter_chunks(paths, ["k", "x"], overlap=False))
        assert len(serial) == len(chunks)
        cat2 = ColumnBatch.concat([c.batch for c in serial])
        assert _bits(cat.to_pydict()) == _bits(cat2.to_pydict())

    def test_chunk_groups_respect_order_and_target(self, tmp_path):
        paths = _write_multifile(str(tmp_path))
        groups = cio.plan_chunk_groups(paths, target_bytes=1)  # one per file
        assert [p for g in groups for p in g] == paths
        assert all(len(g) == 1 for g in groups)
        one = cio.plan_chunk_groups(paths, target_bytes=1 << 40)
        assert one == [paths]

    def test_chunk_read_error_wraps_io_failures(self, tmp_path):
        with pytest.raises(cio.ChunkReadError):
            list(cio.iter_chunks([str(tmp_path / "missing.parquet")]))

    def test_chunk_cache_concurrent_readers(self, tmp_path, monkeypatch):
        """Decoded-chunk cache under thread-pool readers: every thread must
        see the same decoded bytes, and the cache's byte accounting must
        stay consistent under racing set/evict."""
        paths = _write_multifile(str(tmp_path), n_files=3)
        monkeypatch.setenv("HYPERSPACE_IO_THREADS", "4")
        expected = _bits(cio.read_parquet(paths, cache=True).to_pydict())
        errors = []

        def reader():
            try:
                for _ in range(5):
                    got = cio.read_parquet(paths, cache=True)
                    assert _bits(got.to_pydict()) == expected
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        threads = [threading.Thread(target=reader) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors
        assert cio._INDEX_CHUNK_CACHE._bytes >= 0

    def test_bytes_lru_eviction_accounting(self):
        lru = cio._BytesBoundedLRU(100, metric_name="_test_lru")
        lru.set("a", 1, 60)
        lru.set("b", 2, 60)  # evicts a
        assert lru.get("a") is None
        assert lru.get("b") == 2
        assert lru._bytes == 60
        assert REGISTRY.counter("cache._test_lru.evicted_bytes").value >= 60
        assert REGISTRY.gauge("cache._test_lru.bytes").value == 60


class TestDeviceCacheConcurrency:
    def test_concurrent_get_or_put_single_value(self, monkeypatch):
        from hyperspace_tpu.utils.device_cache import DeviceArrayCache

        monkeypatch.setenv("HYPERSPACE_TEST_DC_MB", "64")
        cache = DeviceArrayCache("HYPERSPACE_TEST_DC_MB", "64")
        src = np.arange(1000)
        builds = []

        def build():
            builds.append(1)
            return np.asarray(src, dtype=np.float32)

        results, errors = [], []

        def worker():
            try:
                for _ in range(20):
                    results.append(cache.get_or_put(src, ("pad", 1024), build))
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        threads = [threading.Thread(target=worker) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors
        # every returned value is THE cached object after the first build(s)
        assert len({id(r) for r in results[-100:]}) == 1
        assert cache.hits > 0

    def test_eviction_records_bytes_and_gauge(self, monkeypatch):
        from hyperspace_tpu.utils.device_cache import DeviceArrayCache

        monkeypatch.setenv("HYPERSPACE_TEST_DC2_MB", "0.01")  # ~10 KB budget
        cache = DeviceArrayCache("HYPERSPACE_TEST_DC2_MB", "0.01")
        srcs = [np.arange(1000) for _ in range(4)]  # 8 KB each
        for s in srcs:
            cache.get_or_put(s, ("x",), lambda s=s: s.astype(np.float32))
        assert cache.evictions > 0
        assert cache.evicted_bytes > 0
        assert cache.occupancy_bytes <= 0.01 * 2**20
        assert (
            REGISTRY.gauge("cache.host_derived.bytes").value
            == cache.occupancy_bytes
        )


@pytest.fixture()
def pipe_session(tmp_path, monkeypatch):
    """Session over a 5-file table with chunking forced small so streaming
    engages; EXEC on."""
    monkeypatch.setenv("HYPERSPACE_STREAM_CHUNK_MB", "0.02")
    _write_multifile(str(tmp_path))
    session = HyperspaceSession(warehouse_dir=str(tmp_path))
    session.set_conf(C.EXEC_TPU_ENABLED, True)
    return session, str(tmp_path / "t")


_QUERIES = {
    # concat route: float sums
    "global_float": lambda t: t.filter(col("d") < 9000).agg(
        Sum(col("x") * col("x")).alias("s"), Count(lit(1)).alias("n")
    ),
    # partial route: exact folds only
    "global_exact": lambda t: t.filter(col("d") < 9500).agg(
        Sum(col("q")).alias("sq"), Min(col("x")).alias("mn"),
        Max(col("q")).alias("mx"), Avg(col("q")).alias("aq"),
        Count(lit(1)).alias("n"),
    ),
    # grouped concat route with string keys (keys stay host-side)
    "grouped_float": lambda t: t.filter(col("d") < 9500)
    .group_by("flag")
    .agg(Sum(col("x")).alias("sx"), Avg(col("x")).alias("ax"),
         Count(lit(1)).alias("n")),
    # grouped partial route (int sums fold exactly across chunks)
    "grouped_exact": lambda t: t.filter(col("d") < 9500)
    .group_by("k")
    .agg(Sum(col("q")).alias("sq"), Min(col("q")).alias("mn"),
         Avg(col("q")).alias("aq"), Count(lit(1)).alias("n")),
    # per-chunk string-predicate re-encoding on the partial route
    "string_pred": lambda t: t.filter(col("flag") == "A").agg(
        Count(lit(1)).alias("n"), Sum(col("q")).alias("sq")
    ),
}


class TestPipelinedBitIdentity:
    @pytest.mark.parametrize("qname", sorted(_QUERIES))
    def test_pipelined_matches_serial(self, pipe_session, monkeypatch, qname):
        session, table = pipe_session
        q = _QUERIES[qname]
        monkeypatch.setenv("HYPERSPACE_PIPELINE", "1")
        before = REGISTRY.counter("pipeline.chunks").value
        on = q(session.read.parquet(table)).to_pydict()
        assert REGISTRY.counter("pipeline.chunks").value > before  # streamed
        monkeypatch.setenv("HYPERSPACE_PIPELINE", "0")
        off = q(session.read.parquet(table)).to_pydict()
        monkeypatch.setenv("HYPERSPACE_PIPELINE", "serial")
        serial = q(session.read.parquet(table)).to_pydict()
        assert _bits(on) == _bits(off)  # pipelined == monolithic, bit for bit
        assert _bits(on) == _bits(serial)  # overlap never changes results

    def test_pipelined_matches_host_exact_aggs(self, pipe_session, monkeypatch):
        """Exact aggregates (counts, int sums) must agree with the HOST tier
        too, not just across device paths."""
        session, table = pipe_session
        monkeypatch.setenv("HYPERSPACE_PIPELINE", "1")
        dev = _QUERIES["global_exact"](session.read.parquet(table)).to_pydict()
        session.set_conf(C.EXEC_TPU_ENABLED, False)
        host = _QUERIES["global_exact"](session.read.parquet(table)).to_pydict()
        session.set_conf(C.EXEC_TPU_ENABLED, True)
        assert dev["sq"] == host["sq"]
        assert dev["n"] == host["n"]
        assert dev["mx"] == host["mx"]

    def test_pipeline_off_streams_nothing(self, pipe_session, monkeypatch):
        session, table = pipe_session
        monkeypatch.setenv("HYPERSPACE_PIPELINE", "0")
        before = REGISTRY.counter("pipeline.chunks").value
        _QUERIES["global_exact"](session.read.parquet(table)).collect()
        assert REGISTRY.counter("pipeline.chunks").value == before

    def test_nullable_chunk_aborts_to_monolithic(self, tmp_path, monkeypatch):
        """A chunk with NULLs can't ship; the stream must abort cleanly and
        the query still answers (host tier) with correct results."""
        monkeypatch.setenv("HYPERSPACE_STREAM_CHUNK_MB", "0.01")
        root = str(tmp_path / "nt")
        rng = np.random.default_rng(1)
        for i in range(3):
            q = rng.integers(1, 50, 1000).astype(np.float64)
            data = {"q": q.tolist(), "d": rng.integers(0, 10, 1000).tolist()}
            b = ColumnBatch.from_pydict(data)
            if i == 1:  # poison the middle chunk with NULLs
                c = b.column("q")
                validity = np.ones(1000, dtype=bool)
                validity[::7] = False
                from hyperspace_tpu.columnar.table import Column

                b = b.with_column("q", Column(c.data, c.dtype, validity))
            cio.write_parquet(b, os.path.join(root, f"p{i}.parquet"))
        session = HyperspaceSession(warehouse_dir=str(tmp_path))
        session.set_conf(C.EXEC_TPU_ENABLED, True)
        monkeypatch.setenv("HYPERSPACE_PIPELINE", "1")
        before = REGISTRY.counter("pipeline.aborted").value
        got = (
            session.read.parquet(root)
            .filter(col("d") < 5)
            .agg(Sum(col("q")).alias("s"), Count(lit(1)).alias("n"))
            .to_pydict()
        )
        session.set_conf(C.EXEC_TPU_ENABLED, False)
        host = (
            session.read.parquet(root)
            .filter(col("d") < 5)
            .agg(Sum(col("q")).alias("s"), Count(lit(1)).alias("n"))
            .to_pydict()
        )
        assert got == host
        assert REGISTRY.counter("pipeline.aborted").value > before


class TestKernelCacheCrossQuery:
    def test_warm_repeat_has_zero_retraces(self, pipe_session, monkeypatch):
        from hyperspace_tpu.telemetry import trace

        session, table = pipe_session
        monkeypatch.setenv("HYPERSPACE_PIPELINE", "1")
        q = _QUERIES["global_float"]
        q(session.read.parquet(table)).collect()  # cold: compiles
        retraces_warm = REGISTRY.counter("kernel.retrace").value
        hits_before = REGISTRY.counter("cache.kernel.hits").value
        sink = _ListSink()
        trace.enable(sink)
        try:
            got = q(session.read.parquet(table)).to_pydict()
        finally:
            trace.disable()
        assert REGISTRY.counter("kernel.retrace").value == retraces_warm
        assert REGISTRY.counter("cache.kernel.hits").value > hits_before
        names = [s["name"] for s in sink.spans]
        assert not [n for n in names if n.startswith("compile:")]
        assert [n for n in names if n.startswith("pipeline:")]
        assert got["n"][0] is not None

    def test_fingerprints_shared_between_paths(self, pipe_session, monkeypatch):
        """A kernel compiled by the monolithic path must serve the pipelined
        path (and vice versa): identical fingerprints by construction."""
        from hyperspace_tpu.plan import tpu_exec

        session, table = pipe_session
        q = _QUERIES["global_float"]
        tpu_exec._KERNEL_CACHE.clear()
        monkeypatch.setenv("HYPERSPACE_PIPELINE", "0")
        q(session.read.parquet(table)).collect()
        n_mono = len(tpu_exec._KERNEL_CACHE)
        assert n_mono > 0
        monkeypatch.setenv("HYPERSPACE_PIPELINE", "1")
        retraces = REGISTRY.counter("kernel.retrace").value
        q(session.read.parquet(table)).collect()
        assert len(tpu_exec._KERNEL_CACHE) == n_mono  # no new kernels
        assert REGISTRY.counter("kernel.retrace").value == retraces


class _ListSink:
    """In-memory TraceSink: collects completed span names."""

    def __init__(self):
        self.spans = []

    def write_span(self, span):
        self.spans.append({"name": span.name})

    def close(self):
        pass
