"""Predicate-driven index pruning: bucket pruning, row-group skipping,
the write/read hash contract, the verify debug path, caches, telemetry.

The soundness bar: every row satisfying the predicate must survive pruning
(the plan Filter is authoritative, so over-keeping is slow and under-keeping
is a wrong answer). These tests pin the hash contract bit-for-bit, prove
end-to-end value identity pruned-vs-full on point/range/IN/null shapes, and
check the observability surfaces (counters, spans, usage events, caches).
"""

import os

import numpy as np
import pytest

from hyperspace_tpu import CoveringIndexConfig, Hyperspace
from hyperspace_tpu import constants as C
from hyperspace_tpu.columnar import io as cio
from hyperspace_tpu.columnar.table import Column, ColumnBatch
from hyperspace_tpu.plan import Count, Sum, col
from hyperspace_tpu.plan import pruning
from hyperspace_tpu.telemetry.metrics import REGISTRY


# ---------------------------------------------------------------------------
# hash contract: write-side partition_batch vs read-side literal hashing
# ---------------------------------------------------------------------------

class TestHashContract:
    """A silent divergence between the write-side bucket hash and the
    read-side literal hash would make bucket pruning drop matching rows —
    assert bit-for-bit agreement for every key dtype pruning handles."""

    @pytest.mark.parametrize("num_buckets", [1, 2, 7, 8, 64, 200])
    def test_int_keys(self, num_buckets):
        from hyperspace_tpu.ops.bucketize import partition_batch

        for np_dt, logical in [
            (np.int64, "int64"),
            (np.int32, "int32"),
            (np.int16, "int16"),
        ]:
            vals = np.array([0, 1, -1, 5, 1234, 32000, -32000], dtype=np_dt)
            batch = ColumnBatch({"k": Column(vals, logical)})
            parts = dict(partition_batch(batch, ["k"], num_buckets))
            write_side = np.empty(len(vals), dtype=np.int64)
            for b, rows in parts.items():
                write_side[rows] = b
            for i, v in enumerate(vals.tolist()):
                read_side = pruning.bucket_of_literals([v], [logical], num_buckets)
                assert read_side == write_side[i], (logical, v, num_buckets)

    @pytest.mark.parametrize("num_buckets", [2, 8, 33])
    def test_string_keys(self, num_buckets):
        from hyperspace_tpu.ops.bucketize import partition_batch

        values = ["", "a", "bb", "Brand#3", "日本語", "a" * 100]
        batch = ColumnBatch({"s": Column.from_values(values)})
        parts = dict(partition_batch(batch, ["s"], num_buckets))
        write_side = np.empty(len(values), dtype=np.int64)
        for b, rows in parts.items():
            write_side[rows] = b
        for i, v in enumerate(values):
            read_side = pruning.bucket_of_literals([v], ["string"], num_buckets)
            assert read_side == write_side[i], (v, num_buckets)

    @pytest.mark.parametrize("num_buckets", [2, 8, 33])
    def test_null_int_keys(self, num_buckets):
        """Null numeric keys store the fill value 0 (columnar.io
        fill_null(0)) — IS NULL pruning must land on hash(0)'s bucket."""
        from hyperspace_tpu.ops.bucketize import partition_batch

        import pyarrow as pa

        tbl = pa.table({"k": pa.array([None, 3, None, 9], type=pa.int64())})
        batch = cio.table_to_batch(tbl)
        parts = dict(partition_batch(batch, ["k"], num_buckets))
        write_side = np.empty(4, dtype=np.int64)
        for b, rows in parts.items():
            write_side[rows] = b
        null_bucket = pruning.bucket_of_literals(
            [pruning._NULL], ["int64"], num_buckets
        )
        assert null_bucket == write_side[0] == write_side[2]

    def test_multi_column_keys(self):
        from hyperspace_tpu.ops.bucketize import partition_batch

        batch = ColumnBatch(
            {
                "a": Column(np.array([1, 2, 3], dtype=np.int64), "int64"),
                "s": Column.from_values(["x", "y", "x"]),
            }
        )
        parts = dict(partition_batch(batch, ["a", "s"], 16))
        write_side = np.empty(3, dtype=np.int64)
        for b, rows in parts.items():
            write_side[rows] = b
        for i, (a, s) in enumerate([(1, "x"), (2, "y"), (3, "x")]):
            assert (
                pruning.bucket_of_literals([a, s], ["int64", "string"], 16)
                == write_side[i]
            )

    def test_unmatchable_literals(self):
        # overflow / fractional / type-mismatch literals match no stored row
        assert pruning.bucket_of_literals([2**40], ["int32"], 8) is None
        assert pruning.bucket_of_literals([3.5], ["int64"], 8) is None
        assert pruning.bucket_of_literals(["s"], ["int64"], 8) is None
        assert pruning.bucket_of_literals([7], ["string"], 8) is None
        # exact-integer floats match their int storage
        assert pruning.bucket_of_literals([3.0], ["int64"], 8) == \
            pruning.bucket_of_literals([3], ["int64"], 8)


# ---------------------------------------------------------------------------
# end-to-end fixtures
# ---------------------------------------------------------------------------

@pytest.fixture()
def indexed_env(tmp_session, tmp_path):
    """Covering index over a table with an int key, a string key, a float
    value, and nulls in a secondary int column."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    rng = np.random.default_rng(5)
    n = 20_000
    tbl = pa.table(
        {
            "k": pa.array(rng.integers(0, 2_000, n), pa.int64()),
            "s": pa.array(rng.choice(["r", "g", "b"], n).tolist()),
            "v": pa.array(rng.uniform(0, 10, n)),
            "m": pa.array(
                [None if i % 97 == 0 else int(i % 50) for i in range(n)],
                pa.int64(),
            ),
        }
    )
    os.makedirs(str(tmp_path / "T"), exist_ok=True)
    pq.write_table(tbl, str(tmp_path / "T" / "part-0.parquet"))
    tmp_session.set_conf(C.INDEX_NUM_BUCKETS, 8)
    hs = Hyperspace(tmp_session)
    df = tmp_session.read.parquet(str(tmp_path / "T"))
    hs.create_index(df, CoveringIndexConfig("pk_k", ["k"], ["v", "s", "m"]))
    hs.create_index(df, CoveringIndexConfig("pk_s", ["s"], ["k", "v"]))
    hs.create_index(df, CoveringIndexConfig("pk_m", ["m"], ["k", "v"]))
    tmp_session.enable_hyperspace()
    return tmp_session, str(tmp_path / "T")


def _identical(q, monkeypatch):
    """Run q pruned and unpruned; assert value-identical (floats via hex)."""
    got = q().to_pydict()
    monkeypatch.setenv("HYPERSPACE_PRUNE", "0")
    expected = q().to_pydict()
    monkeypatch.delenv("HYPERSPACE_PRUNE")

    def bits(d):
        return {
            k: [x.hex() if isinstance(x, float) else x for x in v]
            for k, v in d.items()
        }

    assert bits(got) == bits(expected)
    return got


class TestEndToEnd:
    def test_point_lookup_prunes_and_matches(self, indexed_env, monkeypatch):
        session, root = indexed_env
        from hyperspace_tpu.plan.nodes import FileScan

        q = lambda: session.read.parquet(root).filter(col("k") == 777).select("k", "v")
        plan = q().optimized_plan()
        scan = [n for n in plan.preorder() if isinstance(n, FileScan)][0]
        assert scan.index_info is not None and scan.index_info.index_name == "pk_k"
        assert scan.prune_spec is not None and scan.prune_spec.active
        assert scan.prune_spec.bucket_keep is not None
        assert len(scan.files) < 8  # bucket pruning shrank the file list
        got = _identical(q, monkeypatch)
        assert len(got["k"]) > 0 and set(got["k"]) == {777}

    def test_string_key_point_lookup(self, indexed_env, monkeypatch):
        session, root = indexed_env
        q = lambda: session.read.parquet(root).filter(col("s") == "g").select("s", "k")
        got = _identical(q, monkeypatch)
        assert set(got["s"]) == {"g"}

    def test_in_lookup(self, indexed_env, monkeypatch):
        session, root = indexed_env
        q = (
            lambda: session.read.parquet(root)
            .filter(col("k").isin([3, 777, 1999, 10**7]))
            .select("k", "v")
        )
        got = _identical(q, monkeypatch)
        assert set(got["k"]) <= {3, 777, 1999}

    def test_is_null_lookup(self, indexed_env, monkeypatch):
        session, root = indexed_env
        q = (
            lambda: session.read.parquet(root)
            .filter(col("m").is_null())
            .select("m", "k")
        )
        got = _identical(q, monkeypatch)
        assert got["m"] and all(v is None for v in got["m"])

    def test_range_and_agg(self, indexed_env, monkeypatch):
        session, root = indexed_env
        q = (
            lambda: session.read.parquet(root)
            .filter((col("k") >= 100) & (col("k") < 160))
            .agg(Sum(col("v")).alias("sv"), Count(col("k")).alias("n"))
        )
        _identical(q, monkeypatch)

    def test_escape_hatch_disables(self, indexed_env, monkeypatch):
        session, root = indexed_env
        from hyperspace_tpu.plan.nodes import FileScan

        monkeypatch.setenv("HYPERSPACE_PRUNE", "0")
        plan = (
            session.read.parquet(root)
            .filter(col("k") == 777)
            .select("k", "v")
            .optimized_plan()
        )
        scan = [n for n in plan.preorder() if isinstance(n, FileScan)][0]
        assert scan.prune_spec is not None and not scan.prune_spec.active
        assert len(scan.files) == 8

    def test_usage_event_emitted(self, indexed_env):
        session, root = indexed_env
        before = REGISTRY.counter("rules.usage.IndexPruning").value
        session.read.parquet(root).filter(col("k") == 5).select("k", "v").collect()
        assert REGISTRY.counter("rules.usage.IndexPruning").value > before

    def test_counters_fire(self, indexed_env):
        session, root = indexed_env
        t0 = REGISTRY.counter("pruning.files_total").value
        k0 = REGISTRY.counter("pruning.files_kept").value
        session.read.parquet(root).filter(col("k") == 5).select("k", "v").collect()
        dt = REGISTRY.counter("pruning.files_total").value - t0
        dk = REGISTRY.counter("pruning.files_kept").value - k0
        assert dk < dt


class TestVerifyMode:
    def test_verify_passes_on_sound_pruning(self, indexed_env, monkeypatch):
        session, root = indexed_env
        monkeypatch.setenv("HYPERSPACE_PRUNE", "verify")
        before = REGISTRY.counter("pruning.verified").value
        got = (
            session.read.parquet(root)
            .filter(col("k") == 777)
            .select("k", "v")
            .to_pydict()
        )
        assert set(got["k"]) == {777}
        assert REGISTRY.counter("pruning.verified").value > before

    def test_verify_detects_overpruning(self, indexed_env, monkeypatch):
        """Tamper the kept-bucket set: verify must raise, not lose rows."""
        from dataclasses import replace

        from hyperspace_tpu.exceptions import HyperspaceError
        from hyperspace_tpu.plan.executor import _exec_file_scan
        from hyperspace_tpu.plan.nodes import FileScan

        session, root = indexed_env
        monkeypatch.setenv("HYPERSPACE_PRUNE", "verify")
        plan = (
            session.read.parquet(root)
            .filter(col("k") == 777)
            .select("k", "v")
            .optimized_plan()
        )
        scan = [n for n in plan.preorder() if isinstance(n, FileScan)][0]
        assert scan.prune_spec.verify_files
        # drop every file the (sound) pruning kept: rows for k=777 vanish
        bad = scan.copy(
            files=[],
            prune_spec=replace(scan.prune_spec, bucket_keep=frozenset()),
        )
        with pytest.raises(HyperspaceError, match="verify mismatch"):
            _exec_file_scan(bad)


class TestRowGroupSkipping:
    @pytest.fixture()
    def multirun_env(self, tmp_session, tmp_path):
        """Clustered key over several source files + a small build budget:
        the streaming build writes one sorted run per file group, so range
        predicates can drop whole runs."""
        n, files = 40_000, 8
        per = n // files
        rng = np.random.default_rng(11)
        for i in range(files):
            data = {
                "k": (np.arange(per, dtype=np.int64) + i * per).tolist(),
                "v": rng.uniform(0, 1, per).tolist(),
            }
            cio.write_parquet(
                ColumnBatch.from_pydict(data),
                str(tmp_path / "S" / f"part-{i:02d}.parquet"),
            )
        tmp_session.set_conf(C.INDEX_NUM_BUCKETS, 4)
        tmp_session.set_conf(C.BUILD_MAX_BYTES_IN_MEMORY, 256 * 1024)
        hs = Hyperspace(tmp_session)
        hs.create_index(
            tmp_session.read.parquet(str(tmp_path / "S")),
            CoveringIndexConfig("rg_k", ["k"], ["v"]),
        )
        tmp_session.enable_hyperspace()
        return tmp_session, str(tmp_path / "S")

    def test_range_drops_runs_bitwise(self, multirun_env, monkeypatch):
        session, root = multirun_env
        t0 = REGISTRY.counter("pruning.rowgroups_total").value
        k0 = REGISTRY.counter("pruning.rowgroups_kept").value
        f0 = REGISTRY.counter("pruning.files_total").value
        fk0 = REGISTRY.counter("pruning.files_kept").value
        q = (
            lambda: session.read.parquet(root)
            .filter((col("k") >= 5_000) & (col("k") < 6_000))
            .select("k", "v")
        )
        got = _identical(q, monkeypatch)
        assert len(got["k"]) == 1_000
        assert (
            REGISTRY.counter("pruning.rowgroups_kept").value - k0
            < REGISTRY.counter("pruning.rowgroups_total").value - t0
        )
        assert (
            REGISTRY.counter("pruning.files_kept").value - fk0
            < REGISTRY.counter("pruning.files_total").value - f0
        )

    def test_stats_cache_hits_on_repeat(self, multirun_env):
        session, root = multirun_env
        q = lambda: (
            session.read.parquet(root)
            .filter((col("k") >= 5_000) & (col("k") < 6_000))
            .select("k", "v")
            .collect()
        )
        q()
        h0 = REGISTRY.counter("cache.rowgroup_stats.hits").value
        q()
        assert REGISTRY.counter("cache.rowgroup_stats.hits").value > h0

    def test_warm_repeat_pruned_agg_zero_compile_spans(
        self, multirun_env, monkeypatch
    ):
        """Pruning must not destabilize the kernel cache: a warm repeat of a
        pruned device aggregate emits zero compile:* spans."""
        from hyperspace_tpu.telemetry import trace

        session, root = multirun_env
        monkeypatch.setenv("HYPERSPACE_PIPELINE", "1")
        session.set_conf(C.EXEC_TPU_ENABLED, True)
        q = lambda: (
            session.read.parquet(root)
            .filter((col("k") >= 5_000) & (col("k") < 9_000))
            .agg(Count(col("k")).alias("n"), Sum(col("k")).alias("sk"))
            .to_pydict()
        )
        cold = q()  # compiles
        sink = _ListSink()
        trace.enable(sink)
        try:
            warm = q()
        finally:
            trace.disable()
        assert warm == cold
        names = [s["name"] for s in sink.spans]
        assert not [n for n in names if n.startswith("compile:")]
        assert [n for n in names if n == "prune:rowgroup"]


class TestReadCacheKeys:
    def test_filtered_source_read_caches(self, tmp_path):
        """Satellite: filtered reads key the source-column cache on the
        filter repr (and row-group selection) instead of bypassing it."""
        import pyarrow.compute as pc

        path = str(tmp_path / "c" / "f.parquet")
        cio.write_parquet(
            ColumnBatch.from_pydict(
                {"a": list(range(1000)), "b": [float(i) for i in range(1000)]}
            ),
            path,
        )
        flt = pc.field("a") < 10
        with cio.source_cache_scope():
            m0 = REGISTRY.counter("cache.source_col.misses").value
            h0 = REGISTRY.counter("cache.source_col.hits").value
            one = cio.read_parquet([path], ["a", "b"], arrow_filter=flt)
            assert REGISTRY.counter("cache.source_col.misses").value > m0
            two = cio.read_parquet([path], ["a", "b"], arrow_filter=flt)
            assert REGISTRY.counter("cache.source_col.hits").value >= h0 + 2
            # different filter -> different key -> no stale hit
            other = cio.read_parquet(
                [path], ["a", "b"], arrow_filter=pc.field("a") < 20
            )
        assert one.num_rows == two.num_rows == 10
        assert other.num_rows == 20
        assert one.column("a").data.tolist() == two.column("a").data.tolist()

    def test_rowgroup_selected_read_caches_and_evicts(self, tmp_path, monkeypatch):
        """Row-group selections are part of the chunk-cache key, and
        evictions keep exact byte accounting."""
        import pyarrow as pa
        import pyarrow.parquet as pq

        paths = []
        for i in range(3):
            p = str(tmp_path / f"rg{i}.parquet")
            pq.write_table(
                pa.table({"a": pa.array(np.arange(4000) + i * 4000, pa.int64())}),
                p,
                row_group_size=1000,
            )
            paths.append(p)
        cache = cio._INDEX_CHUNK_CACHE
        old_max = cache.max_bytes
        cache.clear()
        ev0 = REGISTRY.counter("cache.index_chunk.evictions").value
        evb0 = REGISTRY.counter("cache.index_chunk.evicted_bytes").value
        try:
            sel_a = {paths[0]: (0, 2)}
            a1 = cio.read_parquet([paths[0]], ["a"], cache=True, row_groups=sel_a)
            m0 = REGISTRY.counter("cache.index_chunk.misses").value
            h0 = REGISTRY.counter("cache.index_chunk.hits").value
            a2 = cio.read_parquet([paths[0]], ["a"], cache=True, row_groups=sel_a)
            assert REGISTRY.counter("cache.index_chunk.hits").value == h0 + 1
            assert a1.column("a").data.tolist() == a2.column("a").data.tolist()
            assert a1.num_rows == 2000
            # a different selection is a different cached value
            b = cio.read_parquet(
                [paths[0]], ["a"], cache=True, row_groups={paths[0]: (1,)}
            )
            assert b.num_rows == 1000
            assert REGISTRY.counter("cache.index_chunk.misses").value > m0
            # shrink the cache so the next insert evicts: byte accounting
            # must balance (occupancy gauge == sum of resident entries)
            cache.max_bytes = cio._batch_nbytes(a1) + cio._batch_nbytes(b) - 1
            cio.read_parquet(
                [paths[1]], ["a"], cache=True, row_groups={paths[1]: (0,)}
            )
            evd = REGISTRY.counter("cache.index_chunk.evictions").value - ev0
            evb = REGISTRY.counter("cache.index_chunk.evicted_bytes").value - evb0
            assert evd > 0 and evb > 0
            with cache._lock:
                assert cache._bytes == sum(b_ for (_v, b_) in cache._d.values())
                assert cache._bytes <= cache.max_bytes
        finally:
            cache.max_bytes = old_max
            cache.clear()


class TestRanker:
    def test_selectivity_prefers_bucket_match(self, tmp_session, tmp_path):
        """Two covering candidates: a bigger index whose bucket key the
        predicate pins must outrank a smaller one it cannot prune."""
        rng = np.random.default_rng(2)
        n = 30_000
        cio.write_parquet(
            ColumnBatch.from_pydict(
                {
                    "a": rng.integers(0, 1000, n).tolist(),
                    "b": rng.integers(0, 1000, n).tolist(),
                    "v": rng.uniform(0, 1, n).tolist(),
                }
            ),
            str(tmp_path / "R" / "r.parquet"),
        )
        tmp_session.set_conf(C.INDEX_NUM_BUCKETS, 8)
        hs = Hyperspace(tmp_session)
        df = tmp_session.read.parquet(str(tmp_path / "R"))
        # idx_b is smaller (fewer covered columns) but cannot prune a filter
        # on `a`; idx_a is bigger but bucket-prunes to 1/8
        hs.create_index(df, CoveringIndexConfig("idx_a", ["a"], ["b", "v"]))
        hs.create_index(df, CoveringIndexConfig("idx_b", ["b"], ["a", "v"]))
        tmp_session.enable_hyperspace()
        from hyperspace_tpu.plan.nodes import FileScan

        plan = (
            tmp_session.read.parquet(str(tmp_path / "R"))
            .filter((col("a") == 7) & (col("b") > 100))
            .select("a", "b", "v")
            .optimized_plan()
        )
        scan = [n_ for n_ in plan.preorder() if isinstance(n_, FileScan)][0]
        assert scan.index_info is not None
        assert scan.index_info.index_name == "idx_a"
        assert len(scan.files) < 8


class _ListSink:
    def __init__(self):
        self.spans = []

    def write_span(self, span):
        self.spans.append({"name": span.name})

    def close(self):
        pass
