"""Compat-surface and layout-analyzer tests (ref: python binding tests +
MinMaxAnalysisUtil)."""

import pytest

from hyperspace_tpu.columnar import io as cio
from hyperspace_tpu.columnar.table import ColumnBatch
from hyperspace_tpu.compat import (
    Hyperspace,
    IndexConfig,
    ZOrderIndexConfig,
    disableHyperspace,
    enableHyperspace,
    isHyperspaceEnabled,
)
from hyperspace_tpu.plan import col


@pytest.fixture()
def env(tmp_session, tmp_path):
    cio.write_parquet(
        ColumnBatch.from_pydict({"k": list(range(50)), "v": [float(i) for i in range(50)]}),
        str(tmp_path / "d" / "p.parquet"),
    )
    return tmp_session, tmp_path


class TestCompatSurface:
    def test_camel_case_lifecycle(self, env):
        session, tmp = env
        hs = Hyperspace(session)
        df = session.read.parquet(str(tmp / "d"))
        hs.createIndex(df, IndexConfig("i1", ["k"], ["v"]))
        assert hs.indexes().to_pydict()["name"] == ["i1"]
        hs.refreshIndex("i1", "full")  # no-op refresh swallowed
        hs.deleteIndex("i1")
        hs.restoreIndex("i1")
        hs.optimizeIndex("i1", "quick")
        hs.deleteIndex("i1")
        hs.vacuumIndex("i1")

    def test_enable_helpers(self, env):
        session, _ = env
        assert not isHyperspaceEnabled(session)
        enableHyperspace(session)
        assert isHyperspaceEnabled(session)
        disableHyperspace(session)
        assert not isHyperspaceEnabled(session)

    def test_rewrite_through_compat(self, env):
        session, tmp = env
        hs = Hyperspace(session)
        df = session.read.parquet(str(tmp / "d"))
        hs.createIndex(df, IndexConfig("i1", ["k"], ["v"]))
        enableHyperspace(session)
        q = session.read.parquet(str(tmp / "d")).filter(col("k") == 5).select("k", "v")
        assert "Hyperspace(" in q.explain_plan()
        assert hs.whyNot(q) is not None

    def test_zorder_alias(self, env):
        session, tmp = env
        hs = Hyperspace(session)
        df = session.read.parquet(str(tmp / "d"))
        hs.createIndex(df, ZOrderIndexConfig("z1", ["k"], ["v"]))
        assert hs.get_index("z1").kind == "ZCI"


class TestMinMaxAnalyzer:
    def test_report(self, tmp_session, tmp_path):
        from hyperspace_tpu.analysis.minmax_analysis import analyze

        # clustered column k (disjoint per file), scattered column s
        for i in range(4):
            cio.write_parquet(
                ColumnBatch.from_pydict(
                    {
                        "k": list(range(i * 10, (i + 1) * 10)),
                        "s": list(range(0, 100, 10)),
                    }
                ),
                str(tmp_path / "t" / f"f{i}.parquet"),
            )
        df = tmp_session.read.parquet(str(tmp_path / "t"))
        report = analyze(df, ["k", "s"])
        assert "MinMax layout analysis" in report
        lines = {l.split()[0]: l for l in report.splitlines() if l.startswith(("k ", "s "))}
        k_avg = float(lines["k"].split()[2])
        s_avg = float(lines["s"].split()[2])
        assert k_avg < 1.5  # clustered: point query touches ~1 file
        assert s_avg > 3.0  # scattered: touches all 4


class TestMinMaxAnalyzerVerbose:
    def test_chart_and_stats(self, tmp_session, tmp_path):
        from hyperspace_tpu.analysis.minmax_analysis import analyze, column_stats
        from hyperspace_tpu.models.covering import _single_file_scan

        for i in range(4):
            cio.write_parquet(
                ColumnBatch.from_pydict(
                    {"k": list(range(i * 10, (i + 1) * 10))}
                ),
                str(tmp_path / "t" / f"f{i}.parquet"),
            )
        df = tmp_session.read.parquet(str(tmp_path / "t"))
        report = analyze(df, ["k"], verbose=True)
        assert "skip 1%" in report  # range-width skip ratio columns
        assert "overlap across" in report  # the domain chart rendered
        assert "Recommendations:" in report
        stats = column_stats(_single_file_scan(df), "k")
        assert stats.clustered
        assert stats.skip_ratio_point > 0.6  # point query skips ~3 of 4 files
        assert stats.disjoint_sorted  # per-file ranges never overlap
        assert stats.skip_ratio_range10 > 0.5  # narrow ranges skip most files
        assert stats.bucket_overlaps is not None
        assert len(stats.bucket_overlaps) == 24

    def test_scattered_column_recommended(self, tmp_session, tmp_path):
        from hyperspace_tpu.analysis.minmax_analysis import analyze, column_stats
        from hyperspace_tpu.models.covering import _single_file_scan

        for i in range(4):
            cio.write_parquet(
                ColumnBatch.from_pydict({"s": list(range(0, 100, 10))}),
                str(tmp_path / "t" / f"f{i}.parquet"),
            )
        df = tmp_session.read.parquet(str(tmp_path / "t"))
        stats = column_stats(_single_file_scan(df), "s")
        assert not stats.disjoint_sorted
        assert stats.skip_ratio_range1 < 0.2  # every file overlaps every range
        assert stats.widest_files  # the offenders table has entries
        report = analyze(df, ["s"], verbose=True)
        assert "re-clustering" in report  # recommendation fired
        assert "widest file ranges" in report


class TestDisplayModes:
    """DisplayMode/BufferStream machinery (ref: DisplayMode.scala:24-89,
    BufferStream.scala:23-83): per-mode highlight tags, conf overrides,
    HTML escaping + wrapping, unknown-mode rejection."""

    @pytest.fixture()
    def indexed_query(self, env):
        session, tmp = env
        hs = Hyperspace(session)
        df = session.read.parquet(str(tmp / "d"))
        hs.createIndex(df, IndexConfig("i1", ["k"], ["v"]))
        enableHyperspace(session)
        q = session.read.parquet(str(tmp / "d")).filter(col("k") == 5).select("k", "v")
        return session, hs, q

    def test_plaintext_default_tags(self, indexed_query):
        session, hs, q = indexed_query
        out = hs.explain(q)  # the facade path must honor the mode too
        assert "<----" in out and "---->" in out
        assert "<pre>" not in out
        # redirect mode passes the same string and returns None
        sunk = []
        assert hs.explain(q, redirect=sunk.append) is None
        assert sunk == [out]

    def test_html_mode_wraps_escapes_and_highlights(self, indexed_query):
        session, _hs, q = indexed_query
        from hyperspace_tpu import constants as C
        from hyperspace_tpu.analysis.explain import explain_string

        session.set_conf(C.DISPLAY_MODE, "html")
        try:
            out = explain_string(session, q)
        finally:
            session.set_conf(C.DISPLAY_MODE, "plaintext")
        assert out.startswith("<pre>") and out.endswith("</pre>")
        assert "<br>" in out
        assert 'style="background:LightGreen"' in out
        # plan text contains '<' comparisons on other queries; the literal
        # index marker must survive escaping as text, not markup
        assert "Hyperspace(" in out

    def test_console_mode_ansi(self, indexed_query):
        session, _hs, q = indexed_query
        from hyperspace_tpu import constants as C
        from hyperspace_tpu.analysis.explain import explain_string

        session.set_conf(C.DISPLAY_MODE, "console")
        try:
            out = explain_string(session, q)
        finally:
            session.set_conf(C.DISPLAY_MODE, "plaintext")
        assert "\033[42m" in out and "\033[0m" in out

    def test_conf_highlight_override_needs_both(self, indexed_query):
        session, _hs, q = indexed_query
        from hyperspace_tpu import constants as C
        from hyperspace_tpu.analysis.explain import explain_string

        session.set_conf(C.HIGHLIGHT_BEGIN_TAG, ">>>")
        try:
            # only begin set: fall back to mode default (ref:
            # DisplayMode.getHighlightTagOrElse nonEmpty-pair check)
            assert "<----" in explain_string(session, q)
            session.set_conf(C.HIGHLIGHT_END_TAG, "<<<")
            out = explain_string(session, q)
            assert ">>>" in out and "<<<" in out and "<----" not in out
        finally:
            session.set_conf(C.HIGHLIGHT_BEGIN_TAG, "")
            session.set_conf(C.HIGHLIGHT_END_TAG, "")

    def test_verbose_explain_honors_disable_and_fails_open(self, indexed_query):
        session, hs, q = indexed_query
        from hyperspace_tpu import constants as C

        # disabled sessions must render identical plans in BOTH modes —
        # verbose analysis must not sneak the rewrite back in
        session.set_conf(C.APPLY_ENABLED, False)
        try:
            out = hs.explain(q, verbose=True)
        finally:
            session.set_conf(C.APPLY_ENABLED, True)
        assert "Hyperspace(" not in out
        assert "unavailable: hyperspace is disabled" in out

    def test_unknown_mode_raises(self, indexed_query):
        session, _hs, q = indexed_query
        from hyperspace_tpu import constants as C
        from hyperspace_tpu.analysis.explain import explain_string
        from hyperspace_tpu.exceptions import HyperspaceError

        session.set_conf(C.DISPLAY_MODE, "latex")
        try:
            with pytest.raises(HyperspaceError, match="display mode"):
                explain_string(session, q)
        finally:
            session.set_conf(C.DISPLAY_MODE, "plaintext")


class TestWhyNotSections:
    """Deepened whyNot rendering (ref: CandidateIndexAnalyzer
    generateWhyNotString:147-240)."""

    @pytest.fixture()
    def two_index_env(self, env):
        session, tmp = env
        hs = Hyperspace(session)
        df = session.read.parquet(str(tmp / "d"))
        hs.createIndex(df, IndexConfig("i1", ["k"], ["v"]))
        hs.createIndex(df, IndexConfig("i2", ["v"], ["k"]))
        enableHyperspace(session)
        q = session.read.parquet(str(tmp / "d")).filter(col("k") == 5).select("k", "v")
        return session, hs, q

    def test_summary_sections(self, two_index_env):
        session, hs, q = two_index_env
        out = hs.why_not(q)  # through the facade
        assert "Plan with Hyperspace & Summary:" in out
        assert "Applied indexes:" in out
        assert "- i1 (Type: CI, LogVersion: 1)" in out
        assert "Applicable indexes, but not applied due to priority:" in out

    def test_non_extended_hides_schema_mismatch(self, two_index_env, tmp_path):
        session, hs, q = two_index_env
        from hyperspace_tpu.analysis.whynot import why_not_string

        # an index over a DIFFERENT table: its only reason against this
        # query is COL_SCHEMA_MISMATCH, the exact noise the filter hides
        cio.write_parquet(
            ColumnBatch.from_pydict({"x": [1, 2, 3], "y": [4.0, 5.0, 6.0]}),
            str(tmp_path / "other" / "p.parquet"),
        )
        other = session.read.parquet(str(tmp_path / "other"))
        hs.createIndex(other, IndexConfig("ix", ["x"], ["y"]))

        brief = why_not_string(session, q, extended=False)
        full = why_not_string(session, q, extended=True)
        # i2 (indexed on v, filter is on k) explains itself in extended
        # mode, but the brief table drops COL_SCHEMA_MISMATCH noise rows
        # and says how many it dropped (ref: :230-235)
        table_lines = [
            l
            for l in brief.split("Index reasons:")[1].splitlines()
            if "rows hidden" not in l  # the footer names the code itself
        ]
        assert not any("COL_SCHEMA_MISMATCH" in l for l in table_lines)
        assert "COL_SCHEMA_MISMATCH rows hidden" in brief
        assert "COL_SCHEMA_MISMATCH" in full.split("Index reasons:")[1]
        assert "message" in full.split("Index reasons:")[1]
        # the filtered index must NOT be misreported as lacking a candidate
        # leaf — its reasons existed, they were just hidden
        assert "NO_CANDIDATE_LEAF" not in brief

    def test_hidden_footer_only_when_rows_dropped(self, env):
        session, tmp = env
        hs = Hyperspace(session)
        df = session.read.parquet(str(tmp / "d"))
        hs.createIndex(df, IndexConfig("i1", ["k"], ["v"]))
        enableHyperspace(session)
        from hyperspace_tpu.analysis.whynot import why_not_string

        # i1 applies cleanly: nothing is filtered, so no hidden-rows footer
        q = session.read.parquet(str(tmp / "d")).filter(col("k") == 5).select("k", "v")
        out = why_not_string(session, q, extended=False)
        assert "(applied)" in out
        assert "hidden" not in out

    def test_applicable_info_empty_case(self, tmp_session, tmp_path):
        from hyperspace_tpu.analysis.whynot import applicable_index_info_string

        cio.write_parquet(
            ColumnBatch.from_pydict({"a": [1, 2, 3]}), str(tmp_path / "e" / "p.parquet")
        )
        q = tmp_session.read.parquet(str(tmp_path / "e")).filter(col("a") == 1)
        out = applicable_index_info_string(tmp_session, q)
        assert out == "No applicable indexes. Try hyperspace.whyNot()"

    def test_named_index_scopes_report(self, two_index_env):
        session, _hs, q = two_index_env
        from hyperspace_tpu.analysis.whynot import why_not_string

        out = why_not_string(session, q, index_name="i2", extended=True)
        assert "i2" in out
        # i1's rows are scoped out entirely (ref: whyNotIndexString filters
        # the entry list before analysis)
        assert "- i1" not in out


class TestMinMaxAnalyzerFormats:
    """HTML writer + before/after comparison (ref: MinMaxAnalysisUtil
    TextResultWriter/HtmlResultWriter split + appendComparisonResult)."""

    def _write_layouts(self, tmp_path):
        # before: every file spans the whole domain; after: disjoint ranges
        for i in range(4):
            cio.write_parquet(
                ColumnBatch.from_pydict({"k": list(range(0, 100, 3))}),
                str(tmp_path / "before" / f"f{i}.parquet"),
            )
            cio.write_parquet(
                ColumnBatch.from_pydict({"k": list(range(i * 25, (i + 1) * 25))}),
                str(tmp_path / "after" / f"f{i}.parquet"),
            )

    def test_html_report(self, tmp_session, tmp_path):
        from hyperspace_tpu.analysis.minmax_analysis import analyze_html

        self._write_layouts(tmp_path)
        df = tmp_session.read.parquet(str(tmp_path / "before"))
        out = analyze_html(df, ["k"])
        assert out.startswith("<html>") and out.endswith("</html>")
        assert "MinMax layout analysis" in out
        assert "background:LightGreen" in out  # the overlap bars rendered
        assert "Recommendations" in out

    def test_comparison_report(self, tmp_session, tmp_path):
        from hyperspace_tpu.analysis.minmax_analysis import analyze_comparison

        self._write_layouts(tmp_path)
        before = tmp_session.read.parquet(str(tmp_path / "before"))
        after = tmp_session.read.parquet(str(tmp_path / "after"))
        out = analyze_comparison(before, after, ["k"])
        assert "------->>>" in out  # side-by-side merge arrow
        assert "k — before" in out and "k — after" in out
        assert "fewer files after re-layout" in out

    def test_comparison_regression_warns(self, tmp_session, tmp_path):
        from hyperspace_tpu.analysis.minmax_analysis import analyze_comparison

        self._write_layouts(tmp_path)
        # swap sides: disjoint -> overlapping must warn
        before = tmp_session.read.parquet(str(tmp_path / "after"))
        after = tmp_session.read.parquet(str(tmp_path / "before"))
        out = analyze_comparison(before, after, ["k"])
        assert "WARNING: layout regressed" in out


class TestApplicableInfoMemoSafety:
    def test_reused_analysis_result_is_not_mutated(self, env):
        """Two renders off one AnalysisResult must not duplicate the
        '(applied)' rows into the memoized applicable-rows cache."""
        session, tmp = env
        hs = Hyperspace(session)
        df = session.read.parquet(str(tmp / "d"))
        hs.createIndex(df, IndexConfig("i1", ["k"], ["v"]))
        enableHyperspace(session)
        q = session.read.parquet(str(tmp / "d")).filter(col("k") == 5).select("k", "v")
        from hyperspace_tpu.analysis.whynot import (
            applicable_index_info_string,
            collect_analysis,
        )

        res = collect_analysis(session, q)
        first = applicable_index_info_string(session, q, res)
        second = applicable_index_info_string(session, q, res)
        assert first == second
        assert not any("(applied)" in r for r in map(str, res.applicable_rows()))
