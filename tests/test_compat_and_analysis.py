"""Compat-surface and layout-analyzer tests (ref: python binding tests +
MinMaxAnalysisUtil)."""

import pytest

from hyperspace_tpu.columnar import io as cio
from hyperspace_tpu.columnar.table import ColumnBatch
from hyperspace_tpu.compat import (
    Hyperspace,
    IndexConfig,
    ZOrderIndexConfig,
    disableHyperspace,
    enableHyperspace,
    isHyperspaceEnabled,
)
from hyperspace_tpu.plan import col


@pytest.fixture()
def env(tmp_session, tmp_path):
    cio.write_parquet(
        ColumnBatch.from_pydict({"k": list(range(50)), "v": [float(i) for i in range(50)]}),
        str(tmp_path / "d" / "p.parquet"),
    )
    return tmp_session, tmp_path


class TestCompatSurface:
    def test_camel_case_lifecycle(self, env):
        session, tmp = env
        hs = Hyperspace(session)
        df = session.read.parquet(str(tmp / "d"))
        hs.createIndex(df, IndexConfig("i1", ["k"], ["v"]))
        assert hs.indexes().to_pydict()["name"] == ["i1"]
        hs.refreshIndex("i1", "full")  # no-op refresh swallowed
        hs.deleteIndex("i1")
        hs.restoreIndex("i1")
        hs.optimizeIndex("i1", "quick")
        hs.deleteIndex("i1")
        hs.vacuumIndex("i1")

    def test_enable_helpers(self, env):
        session, _ = env
        assert not isHyperspaceEnabled(session)
        enableHyperspace(session)
        assert isHyperspaceEnabled(session)
        disableHyperspace(session)
        assert not isHyperspaceEnabled(session)

    def test_rewrite_through_compat(self, env):
        session, tmp = env
        hs = Hyperspace(session)
        df = session.read.parquet(str(tmp / "d"))
        hs.createIndex(df, IndexConfig("i1", ["k"], ["v"]))
        enableHyperspace(session)
        q = session.read.parquet(str(tmp / "d")).filter(col("k") == 5).select("k", "v")
        assert "Hyperspace(" in q.explain_plan()
        assert hs.whyNot(q) is not None

    def test_zorder_alias(self, env):
        session, tmp = env
        hs = Hyperspace(session)
        df = session.read.parquet(str(tmp / "d"))
        hs.createIndex(df, ZOrderIndexConfig("z1", ["k"], ["v"]))
        assert hs.get_index("z1").kind == "ZCI"


class TestMinMaxAnalyzer:
    def test_report(self, tmp_session, tmp_path):
        from hyperspace_tpu.analysis.minmax_analysis import analyze

        # clustered column k (disjoint per file), scattered column s
        for i in range(4):
            cio.write_parquet(
                ColumnBatch.from_pydict(
                    {
                        "k": list(range(i * 10, (i + 1) * 10)),
                        "s": list(range(0, 100, 10)),
                    }
                ),
                str(tmp_path / "t" / f"f{i}.parquet"),
            )
        df = tmp_session.read.parquet(str(tmp_path / "t"))
        report = analyze(df, ["k", "s"])
        assert "MinMax layout analysis" in report
        lines = {l.split()[0]: l for l in report.splitlines() if l.startswith(("k ", "s "))}
        k_avg = float(lines["k"].split()[2])
        s_avg = float(lines["s"].split()[2])
        assert k_avg < 1.5  # clustered: point query touches ~1 file
        assert s_avg > 3.0  # scattered: touches all 4


class TestMinMaxAnalyzerVerbose:
    def test_chart_and_stats(self, tmp_session, tmp_path):
        from hyperspace_tpu.analysis.minmax_analysis import analyze, column_stats
        from hyperspace_tpu.models.covering import _single_file_scan

        for i in range(4):
            cio.write_parquet(
                ColumnBatch.from_pydict(
                    {"k": list(range(i * 10, (i + 1) * 10))}
                ),
                str(tmp_path / "t" / f"f{i}.parquet"),
            )
        df = tmp_session.read.parquet(str(tmp_path / "t"))
        report = analyze(df, ["k"], verbose=True)
        assert "skip 1%" in report  # range-width skip ratio columns
        assert "overlap across" in report  # the domain chart rendered
        assert "Recommendations:" in report
        stats = column_stats(_single_file_scan(df), "k")
        assert stats.clustered
        assert stats.skip_ratio_point > 0.6  # point query skips ~3 of 4 files
        assert stats.disjoint_sorted  # per-file ranges never overlap
        assert stats.skip_ratio_range10 > 0.5  # narrow ranges skip most files
        assert stats.bucket_overlaps is not None
        assert len(stats.bucket_overlaps) == 24

    def test_scattered_column_recommended(self, tmp_session, tmp_path):
        from hyperspace_tpu.analysis.minmax_analysis import analyze, column_stats
        from hyperspace_tpu.models.covering import _single_file_scan

        for i in range(4):
            cio.write_parquet(
                ColumnBatch.from_pydict({"s": list(range(0, 100, 10))}),
                str(tmp_path / "t" / f"f{i}.parquet"),
            )
        df = tmp_session.read.parquet(str(tmp_path / "t"))
        stats = column_stats(_single_file_scan(df), "s")
        assert not stats.disjoint_sorted
        assert stats.skip_ratio_range1 < 0.2  # every file overlaps every range
        assert stats.widest_files  # the offenders table has entries
        report = analyze(df, ["s"], verbose=True)
        assert "re-clustering" in report  # recommendation fired
        assert "widest file ranges" in report
