"""Approximate query tier: sample-twin maintenance across the index
lifecycle, eligibility guards, CI honesty, snapshot pinning, vacuum
protection, and crash cells for the ``approx.sample`` fault point.

The tier's contract (docs/performance.md "Approximate tier"): exact mode
is the default and bit-identical; when engaged, estimates carry cluster-
level CLT confidence intervals that cover the exact answer; anything the
rewrite cannot prove unbiased declines to exact execution — never a
quietly-wrong estimate.
"""

import glob
import os

import numpy as np
import pytest

from hyperspace_tpu import CoveringIndexConfig, Hyperspace, HyperspaceSession
from hyperspace_tpu import constants as C
from hyperspace_tpu import ingest
from hyperspace_tpu.columnar import io as cio
from hyperspace_tpu.columnar.table import Column, ColumnBatch
from hyperspace_tpu.meta.data_manager import IndexDataManager
from hyperspace_tpu.models import sample_store
from hyperspace_tpu.plan import Count, Min, Sum, col, lit
from hyperspace_tpu.plan import sampling
from hyperspace_tpu.plan.executor import execute_plan
from hyperspace_tpu.plan.nodes import FileScan, Join
from hyperspace_tpu.telemetry import plan_stats
from hyperspace_tpu.utils import faults

FR = 0.1  # a default-config sampling tier (HYPERSPACE_APPROX_FRACTIONS)


# ---------------------------------------------------------------------------
# fixtures
# ---------------------------------------------------------------------------


def _ev_batch(seed: int, n: int = 3000) -> dict:
    r = np.random.default_rng(seed)
    return {
        # high-NDV key: cluster sizes stay small, so kept-row fractions
        # track the nominal sampling fraction tightly
        "k": r.integers(0, 100_000, n).tolist(),
        "v": r.integers(0, 1000, n).tolist(),
    }


def _mk_ev(tmp_path, buckets: int = 4):
    ws = str(tmp_path)
    src = os.path.join(ws, "events")
    os.makedirs(src, exist_ok=True)
    cio.write_parquet(
        ColumnBatch.from_pydict(_ev_batch(0)), os.path.join(src, "part0.parquet")
    )
    session = HyperspaceSession(warehouse_dir=ws)
    session.set_conf(C.INDEX_NUM_BUCKETS, buckets)
    hs = Hyperspace(session)
    hs.create_index(
        session.read.parquet(src), CoveringIndexConfig("ev", ["k"], ["v"])
    )
    session.enable_hyperspace()
    return session, hs, src


def _mk_join(tmp_path, n=6000, orders=1500, hot_key=None, hot_n=0):
    """Two covering indexes over a synthetic fact/dim pair, joined on an
    int64 key — the flagship correlated-sampling shape."""
    ws = str(tmp_path)
    rng = np.random.default_rng(7)
    fk = rng.integers(0, orders, n).astype(np.int64)
    if hot_n:
        fk[:hot_n] = hot_key
    cio.write_parquet(
        ColumnBatch.from_pydict(
            {"fk": fk.tolist(), "amt": rng.uniform(1, 100, n).tolist()}
        ),
        os.path.join(ws, "li", "part0.parquet"),
    )
    cio.write_parquet(
        ColumnBatch.from_pydict(
            {
                "ok": np.arange(orders, dtype=np.int64).tolist(),
                "dt": rng.integers(0, 1000, orders).tolist(),
            }
        ),
        os.path.join(ws, "od", "part0.parquet"),
    )
    session = HyperspaceSession(warehouse_dir=ws)
    session.set_conf(C.INDEX_NUM_BUCKETS, 4)
    hs = Hyperspace(session)
    hs.create_index(
        session.read.parquet(os.path.join(ws, "li")),
        CoveringIndexConfig("li_idx", ["fk"], ["amt"]),
    )
    hs.create_index(
        session.read.parquet(os.path.join(ws, "od")),
        CoveringIndexConfig("od_idx", ["ok"], ["dt"]),
    )
    session.enable_hyperspace()
    return session, hs, ws


def _mk_join_cov(tmp_path):
    """Fact/dim pair whose covering indexes also COVER a non-key column
    pair (g/h) joinable only through the generic hash-join fallback, plus
    a float32 measure (declared-dtype fidelity)."""
    ws = str(tmp_path)
    rng = np.random.default_rng(11)
    n, orders = 6000, 1500
    cio.write_parquet(
        ColumnBatch({
            "fk": Column(rng.integers(0, orders, n).astype(np.int64), "int64"),
            "g": Column(rng.integers(0, 40, n).astype(np.int64), "int64"),
            "amt": Column(
                rng.uniform(1, 100, n).astype(np.float32), "float32"
            ),
        }),
        os.path.join(ws, "li", "part0.parquet"),
    )
    cio.write_parquet(
        ColumnBatch({
            "ok": Column(np.arange(orders, dtype=np.int64), "int64"),
            "h": Column(
                rng.integers(0, 40, orders).astype(np.int64), "int64"
            ),
            "dt": Column(
                rng.integers(0, 1000, orders).astype(np.int64), "int64"
            ),
        }),
        os.path.join(ws, "od", "part0.parquet"),
    )
    session = HyperspaceSession(warehouse_dir=ws)
    session.set_conf(C.INDEX_NUM_BUCKETS, 4)
    hs = Hyperspace(session)
    hs.create_index(
        session.read.parquet(os.path.join(ws, "li")),
        CoveringIndexConfig("li_idx", ["fk"], ["g", "amt"]),
    )
    hs.create_index(
        session.read.parquet(os.path.join(ws, "od")),
        CoveringIndexConfig("od_idx", ["ok"], ["h", "dt"]),
    )
    session.enable_hyperspace()
    return session, hs, ws


def _qj(session, ws, cut: int = 500):
    li = session.read.parquet(os.path.join(ws, "li"))
    od = session.read.parquet(os.path.join(ws, "od"))
    return (
        li.select("fk", "amt")
        .join(od.select("ok", "dt"), col("fk") == col("ok"))
        .filter(col("dt") < cut)
        .agg(Sum(col("amt")).alias("s"), Count(lit(1)).alias("n"))
    )


def _index_files(hs, name):
    return [f.name for f in hs.get_index(name).index_data_files()]


def _twin_rows(path, fraction):
    return cio.read_parquet([sample_store.sample_path(path, fraction)]).num_rows


def _dropped_key(fraction, upper=100_000):
    """Smallest int64 key value the universe hash DROPS at ``fraction``."""
    for k in range(upper):
        b = ColumnBatch.from_pydict({"fk": np.array([k], dtype=np.int64).tolist()})
        if not sample_store.universe_keep_mask(b, ["fk"], fraction)[0]:
            return k
    raise AssertionError("no dropped key found")


# ---------------------------------------------------------------------------
# sample maintenance across the index lifecycle
# ---------------------------------------------------------------------------


def test_twins_and_meta_written_at_create(tmp_path, monkeypatch):
    monkeypatch.setenv("HYPERSPACE_APPROX", "1")
    session, hs, src = _mk_ev(tmp_path)
    for path in _index_files(hs, "ev"):
        meta = sample_store.load_sample_meta(path)
        assert meta is not None, path
        rows = cio.read_parquet([path]).num_rows
        assert meta["rows"] == rows
        assert 0 < meta["key_ndv"] <= rows
        assert "heavy" in meta
        for f in sample_store.sample_fractions():
            tr = _twin_rows(path, f)
            assert tr == meta["kept"][str(sample_store.fraction_ppm(f))]
            assert tr < rows


def test_approx_off_writes_no_twins_and_scope_is_noop(tmp_path):
    # default: HYPERSPACE_APPROX unset -> off
    session, hs, src = _mk_ev(tmp_path)
    assert not glob.glob(
        os.path.join(str(tmp_path), "indexes", "**", "_sample.*"), recursive=True
    )
    q = lambda: (
        session.read.parquet(src)
        .filter(col("k") < 50_000)
        .agg(Sum(col("v")).alias("s"), Count(lit(1)).alias("n"))
        .to_pydict()
    )
    ref = q()
    with sampling.approx_scope(FR):
        assert q() == ref  # scope ignored while the tier is off


def test_twin_fractions_stable_across_append_append_compact(tmp_path, monkeypatch):
    """The append-stability contract: keep/drop is a pure function of the
    key value, so per-file twins are exactly the universe mask of the file
    at every lifecycle stage, and compaction re-stratifies to the SAME
    kept-key set (it only merges rows; no key changes its decision)."""
    monkeypatch.setenv("HYPERSPACE_APPROX", "1")
    session, hs, src = _mk_ev(tmp_path)
    ingest.append_batch(session, "ev", _ev_batch(1))
    ingest.append_batch(session, "ev", _ev_batch(2))

    def check_stage():
        kept_keys, kept_total, total = set(), 0, 0
        for path in _index_files(hs, "ev"):
            batch = cio.read_parquet([path])
            mask = sample_store.universe_keep_mask(batch, ["k"], FR)
            tw = cio.read_parquet([sample_store.sample_path(path, FR)])
            assert tw.num_rows == int(mask.sum()), path
            kept_keys.update(np.asarray(tw.column("k").data).tolist())
            kept_total += tw.num_rows
            total += batch.num_rows
        assert abs(kept_total / total - FR) < 0.05
        return kept_keys

    before = check_stage()
    hs.compact_index("ev", min_runs=2)
    after = check_stage()
    assert before == after


def test_vacuum_keeps_derived_files_of_referenced_data(tmp_path, monkeypatch):
    monkeypatch.setenv("HYPERSPACE_APPROX", "1")
    session, hs, src = _mk_ev(tmp_path)
    ingest.append_batch(session, "ev", _ev_batch(1))
    files = _index_files(hs, "ev")
    # plant debris inside a referenced version dir: a stray data file and
    # an orphan twin whose base data file is not referenced
    vdir = os.path.dirname(files[0])
    stray = os.path.join(vdir, "stray.parquet")
    orphan = os.path.join(vdir, "_sample.r100000.ghost.parquet")
    for p in (stray, orphan):
        with open(p, "wb") as f:
            f.write(b"x")
    hs.vacuum_outdated_index("ev")
    # referenced data files keep their twins + metas; debris is swept
    for path in files:
        assert os.path.exists(sample_store.sample_path(path, FR)), path
        assert os.path.exists(sample_store.sample_meta_path(path)), path
    assert not os.path.exists(stray)
    assert not os.path.exists(orphan)


def test_pinned_snapshot_serves_pinned_sample_version(tmp_path, monkeypatch):
    """A plan pinned before append+compact+vacuum still has its sample
    twins on disk (they live inside the pinned version dirs), executes
    sampled against them, and its CI covers the OLD exact answer. Once
    the pin drains, vacuum retires the versions — twins included."""
    monkeypatch.setenv("HYPERSPACE_APPROX", "1")
    session, hs, ws = _mk_join(tmp_path)
    old_exact = _qj(session, ws).to_pydict()
    with ingest.pin_scope():
        plan = _qj(session, ws).optimized_plan()  # resolves + pins
        rng = np.random.default_rng(99)
        cio.write_parquet(
            ColumnBatch.from_pydict(
                {
                    "fk": rng.integers(0, 1500, 2000).astype(np.int64).tolist(),
                    "amt": rng.uniform(1, 100, 2000).tolist(),
                }
            ),
            os.path.join(ws, "li", "part1.parquet"),
        )
        hs.append("li_idx", session.read.parquet(os.path.join(ws, "li")))
        hs.compact_index("li_idx", min_runs=2)
        hs.vacuum_outdated_index("li_idx")
        sp = sampling.build_sampled_plan(session, plan, FR)
        assert not isinstance(sp, str), f"declined: {sp}"
        twin_files = [
            f.name
            for n in sp.plan.preorder()
            if isinstance(n, FileScan) and n.sample_spec is not None
            for f in n.files
        ]
        assert twin_files and all(os.path.exists(p) for p in twin_files)
        out, estimates, info = sampling._finalize(
            execute_plan(sp.plan, session), sp
        )
        got = out.to_pydict()
        for name in ("s", "n"):
            diff = abs(float(got[name][0]) - float(old_exact[name][0]))
            assert diff <= info["outputs"][name]["ci95_max"], name
    assert ingest.REGISTRY.active_pins() == 0
    hs.vacuum_outdated_index("li_idx")
    ip = os.path.join(ws, C.INDEXES_DIR, "li_idx")
    assert len(IndexDataManager(ip).get_all_versions()) == 1
    # the superseded li_idx versions retire, twins included (od_idx was
    # never superseded — its v0 twins legitimately stay)
    li_twins = [p for p in twin_files if f"{os.sep}li_idx{os.sep}" in p]
    assert li_twins
    for p in li_twins:
        assert not os.path.exists(p), p


# ---------------------------------------------------------------------------
# eligibility guards
# ---------------------------------------------------------------------------


def test_eligibility_reasons(tmp_path, monkeypatch):
    monkeypatch.setenv("HYPERSPACE_APPROX", "1")
    session, hs, ws = _mk_join(tmp_path)
    bsp = lambda df, f=FR: sampling.build_sampled_plan(
        session, df.optimized_plan(), f
    )
    li = lambda: session.read.parquet(os.path.join(ws, "li"))
    od = lambda: session.read.parquet(os.path.join(ws, "od"))
    join = lambda: li().select("fk", "amt").join(
        od().select("ok", "dt"), col("fk") == col("ok")
    )

    # the flagship shape is eligible
    sp = bsp(_qj(session, ws))
    assert not isinstance(sp, str), f"declined: {sp}"

    # no aggregate at the root
    assert bsp(li().select("fk", "amt")) == "shape"
    # unsupported aggregate function
    assert bsp(join().agg(Min(col("amt")).alias("m"))) == "aggfunc"
    # grouping on the sampling key: surviving groups are complete
    assert (
        bsp(join().group_by("fk").agg(Sum(col("amt")).alias("s")))
        == "group-on-key"
    )
    # filtering on the sampling key: selects a subset of the key universe
    assert (
        bsp(
            join()
            .filter(col("fk") < 500)
            .agg(Sum(col("amt")).alias("s"))
        )
        == "key-filtered"
    )
    # a fraction expected to keep too few distinct keys
    monkeypatch.setenv("HYPERSPACE_APPROX_MIN_KEYS", "100000")
    assert bsp(_qj(session, ws)) == "ndv"
    monkeypatch.delenv("HYPERSPACE_APPROX_MIN_KEYS")

    # a missing twin makes the whole tier ineligible
    victim = sample_store.sample_path(_index_files(hs, "li_idx")[0], FR)
    os.rename(victim, victim + ".bak")
    try:
        assert bsp(_qj(session, ws)) == "missing-samples"
    finally:
        os.rename(victim + ".bak", victim)


def test_join_on_non_key_column_declines(tmp_path, monkeypatch):
    """The generic-hash-join shape: two covering-index scans joined on a
    covered NON-key column pair. The sides' universe samples are
    independent w.r.t. the join column — joined pairs would survive at
    ~p^2 instead of p, so 1/p scaling underestimates by ~p (about 100x
    at f=0.01). Eligibility must decline on the join CONDITION, not just
    on key dtypes (which agree here: int64 on both sides)."""
    monkeypatch.setenv("HYPERSPACE_APPROX", "verify")
    session, hs, ws = _mk_join_cov(tmp_path)
    q = lambda cond: (
        session.read.parquet(os.path.join(ws, "li"))
        .select("fk", "g", "amt")
        .join(
            session.read.parquet(os.path.join(ws, "od"))
            .select("ok", "h", "dt"),
            cond,
        )
        .agg(Sum(col("amt")).alias("s"), Count(lit(1)).alias("n"))
    )
    # an index-scan join plan (keys rewrite both sides), condition then
    # swapped — the shape a sketch-admitted index scan pair reaches when
    # the join itself is not on the bucket keys
    base = q(col("fk") == col("ok")).optimized_plan()
    swap = lambda cond: base.transform_up(
        lambda n: Join(n.left, n.right, cond, n.how)
        if isinstance(n, Join) else n
    )
    bsp = lambda plan: sampling.build_sampled_plan(session, plan, FR)
    assert bsp(swap(col("g") == col("h"))) == "join-not-on-key"
    # a residual conjunct referencing a key column filters the key
    # universe — same bias as the key-filtered guard
    assert (
        bsp(swap((col("fk") == col("ok")) & (col("fk") > lit(10))))
        == "join-not-on-key"
    )
    # an extra equi pair beyond the keys: the key tuples no longer match
    # the equi pairs pairwise, so the conservative guard declines
    assert (
        bsp(swap((col("fk") == col("ok")) & (col("g") == col("h"))))
        == "join-not-on-key"
    )
    # a non-key residual on top of the key equi-join stays eligible
    sp = bsp(swap((col("fk") == col("ok")) & (col("dt") > col("amt"))))
    assert not isinstance(sp, str), f"declined: {sp}"
    # end-to-end in verify mode: the non-key join falls back to the exact
    # answer (a biased ~p^2 estimate could never pass verify coverage)
    exact = q(col("g") == col("h")).to_pydict()
    with sampling.approx_scope(FR):
        assert q(col("g") == col("h")).to_pydict() == exact


def test_sampled_float_outputs_cast_to_declared_dtype(tmp_path, monkeypatch):
    """A float32 Sum keeps Column.data and Column.dtype consistent after
    1/p scaling: the estimator math runs in float64, but the surfaced
    column must honor the exact plan's declared dtype."""
    monkeypatch.setenv("HYPERSPACE_APPROX", "1")
    session, hs, ws = _mk_join_cov(tmp_path)
    df = (
        session.read.parquet(os.path.join(ws, "li"))
        .select("fk", "amt")
        .join(
            session.read.parquet(os.path.join(ws, "od")).select("ok", "dt"),
            col("fk") == col("ok"),
        )
        .agg(Sum(col("amt")).alias("s"), Count(lit(1)).alias("n"))
    )
    plan = df.optimized_plan()
    assert plan.schema.field("s").dtype == "float32"
    sp = sampling.build_sampled_plan(session, plan, FR)
    assert not isinstance(sp, str), f"declined: {sp}"
    out, _, _ = sampling._finalize(execute_plan(sp.plan, session), sp)
    s = out.column("s")
    assert s.dtype == "float32"
    assert np.asarray(s.data).dtype == np.float32
    n = out.column("n")
    assert n.dtype == "int64"
    assert np.asarray(n.data).dtype == np.int64


def test_heavy_recording_floor_tracks_guard_threshold(tmp_path, monkeypatch):
    """The per-file heavy-cluster recording floor derives from
    HYPERSPACE_APPROX_MAX_KEY_SHARE (half the threshold, 1% cap): a
    configured guard below 1% still sees its hot keys recorded, so the
    read-side skew guard can honor it."""
    monkeypatch.setenv("HYPERSPACE_APPROX", "1")
    monkeypatch.setenv("HYPERSPACE_APPROX_MAX_KEY_SHARE", "0.004")
    rng = np.random.default_rng(13)
    n = 10_000
    keys = rng.integers(1000, 1_000_000, n).astype(np.int64)
    keys[:50] = 7  # 0.5% of rows: below the old hardcoded 1% floor
    batch = ColumnBatch.from_pydict(
        {"k": keys.tolist(), "v": rng.integers(0, 10, n).tolist()}
    )
    data_path = os.path.join(str(tmp_path), "part0.parquet")
    assert sample_store.maybe_write_samples(batch, data_path, 4096, ["k"]) > 0
    meta = sample_store.load_sample_meta(data_path)
    h7 = int(
        sample_store._key_hash(
            ColumnBatch.from_pydict({"k": [7]}), ["k"]
        )[0]
    )
    assert meta["heavy"].get(str(h7)) == 50


def test_hot_key_guard_declines_when_dominant_cluster_dropped(
    tmp_path, monkeypatch
):
    monkeypatch.setenv("HYPERSPACE_APPROX", "1")
    hot = _dropped_key(FR)
    session, hs, ws = _mk_join(tmp_path, hot_key=hot, hot_n=1800)  # ~30%
    sp = sampling.build_sampled_plan(
        session, _qj(session, ws).optimized_plan(), FR
    )
    assert sp == "hot-key"
    # and the collect path serves the exact answer
    exact = _qj(session, ws).to_pydict()
    with sampling.approx_scope(FR):
        assert _qj(session, ws).to_pydict() == exact


# ---------------------------------------------------------------------------
# CI honesty + observability of the engaged tier
# ---------------------------------------------------------------------------


def test_sampled_join_ci_covers_and_explain_renders(tmp_path, monkeypatch):
    monkeypatch.setenv("HYPERSPACE_APPROX", "1")
    session, hs, ws = _mk_join(tmp_path)
    exact = _qj(session, ws).to_pydict()
    with plan_stats.collect_scope() as cap:
        with sampling.approx_scope(FR):
            approx = _qj(session, ws).to_pydict()
    info = (cap.summary() or {}).get("approx") or {}
    outs = info.get("outputs") or {}
    assert outs, "sampled tier did not engage"
    assert info["fraction"] == FR
    for name in ("s", "n"):
        diff = abs(float(approx[name][0]) - float(exact[name][0]))
        assert diff <= outs[name]["ci95_max"], name
    text = plan_stats.summary_string(cap)
    assert "sampled(f=0.1)" in text
    assert "±" in text and "@95%" in text


def test_verify_mode_passes_on_clean_data(tmp_path, monkeypatch):
    monkeypatch.setenv("HYPERSPACE_APPROX", "verify")
    session, hs, ws = _mk_join(tmp_path)
    before = sampling.APPROX.snapshot()["verify_checked"]
    with sampling.approx_scope(FR):
        _qj(session, ws).collect()  # raises ApproxVerifyError on a miss
    assert sampling.APPROX.snapshot()["verify_checked"] == before + 1


# ---------------------------------------------------------------------------
# chaos cells for the approx.sample fault point
# ---------------------------------------------------------------------------


@pytest.mark.parametrize(
    "spec",
    ["approx.sample:crash_before:n=1", "approx.sample:crash_after:n=1"],
)
def test_append_crash_cell_recovers_and_converges(tmp_path, monkeypatch, spec):
    """A crash in the twin-write bracket mid-append leaves no torn state:
    recover + re-run converges to a fully twinned index whose queries
    match raw, and the sampled tier stays engageable."""
    monkeypatch.setenv("HYPERSPACE_APPROX", "1")
    session, hs, src = _mk_ev(tmp_path)
    faults.arm(spec)
    try:
        with pytest.raises(faults.InjectedCrash):
            ingest.append_batch(session, "ev", _ev_batch(1))
    finally:
        faults.disarm()
    hs.recover(force=True)  # the "crashed" writer is this very process
    ingest.append_batch(session, "ev", _ev_batch(2))
    q = lambda: (
        session.read.parquet(src)
        .filter(col("k") < 50_000)
        .agg(Sum(col("v")).alias("s"))
        .to_pydict()
    )
    got = q()
    session.disable_hyperspace()
    try:
        assert q() == got
    finally:
        session.enable_hyperspace()
    # convergence: every published data file has its twins + meta back
    for path in _index_files(hs, "ev"):
        for f in sample_store.sample_fractions():
            assert os.path.exists(sample_store.sample_path(path, f)), path
        assert sample_store.load_sample_meta(path) is not None


def test_crash_leaves_tier_ineligible_never_wrong(tmp_path, monkeypatch):
    """If twins are simply absent (crash before any twin write landed),
    the sampled tier declines and the answer is exact."""
    monkeypatch.setenv("HYPERSPACE_APPROX", "1")
    session, hs, ws = _mk_join(tmp_path)
    # simulate the crash aftermath: strip every twin of one index
    for path in _index_files(hs, "li_idx"):
        for f in sample_store.sample_fractions():
            tp = sample_store.sample_path(path, f)
            if os.path.exists(tp):
                os.unlink(tp)
    exact = _qj(session, ws).to_pydict()
    with sampling.approx_scope(FR):
        assert _qj(session, ws).to_pydict() == exact
    assert (
        sampling.build_sampled_plan(
            session, _qj(session, ws).optimized_plan(), FR
        )
        == "missing-samples"
    )
