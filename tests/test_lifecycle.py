"""Resource-lifecycle soundness: leak auditor, HS5xx release-path lint
rules, sampled-plan verifier codes, and the observability drift linter.

The auditor's contract (docs/static_analysis.md "Resource lifecycle"):
with ``HYPERSPACE_LIFECYCLE_AUDIT=1`` every handle acquired at an
instrumented chokepoint (snapshot pins, budget streams, ledger waves,
attribution scopes, cache in-flight markers) is recorded with its owner
and acquire call chain; ``check_quiescent()`` raises ``ResourceLeakError``
naming every handle still live. The cancellation (BaseException) and
crash unwind paths are the prime leak suspects — ``except Exception``
cleanup never sees them, which is exactly what HS502 lints against.
Disarmed, the whole registry is one module-bool read: bit-identical
results, no counters, no allocation.
"""

import importlib.util
import json
import os
import subprocess
import sys
import threading

import numpy as np
import pytest

from hyperspace_tpu import (
    CoveringIndexConfig,
    Hyperspace,
    HyperspaceSession,
    ingest,
    serve,
)
from hyperspace_tpu import constants as C
from hyperspace_tpu.columnar import io as cio
from hyperspace_tpu.columnar.table import ColumnBatch
from hyperspace_tpu.meta.entry import FileInfo
from hyperspace_tpu.models import sample_store
from hyperspace_tpu.plan import Count, Sum, col, lit
from hyperspace_tpu.plan import sampling
from hyperspace_tpu.plan.nodes import FileScan
from hyperspace_tpu.serve.budget import BudgetAccountant
from hyperspace_tpu.staticcheck import lifecycle as lc
from hyperspace_tpu.staticcheck.lifecycle import ResourceLeakError
from hyperspace_tpu.staticcheck.plan_verifier import (
    SAMPLE_FILE_NOT_TWIN,
    SAMPLE_FRACTION_MISMATCH,
    SAMPLE_NOT_DECLARED,
    PlanInvariantError,
    verify_plan,
)
from hyperspace_tpu.telemetry.metrics import REGISTRY
from hyperspace_tpu.utils import backend, faults

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
HSLINT = os.path.join(REPO_ROOT, "tools", "hslint.py")
OBSLINT = os.path.join(REPO_ROOT, "tools", "obslint.py")

FR = 0.1


def _counter(name: str) -> int:
    m = REGISTRY.get(name)
    return 0 if m is None else m.value


def _bits(pydict):
    return repr(
        {
            k: [x.hex() if isinstance(x, float) else x for x in v]
            for k, v in pydict.items()
        }
    )


@pytest.fixture()
def audit():
    """Arm the lifecycle audit for one test, restoring the prior state
    (and an empty live-handle book) around it."""
    prev = lc.set_audit(True)
    lc.reset()
    yield
    lc.reset()
    lc.set_audit(prev)


@pytest.fixture(autouse=True)
def _pristine():
    yield
    faults.disarm()
    backend._reset_for_testing()
    serve.reset_global_budget()


def _write_multifile(root, n_files=4, rows=1500, seed=3):
    rng = np.random.default_rng(seed)
    paths = []
    for i in range(n_files):
        n = rows + i * 97
        data = {
            "k": rng.integers(0, 40, n).tolist(),
            "x": rng.uniform(0, 100, n).tolist(),
        }
        p = os.path.join(root, "t", f"part-{i}.parquet")
        cio.write_parquet(ColumnBatch.from_pydict(data), p)
        paths.append(p)
    return paths


# ---------------------------------------------------------------------------
# the registry: leak detection, owner/stack reporting, disarmed overhead
# ---------------------------------------------------------------------------

class TestLeakDetection:
    def test_leaked_stream_named_with_acquire_stack(self, audit):
        """A deliberately-unreleased budget stream is reported by kind,
        detail, and acquire call chain — the error message alone must be
        enough to find the leak site."""
        acct = BudgetAccountant(1000, name="serve.budget")
        s = acct.stream("leaky-scan")
        with pytest.raises(ResourceLeakError) as ei:
            lc.check_quiescent()
        msg = str(ei.value)
        assert "budget.stream" in msg
        assert "leaky-scan" in msg
        # the acquire call chain walks out of lifecycle.py into the
        # chokepoint (budget.py) and then this test file
        assert "budget.py" in msg
        assert "test_lifecycle.py" in msg
        assert len(ei.value.leaks) == 1
        s.close()
        assert lc.check_quiescent() == []

    def test_leaked_pin_detected_then_released(self, audit, tmp_session,
                                               tmp_path):
        hs = Hyperspace(tmp_session)
        src = str(tmp_path / "t")
        _write_multifile(str(tmp_path))
        hs.create_index(
            tmp_session.read.parquet(src),
            CoveringIndexConfig("ci", ["k"], ["x"]),
        )
        lc.reset()  # index build noise is not under test
        entry = hs.get_index("ci")
        ip = os.path.join(str(tmp_path), C.INDEXES_DIR, "ci")
        snap = ingest.REGISTRY.pin(ip, entry)
        with pytest.raises(ResourceLeakError) as ei:
            lc.check_quiescent()
        assert "snapshot.pin" in str(ei.value)
        ingest.REGISTRY.release(snap)
        assert lc.check_quiescent() == []

    def test_leaks_counter_and_report_shape(self, audit):
        acct = BudgetAccountant(1000)
        before = _counter("staticcheck.lifecycle.leaks")
        s = acct.stream("x")
        assert len(lc.check_quiescent(raise_on_leak=False)) == 1
        assert _counter("staticcheck.lifecycle.leaks") == before + 1
        rep = lc.report()
        assert rep["audit_enabled"] and len(rep["live"]) == 1
        assert rep["kinds"] == {"budget.stream": 1}
        s.close()
        assert lc.report()["live"] == []

    def test_mid_run_disarm_does_not_manufacture_leaks(self, audit):
        """A handle acquired while armed and released after a mid-run
        disarm still leaves the book; re-arming shows no phantom leak."""
        acct = BudgetAccountant(1000)
        s = acct.stream("x")
        lc.set_audit(False)
        s.close()
        lc.set_audit(True)
        assert lc.check_quiescent() == []

    def test_disarmed_is_zero_overhead_and_bit_identical(self, tmp_session,
                                                         tmp_path):
        """Disarmed: tracked_resource returns 0, no counters move, no
        handles are recorded — and arming changes no query bits."""
        prev = lc.set_audit(False)
        try:
            assert lc.tracked_resource("budget.stream", "x") == 0
            before = _counter("staticcheck.lifecycle.acquires")
            paths = _write_multifile(str(tmp_path))
            df = tmp_session.read.parquet(os.path.join(str(tmp_path), "t"))
            q = df.filter(col("k") < 20).agg(
                Sum(col("x")).alias("s"), Count(lit(1)).alias("n")
            )
            off = _bits(q.to_pydict())
            assert _counter("staticcheck.lifecycle.acquires") == before
            assert lc.report()["live"] == []
            lc.set_audit(True)
            lc.reset()
            on = _bits(q.to_pydict())
            assert on == off
            assert lc.check_quiescent() == []
        finally:
            lc.reset()
            lc.set_audit(prev)


# ---------------------------------------------------------------------------
# quiescence under hostile unwinds: cancellation storm, crash cells,
# abandoned streams
# ---------------------------------------------------------------------------

class TestQuiescence:
    def test_eight_way_cancellation_storm(self, audit, tmp_session,
                                          tmp_path, monkeypatch):
        """8 client threads submit and immediately cancel served queries;
        the BaseException unwind must release every handle it acquired."""
        monkeypatch.setenv("HYPERSPACE_STREAM_CHUNK_MB", "0.05")
        paths = _write_multifile(str(tmp_path), n_files=6, rows=2500)
        df_root = os.path.join(str(tmp_path), "t")
        sched = serve.QueryScheduler(max_concurrent=4, queue_depth=256)
        errors: list = []
        barrier = threading.Barrier(8)

        def q():
            df = tmp_session.read.parquet(df_root)
            return (
                df.filter(col("k") < 30)
                .agg(Sum(col("x")).alias("s"), Count(lit(1)).alias("n"))
                .collect()
            )

        def client(tid: int) -> None:
            try:
                barrier.wait()
                for i in range(4):
                    h = sched.submit(q, label=f"storm-{tid}-{i}")
                    h.cancel()
                    try:
                        h.result(timeout=120)
                    except serve.QueryCancelledError:
                        pass
            except Exception as e:  # noqa: BLE001 - reported via the gate
                errors.append((tid, repr(e)))

        threads = [
            threading.Thread(target=client, args=(i,)) for i in range(8)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        sched.drain(timeout=60)
        sched.shutdown(wait=True)
        assert not errors
        assert lc.check_quiescent() == []

    @pytest.mark.parametrize("spec", [
        "log.write:crash_before:n=1",
        "log.write:crash_after:n=1",
        "data.publish:crash_before:n=1",
    ])
    def test_crash_cell_quiescent(self, audit, tmp_path, spec):
        """An InjectedCrash (BaseException, the simulated process death of
        the PR 7 fault matrix) mid-maintenance must not strand handles."""
        _write_multifile(str(tmp_path))
        session = HyperspaceSession(warehouse_dir=str(tmp_path))
        session.set_conf(C.INDEX_NUM_BUCKETS, 4)
        hs = Hyperspace(session)
        lc.reset()
        faults.arm(spec)
        try:
            with pytest.raises(faults.InjectedCrash):
                hs.create_index(
                    session.read.parquet(os.path.join(str(tmp_path), "t")),
                    CoveringIndexConfig("ci", ["k"], ["x"]),
                )
        finally:
            faults.disarm()
        assert lc.check_quiescent() == []

    def test_abandoned_stream_mid_iteration_zero_leaks(self, audit,
                                                       tmp_path,
                                                       monkeypatch):
        """The satellite regression: dropping a chunk stream after one
        chunk (the cancellation unwind) must close its BudgetStream in the
        owning scope — under audit, zero live handles afterward."""
        paths = _write_multifile(str(tmp_path), n_files=6, rows=2500)
        monkeypatch.setenv("HYPERSPACE_IO_THREADS", "4")
        monkeypatch.setenv("HYPERSPACE_STREAM_CHUNK_MB", "0.01")
        acct = serve.reset_global_budget()
        lc.reset()
        it = cio.iter_chunks(paths, ["k", "x"])
        next(it)  # read-ahead now holds reservations beyond chunk 0
        it.close()
        assert acct.held_bytes() == 0
        assert lc.report()["acquires"] >= 1  # the stream was tracked
        assert lc.check_quiescent() == []


# ---------------------------------------------------------------------------
# sampled-plan verifier codes
# ---------------------------------------------------------------------------

def _mk_sampled(tmp_path, monkeypatch):
    """Fact/dim pair with sample twins, plus a built sampled plan."""
    monkeypatch.setenv("HYPERSPACE_APPROX", "1")
    ws = str(tmp_path)
    rng = np.random.default_rng(7)
    n, orders = 6000, 1500
    cio.write_parquet(
        ColumnBatch.from_pydict({
            "fk": rng.integers(0, orders, n).astype(np.int64).tolist(),
            "amt": rng.uniform(1, 100, n).tolist(),
        }),
        os.path.join(ws, "li", "part0.parquet"),
    )
    cio.write_parquet(
        ColumnBatch.from_pydict({
            "ok": np.arange(orders, dtype=np.int64).tolist(),
            "dt": rng.integers(0, 1000, orders).tolist(),
        }),
        os.path.join(ws, "od", "part0.parquet"),
    )
    session = HyperspaceSession(warehouse_dir=ws)
    session.set_conf(C.INDEX_NUM_BUCKETS, 4)
    hs = Hyperspace(session)
    hs.create_index(
        session.read.parquet(os.path.join(ws, "li")),
        CoveringIndexConfig("li_idx", ["fk"], ["amt"]),
    )
    hs.create_index(
        session.read.parquet(os.path.join(ws, "od")),
        CoveringIndexConfig("od_idx", ["ok"], ["dt"]),
    )
    session.enable_hyperspace()
    li = session.read.parquet(os.path.join(ws, "li"))
    od = session.read.parquet(os.path.join(ws, "od"))
    q = (
        li.select("fk", "amt")
        .join(od.select("ok", "dt"), col("fk") == col("ok"))
        .filter(col("dt") < 500)
        .agg(Sum(col("amt")).alias("s"), Count(lit(1)).alias("n"))
    )
    sp = sampling.build_sampled_plan(session, q.optimized_plan(), FR)
    assert not isinstance(sp, str), f"sampled tier declined: {sp}"
    return session, sp, q


def _sampled_scans(plan):
    return [
        s for s in plan.preorder()
        if isinstance(s, FileScan) and s.sample_spec is not None
    ]


class TestSampledPlanVerifier:
    def test_accepts_real_sampled_plan(self, tmp_path, monkeypatch):
        session, sp, _q = _mk_sampled(tmp_path, monkeypatch)
        assert _sampled_scans(sp.plan)
        verify_plan(sp.plan, session)  # must not raise

    def test_non_twin_file_rejected(self, tmp_path, monkeypatch):
        """A sampled scan substituted with the BASE file silently changes
        the scale factor — the worst possible bug, caught by name."""
        session, sp, _q = _mk_sampled(tmp_path, monkeypatch)
        scan = _sampled_scans(sp.plan)[0]
        d, base = os.path.split(scan.files[0].name)
        _frac, base_name = sample_store.parse_sample_name(base)
        scan.files = [FileInfo.from_path(os.path.join(d, base_name))]
        with pytest.raises(PlanInvariantError) as ei:
            verify_plan(sp.plan, session)
        assert SAMPLE_FILE_NOT_TWIN in {v.code for v in ei.value.violations}

    def test_fraction_mismatch_against_meta(self, tmp_path, monkeypatch):
        """The spec's tier must be one the sample store materialized: a
        kept-map without the ppm means nobody built twins at that rate."""
        session, sp, _q = _mk_sampled(tmp_path, monkeypatch)
        scan = _sampled_scans(sp.plan)[0]
        spec = scan.sample_spec
        for f in scan.files:
            d, base = os.path.split(f.name)
            _frac, base_name = sample_store.parse_sample_name(base)
            mp = sample_store.sample_meta_path(os.path.join(d, base_name))
            with open(mp, encoding="utf-8") as fh:
                meta = json.load(fh)
            meta["kept"].pop(str(spec.ppm), None)
            with open(mp, "w", encoding="utf-8") as fh:
                json.dump(meta, fh)
        with pytest.raises(PlanInvariantError) as ei:
            verify_plan(sp.plan, session)
        assert SAMPLE_FRACTION_MISMATCH in {
            v.code for v in ei.value.violations
        }

    def test_vanished_twins_rejected(self, tmp_path, monkeypatch):
        """Twins deleted out from under a built plan (a vacuum bug, a
        manual rm): the declared-at-this-fraction check fires."""
        session, sp, _q = _mk_sampled(tmp_path, monkeypatch)
        for scan in _sampled_scans(sp.plan):
            for f in scan.files:
                os.remove(f.name)
        with pytest.raises(PlanInvariantError) as ei:
            verify_plan(sp.plan, session)
        assert SAMPLE_NOT_DECLARED in {v.code for v in ei.value.violations}

    def test_wired_into_verify_knob(self, tmp_path, monkeypatch):
        """HYPERSPACE_VERIFY_PLAN=1 verifies the sampled plan too (it
        bypasses DataFrame.optimized_plan, so sampling calls the hook)."""
        session, sp, q = _mk_sampled(tmp_path, monkeypatch)
        monkeypatch.setenv("HYPERSPACE_VERIFY_PLAN", "1")
        runs = _counter("staticcheck.plan.runs")
        bad = _counter("staticcheck.plan.violations")
        with sampling.approx_scope(FR):
            q.to_pydict()
        assert _counter("staticcheck.plan.runs") > runs
        assert _counter("staticcheck.plan.violations") == bad


# ---------------------------------------------------------------------------
# HS5xx release-path lint rules
# ---------------------------------------------------------------------------

_PLANTED = '''\
def work(x):
    return x


def hs501_leak(acct):
    s = acct.stream("scan")
    return None


def hs502_blind_cleanup(acct):
    try:
        s = acct.stream("scan")
        work(s)
    except Exception:
        s.release(1)


def hs503_fragile_finally(a, b):
    try:
        work(a)
    finally:
        a.close()
        b.close()


def ok_finally(acct):
    s = acct.stream("scan")
    try:
        return work(s)
    finally:
        s.close()


def ok_with(acct):
    with acct.stream("scan") as s:
        return work(s)


def ok_handoff(acct, sink):
    sink.append(acct.stream("scan"))


def ok_return(acct):
    return acct.stream("scan")


def ok_guarded_finally(a, b):
    try:
        work(a)
    finally:
        try:
            a.close()
        finally:
            b.close()
'''


class TestHS5xx:
    def _run(self, path):
        return subprocess.run(
            [sys.executable, HSLINT, str(path), "--no-baseline"],
            capture_output=True, text=True, timeout=120,
        )

    def test_catches_planted_release_path_bugs(self, tmp_path):
        bad = tmp_path / "planted.py"
        bad.write_text(_PLANTED)
        proc = self._run(bad)
        assert proc.returncode == 1
        for code in ("HS501", "HS502", "HS503"):
            assert code in proc.stdout, f"{code} missing:\n{proc.stdout}"
        # each fires exactly once: the ok_* shapes stay silent
        for code, fn in (
            ("HS501", "hs501_leak"),
            ("HS502", "hs502_blind_cleanup"),
            ("HS503", "hs503_fragile_finally"),
        ):
            lines = [ln for ln in proc.stdout.splitlines() if code in ln]
            assert len(lines) == 1, f"{code}:\n{proc.stdout}"
            assert fn in lines[0]
        assert "ok_" not in proc.stdout

    def test_suppression_comment_silences(self, tmp_path):
        ok = tmp_path / "suppressed.py"
        ok.write_text(
            "def f(acct):\n"
            "    s = acct.stream('scan')  # hslint: HS501 — fixture\n"
            "    return None\n"
        )
        proc = self._run(ok)
        assert proc.returncode == 0, proc.stdout


# ---------------------------------------------------------------------------
# observability drift linter
# ---------------------------------------------------------------------------

def _load_obslint():
    spec = importlib.util.spec_from_file_location("obslint", OBSLINT)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class TestObslint:
    def test_catalog_in_sync(self):
        """Every metric/span name the package can emit is documented in
        docs/observability.md (modulo the checked-in baseline)."""
        proc = subprocess.run(
            [sys.executable, OBSLINT],
            capture_output=True, text=True, timeout=120,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert " 0 undocumented" in proc.stdout

    def test_catches_planted_drift(self, tmp_path):
        mod = _load_obslint()
        (tmp_path / "m.py").write_text(
            'from hyperspace_tpu.telemetry.metrics import REGISTRY\n'
            'from hyperspace_tpu.telemetry import trace\n'
            'REGISTRY.counter("totally.new.metric").inc()\n'
            'with trace.span("brand:new-span"):\n'
            '    pass\n'
        )
        code = mod.collect_code(str(tmp_path))
        patterns = mod.collect_docs()
        assert "metric::totally.new.metric" in code
        assert not mod.covered("totally.new.metric", patterns)
        assert not mod.covered("brand:new-span", patterns)

    def test_wildcard_matching(self):
        mod = _load_obslint()
        pats = ["rules.<Rule>.applied", "serve.budget.force_grants",
                "cache.result.{hits,misses}"]
        pats = [p for raw in pats for p in mod._expand_braces(raw)]
        pats = [mod._to_pattern(p) for p in pats]
        # docs placeholder absorbs a concrete code segment
        assert mod.covered("rules.MyRule.applied", pats)
        # code f-string interpolation absorbed by a literal docs name
        assert mod.covered("*.force_grants", pats)
        assert mod.covered("cache.result.misses", pats)
        assert not mod.covered("cache.result.evictions", pats)

    def test_fstrings_wildcard_and_braces_expand(self, tmp_path):
        mod = _load_obslint()
        (tmp_path / "m.py").write_text(
            'def f(reg, kind):\n'
            '    reg.histogram(f"kernel.{kind}.dispatch_ms").observe(1)\n'
        )
        code = mod.collect_code(str(tmp_path))
        assert "metric::kernel.*.dispatch_ms" in code
        assert mod.covered(
            "kernel.*.dispatch_ms", ["kernel.<name>.dispatch_ms"]
        )


# ---------------------------------------------------------------------------
# env knob registration
# ---------------------------------------------------------------------------

def test_lifecycle_knob_registered():
    from hyperspace_tpu.utils import env as env_registry

    assert "HYPERSPACE_LIFECYCLE_AUDIT" in {
        k.name for k in env_registry.all_knobs()
    }
