"""DataSkippingIndex tests (ref: dataskipping suites — sketches, predicate
translation, rule application, incremental refresh)."""

import numpy as np
import pytest

from hyperspace_tpu import (
    BloomFilterSketch,
    DataSkippingIndexConfig,
    Hyperspace,
    MinMaxSketch,
    ValueListSketch,
)
from hyperspace_tpu import constants as C
from hyperspace_tpu.columnar import io as cio
from hyperspace_tpu.columnar.table import ColumnBatch
from hyperspace_tpu.exceptions import HyperspaceError
from hyperspace_tpu.plan import col, lit, Count, Sum
from hyperspace_tpu.plan.nodes import FileScan


def file_scan(plan):
    return [n for n in plan.preorder() if isinstance(n, FileScan)][0]


@pytest.fixture()
def env(tmp_session, tmp_path):
    # 4 files with disjoint key ranges: perfect skipping setup
    src = tmp_path / "src"
    for i in range(4):
        data = {
            "k": list(range(i * 100, (i + 1) * 100)),
            "v": [float(j) for j in range(100)],
            "cat": [f"c{i}"] * 100,
        }
        cio.write_parquet(ColumnBatch.from_pydict(data), str(src / f"f{i}.parquet"))
    hs = Hyperspace(tmp_session)
    df = tmp_session.read.parquet(str(src))
    return tmp_session, hs, df, src


class TestSketchTable:
    def test_minmax_table(self, env):
        session, hs, df, _ = env
        hs.create_index(df, DataSkippingIndexConfig("ds1", [MinMaxSketch("k")]))
        entry = hs.get_index("ds1")
        assert entry.kind == "DS"
        table = cio.read_parquet(entry.content.files())
        assert table.num_rows == 4
        d = table.to_pydict()
        assert sorted(d["k__min"]) == [0, 100, 200, 300]
        assert sorted(d["k__max"]) == [99, 199, 299, 399]

    def test_multiple_sketches(self, env):
        session, hs, df, _ = env
        hs.create_index(
            df,
            DataSkippingIndexConfig(
                "ds1", [MinMaxSketch("k"), BloomFilterSketch("cat", 10, 0.01)]
            ),
        )
        table = cio.read_parquet(hs.get_index("ds1").content.files())
        assert set(table.schema.names) == {
            "_data_file_id", "k__min", "k__max", "cat__bloom",
        }

    def test_duplicate_sketch_rejected(self):
        with pytest.raises(HyperspaceError, match="Duplicate"):
            DataSkippingIndexConfig("x", [MinMaxSketch("k"), MinMaxSketch("K")])


class TestSkippingRule:
    def test_files_pruned(self, env):
        session, hs, df, src = env
        hs.create_index(df, DataSkippingIndexConfig("ds1", [MinMaxSketch("k")]))
        session.enable_hyperspace()
        df2 = session.read.parquet(str(src))
        q = df2.filter(col("k") == 150).select("k", "v")
        plan = q.optimized_plan()
        scan = file_scan(plan)
        assert len(scan.files) == 1  # 3 of 4 files skipped
        assert scan.index_info is not None and scan.index_info.index_kind_abbr == "DS"
        # correctness preserved
        session.disable_hyperspace()
        expected = df2.filter(col("k") == 150).select("k", "v").to_pydict()
        session.enable_hyperspace()
        assert q.to_pydict() == expected

    def test_range_predicate(self, env):
        session, hs, df, src = env
        hs.create_index(df, DataSkippingIndexConfig("ds1", [MinMaxSketch("k")]))
        session.enable_hyperspace()
        df2 = session.read.parquet(str(src))
        plan = df2.filter(col("k") >= 250).select("k").optimized_plan()
        assert len(file_scan(plan).files) == 2  # files 2 (200-299) and 3

    def test_disjunction(self, env):
        session, hs, df, src = env
        hs.create_index(df, DataSkippingIndexConfig("ds1", [MinMaxSketch("k")]))
        session.enable_hyperspace()
        df2 = session.read.parquet(str(src))
        plan = (
            df2.filter((col("k") == 50) | (col("k") == 350)).select("k").optimized_plan()
        )
        assert len(file_scan(plan).files) == 2

    def test_or_with_unboundable_side_no_skip(self, env):
        session, hs, df, src = env
        hs.create_index(df, DataSkippingIndexConfig("ds1", [MinMaxSketch("k")]))
        session.enable_hyperspace()
        df2 = session.read.parquet(str(src))
        # v is not sketched: OR cannot skip anything
        plan = (
            df2.filter((col("k") == 50) | (col("v") == 1.0)).select("k", "v").optimized_plan()
        )
        assert len(file_scan(plan).files) == 4

    def test_and_partial_bound_still_skips(self, env):
        session, hs, df, src = env
        hs.create_index(df, DataSkippingIndexConfig("ds1", [MinMaxSketch("k")]))
        session.enable_hyperspace()
        df2 = session.read.parquet(str(src))
        plan = (
            df2.filter((col("k") == 50) & (col("v") > 0)).select("k", "v").optimized_plan()
        )
        assert len(file_scan(plan).files) == 1

    def test_bloom_sketch_skips(self, env):
        session, hs, df, src = env
        hs.create_index(
            df, DataSkippingIndexConfig("ds1", [BloomFilterSketch("cat", 10, 0.001)])
        )
        session.enable_hyperspace()
        df2 = session.read.parquet(str(src))
        plan = df2.filter(col("cat") == "c2").select("cat").optimized_plan()
        assert len(file_scan(plan).files) == 1

    def test_value_list_sketch(self, env):
        session, hs, df, src = env
        hs.create_index(df, DataSkippingIndexConfig("ds1", [ValueListSketch("cat")]))
        session.enable_hyperspace()
        df2 = session.read.parquet(str(src))
        plan = df2.filter(col("cat").isin(["c1", "c3"])).select("cat").optimized_plan()
        assert len(file_scan(plan).files) == 2

    def test_covering_beats_skipping(self, env):
        from hyperspace_tpu import CoveringIndexConfig

        session, hs, df, src = env
        hs.create_index(df, DataSkippingIndexConfig("ds1", [MinMaxSketch("k")]))
        hs.create_index(df, CoveringIndexConfig("ci1", ["k"], ["v"]))
        session.enable_hyperspace()
        df2 = session.read.parquet(str(src))
        plan = df2.filter(col("k") == 150).select("k", "v").optimized_plan()
        scan = file_scan(plan)
        assert scan.index_info.index_name == "ci1"  # score 50 beats 1

    def test_ne_skips_constant_files(self, tmp_session, tmp_path):
        src = tmp_path / "c"
        cio.write_parquet(ColumnBatch.from_pydict({"k": [5, 5, 5]}), str(src / "a.parquet"))
        cio.write_parquet(ColumnBatch.from_pydict({"k": [5, 6, 7]}), str(src / "b.parquet"))
        hs = Hyperspace(tmp_session)
        df = tmp_session.read.parquet(str(src))
        hs.create_index(df, DataSkippingIndexConfig("ds1", [MinMaxSketch("k")]))
        tmp_session.enable_hyperspace()
        df2 = tmp_session.read.parquet(str(src))
        from hyperspace_tpu.plan.expr import Not

        plan = df2.filter(Not(col("k") == 5)).select("k").optimized_plan()
        assert len(file_scan(plan).files) == 1  # all-5 file skipped




class TestWhyNotDS:
    def test_why_not_ds_reason(self, env):
        """DS-specific reason code surfaces when no sketch can bound the
        predicate (ref: FilterReason catalog coverage)."""
        session, hs, df, src = env
        hs.create_index(df, DataSkippingIndexConfig("dsr", [MinMaxSketch("k")]))
        # v is not sketched and the predicate has no boundable part
        s = hs.why_not(df.filter(col("v") > 1.0).select("k", "v"), extended=True)
        assert "NO_CONVERTIBLE_PREDICATE" in s or "NO_FIRST_INDEXED_COL" in s


class TestDSRefresh:
    def test_incremental_append_and_delete(self, env):
        import os

        session, hs, df, src = env
        hs.create_index(df, DataSkippingIndexConfig("ds1", [MinMaxSketch("k")]))
        cio.write_parquet(
            ColumnBatch.from_pydict({"k": [1000], "v": [0.0], "cat": ["x"]}),
            str(src / "new.parquet"),
        )
        os.unlink(src / "f0.parquet")
        hs.refresh_index("ds1", "incremental")
        table = cio.read_parquet(hs.get_index("ds1").content.files())
        d = table.to_pydict()
        assert 1000 in d["k__min"]  # appended file sketched
        assert 0 not in d["k__min"]  # deleted file's row dropped
        assert table.num_rows == 4

    def test_full_refresh(self, env):
        session, hs, df, src = env
        hs.create_index(df, DataSkippingIndexConfig("ds1", [MinMaxSketch("k")]))
        cio.write_parquet(
            ColumnBatch.from_pydict({"k": [9999], "v": [0.0], "cat": ["x"]}),
            str(src / "new.parquet"),
        )
        hs.refresh_index("ds1", "full")
        table = cio.read_parquet(hs.get_index("ds1").content.files())
        assert table.num_rows == 5


class TestSketchDtypeWidth:
    """Bloom probes must match regardless of the column's storage width."""

    def test_bloom_on_int32_column(self, tmp_session, tmp_path):
        import pyarrow as pa
        import pyarrow.parquet as pq

        src = tmp_path / "i32"
        src.mkdir()
        pq.write_table(
            pa.table({"a": pa.array([5, 6], type=pa.int32())}), str(src / "1.parquet")
        )
        pq.write_table(
            pa.table({"a": pa.array([100, 101], type=pa.int32())}), str(src / "2.parquet")
        )
        hs = Hyperspace(tmp_session)
        df = tmp_session.read.parquet(str(src))
        hs.create_index(df, DataSkippingIndexConfig("b32", [BloomFilterSketch("a", 10, 0.01)]))
        tmp_session.enable_hyperspace()
        df2 = tmp_session.read.parquet(str(src))
        q = df2.filter(col("a") == 5).select("a")
        plan = q.optimized_plan()
        assert len(file_scan(plan).files) == 1  # must NOT prune the real file
        assert q.to_pydict()["a"] == [5]

    def test_bloom_on_float_column(self, tmp_session, tmp_path):
        src = tmp_path / "f"
        cio.write_parquet(ColumnBatch.from_pydict({"a": [1.5, 2.5]}), str(src / "1.parquet"))
        cio.write_parquet(ColumnBatch.from_pydict({"a": [9.5]}), str(src / "2.parquet"))
        hs = Hyperspace(tmp_session)
        df = tmp_session.read.parquet(str(src))
        hs.create_index(df, DataSkippingIndexConfig("bf", [BloomFilterSketch("a", 10, 0.01)]))
        tmp_session.enable_hyperspace()
        df2 = tmp_session.read.parquet(str(src))
        q = df2.filter(col("a") == 9.5).select("a")
        assert q.to_pydict()["a"] == [9.5]


class TestBuildGuardInWorkers:
    def test_sketch_build_with_rewrite_enabled_and_other_index(self, tmp_session, tmp_path):
        """Per-file maintenance reads in pool workers must not be served
        through another index (thread-local guard propagated to workers)."""
        from hyperspace_tpu import CoveringIndexConfig

        src = tmp_path / "g"
        for i in range(3):
            cio.write_parquet(
                ColumnBatch.from_pydict(
                    {"k": list(range(i * 10, (i + 1) * 10)), "v": [1.0] * 10}
                ),
                str(src / f"f{i}.parquet"),
            )
        hs = Hyperspace(tmp_session)
        df = tmp_session.read.parquet(str(src))
        hs.create_index(df, CoveringIndexConfig("ci_all", ["k"], ["v"]))
        tmp_session.enable_hyperspace()  # rewrite ON during the next build
        hs.create_index(df, DataSkippingIndexConfig("ds_g", [MinMaxSketch("k")]))
        table = cio.read_parquet(hs.get_index("ds_g").content.files())
        d = table.to_pydict()
        # per-FILE ranges, not the whole-source range repeated
        assert sorted(d["k__min"]) == [0, 10, 20]
        assert sorted(d["k__max"]) == [9, 19, 29]


class TestNaNBounds:
    """A NaN row must not poison a file's min/max sketch (regression: the
    NaN bounds made every predicate False and the file was permanently
    skipped). Spark's Min/Max order NaN largest and would not mis-skip."""

    def test_nan_row_does_not_skip_file(self, tmp_session, tmp_path):
        src = tmp_path / "src"
        cio.write_parquet(
            ColumnBatch.from_pydict({"x": [1.0, 2.0, 3.0, float("nan"), 5.0]}),
            str(src / "f0.parquet"),
        )
        cio.write_parquet(
            ColumnBatch.from_pydict({"x": [10.0, 11.0]}), str(src / "f1.parquet")
        )
        hs = Hyperspace(tmp_session)
        df = tmp_session.read.parquet(str(src))
        hs.create_index(df, DataSkippingIndexConfig("dsnan", [MinMaxSketch("x")]))
        tmp_session.enable_hyperspace()
        out = (
            tmp_session.read.parquet(str(src)).filter(col("x") == 2.0).to_pydict()
        )
        assert out["x"] == [2.0]
        # the all-finite file is still skippable
        out2 = (
            tmp_session.read.parquet(str(src)).filter(col("x") == 10.0).to_pydict()
        )
        assert out2["x"] == [10.0]
