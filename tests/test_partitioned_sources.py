"""Hive-style partitioned source tests: virtual columns, partition pruning,
PartitionSketch auto-add (ref: partitioned-data suites + PartitionSketch)."""

import numpy as np
import pytest

from hyperspace_tpu import (
    CoveringIndexConfig,
    DataSkippingIndexConfig,
    Hyperspace,
    MinMaxSketch,
)
from hyperspace_tpu.columnar import io as cio
from hyperspace_tpu.columnar.table import ColumnBatch
from hyperspace_tpu.plan import col, lit, Count, Sum
from hyperspace_tpu.plan.nodes import FileScan
from hyperspace_tpu.utils.partitions import (
    infer_partition_fields,
    parse_partition_values,
)


@pytest.fixture()
def part_src(tmp_path):
    src = tmp_path / "sales"
    for year in (2020, 2021):
        for region in ("eu", "us"):
            data = {
                "amount": [float(year % 100 + i) for i in range(10)],
                "item": [f"i{i}" for i in range(10)],
            }
            cio.write_parquet(
                ColumnBatch.from_pydict(data),
                str(src / f"year={year}" / f"region={region}" / "part-0.parquet"),
            )
    return src


class TestPartitionParsing:
    def test_parse(self):
        assert parse_partition_values("/d/year=2020/region=eu/f.parquet") == {
            "year": "2020",
            "region": "eu",
        }

    def test_infer_types(self):
        fields = infer_partition_fields(
            ["/d/year=2020/region=eu/a.parquet", "/d/year=2021/region=us/b.parquet"]
        )
        assert [(f.name, f.dtype) for f in fields] == [
            ("year", "int64"),
            ("region", "string"),
        ]

    def test_disagreeing_keys_ignored(self):
        assert infer_partition_fields(["/d/year=1/a.parquet", "/d/b.parquet"]) == []


class TestPartitionedScan:
    def test_virtual_columns(self, tmp_session, part_src):
        df = tmp_session.read.parquet(str(part_src))
        assert "year" in df.columns and "region" in df.columns
        out = df.group_by("year", "region").agg(Count(lit(1)).alias("n")).to_pydict()
        assert sorted(zip(out["year"], out["region"], out["n"])) == [
            (2020, "eu", 10), (2020, "us", 10), (2021, "eu", 10), (2021, "us", 10),
        ]

    def test_filter_on_partition_column(self, tmp_session, part_src):
        df = tmp_session.read.parquet(str(part_src))
        out = df.filter((col("year") == 2021) & (col("region") == "us")).agg(
            Count(lit(1)).alias("n")
        )
        assert out.to_pydict()["n"] == [10]

    def test_partition_pruning_skips_reads(self, tmp_session, part_src, monkeypatch):
        import hyperspace_tpu.columnar.io as cio_mod

        reads = []
        orig = cio_mod.read_parquet

        def spy(paths, columns=None, arrow_filter=None, cache=False, **kw):
            reads.extend(paths)
            return orig(paths, columns, arrow_filter, cache=cache, **kw)

        monkeypatch.setattr(cio_mod, "read_parquet", spy)
        df = tmp_session.read.parquet(str(part_src))
        df.filter(col("year") == 2020).select("amount", "year").collect()
        assert all("year=2020" in p for p in reads)
        assert len(reads) == 2  # only the two 2020 files

    def test_mixed_partition_and_data_filter(self, tmp_session, part_src):
        df = tmp_session.read.parquet(str(part_src))
        q = df.filter((col("year") == 2020) & (col("amount") > 22.0)).select(
            "amount", "region"
        )
        out = q.to_pydict()
        assert all(a > 22.0 for a in out["amount"])
        assert len(out["amount"]) == 14  # 2020: amounts 20..29 per region, 7 each > 22


class TestPartitionedIndexes:
    def test_covering_index_over_partitioned_source(self, tmp_session, part_src):
        hs = Hyperspace(tmp_session)
        df = tmp_session.read.parquet(str(part_src))
        hs.create_index(df, CoveringIndexConfig("pidx", ["item"], ["amount", "year"]))
        entry = hs.get_index("pidx")
        batch = cio.read_parquet(entry.content.files())
        # partition column materialized into the index data
        assert "year" in batch.schema.names
        assert batch.num_rows == 40

    def test_partition_sketch_auto_added(self, tmp_session, part_src):
        hs = Hyperspace(tmp_session)
        df = tmp_session.read.parquet(str(part_src))
        hs.create_index(df, DataSkippingIndexConfig("ds", [MinMaxSketch("amount")]))
        entry = hs.get_index("ds")
        kinds = {type(s).__name__ for s in entry.derived_dataset.sketches}
        assert "PartitionSketch" in kinds
        table = cio.read_parquet(entry.content.files())
        assert "year__part" in table.schema.names
        assert "region__part" in table.schema.names

    def test_partition_sketch_skips_disjunction(self, tmp_session, part_src):
        """The PartitionSketch point: OR over partition + data columns can
        still skip files (plain partition pruning cannot handle the OR)."""
        hs = Hyperspace(tmp_session)
        df = tmp_session.read.parquet(str(part_src))
        hs.create_index(df, DataSkippingIndexConfig("ds", [MinMaxSketch("amount")]))
        tmp_session.enable_hyperspace()
        df2 = tmp_session.read.parquet(str(part_src))
        q = df2.filter((col("year") == 2021) | (col("amount") < 5.0))
        plan = q.optimized_plan()
        scan = [n for n in plan.preorder() if isinstance(n, FileScan)][0]
        # amount ranges: 2020 -> 20..29, 2021 -> 21..30; amount<5 never true,
        # so only year=2021 files survive
        assert len(scan.files) == 2
        assert q.count() == 20


class TestPartitionParsingScopes:
    """Only directory components BELOW the read root count as partitions."""

    def test_equals_in_ancestor_dir_ignored(self, tmp_session, tmp_path):
        root = tmp_path / "run=3" / "table"
        cio.write_parquet(ColumnBatch.from_pydict({"a": [1]}), str(root / "f.parquet"))
        df = tmp_session.read.parquet(str(root))
        assert df.columns == ["a"]  # no fabricated 'run' column

    def test_equals_in_filename_ignored(self, tmp_session, tmp_path):
        root = tmp_path / "t"
        cio.write_parquet(
            ColumnBatch.from_pydict({"a": [1]}), str(root / "date=2024.parquet")
        )
        df = tmp_session.read.parquet(str(root))
        assert df.columns == ["a"]

    def test_partition_only_projection_uses_metadata(self, tmp_session, tmp_path, monkeypatch):
        import hyperspace_tpu.columnar.io as cio_mod

        root = tmp_path / "p"
        for y in (1, 2):
            cio.write_parquet(
                ColumnBatch.from_pydict({"a": list(range(5))}),
                str(root / f"y={y}" / "f.parquet"),
            )
        called = []
        orig = cio_mod.read_parquet
        monkeypatch.setattr(
            cio_mod, "read_parquet", lambda *a, **k: called.append(a) or orig(*a, **k)
        )
        df = tmp_session.read.parquet(str(root))
        out = df.select("y").group_by("y").agg(Count(lit(1)).alias("n")).to_pydict()
        assert sorted(zip(out["y"], out["n"])) == [(1, 5), (2, 5)]
        assert not called  # row counts came from parquet metadata only

    def test_reader_format_option_does_not_break_indexing(self, tmp_session, tmp_path):
        cio.write_parquet(
            ColumnBatch.from_pydict({"k": [1, 2], "v": [1.0, 2.0]}),
            str(tmp_path / "f" / "p.parquet"),
        )
        hs = Hyperspace(tmp_session)
        df = tmp_session.read.option("format", "parquet").parquet(str(tmp_path / "f"))
        hs.create_index(df, CoveringIndexConfig("oidx", ["k"], ["v"]))
        tmp_session.enable_hyperspace()
        df2 = tmp_session.read.option("format", "parquet").parquet(str(tmp_path / "f"))
        plan = df2.filter(col("k") == 1).select("k", "v").optimized_plan()
        assert any(
            getattr(n, "index_info", None) for n in plan.preorder()
        ), "unrelated format option must not disable indexing"


class TestPartitionedRefresh:
    def test_full_refresh_over_partitioned_source(self, tmp_session, part_src):
        hs = Hyperspace(tmp_session)
        df = tmp_session.read.parquet(str(part_src))
        hs.create_index(df, CoveringIndexConfig("pr", ["item"], ["amount", "year"]))
        # append inside a new partition dir, then refresh
        cio.write_parquet(
            ColumnBatch.from_pydict({"amount": [7.0], "item": ["i0"]}),
            str(part_src / "year=2022" / "region=eu" / "p.parquet"),
        )
        hs.refresh_index("pr", "full")
        entry = hs.get_index("pr")
        batch = cio.read_parquet(entry.content.files())
        assert batch.num_rows == 41
        assert 2022 in batch.to_pydict()["year"]
