"""DataFrame frontend + executor tests (the analogue of the reference's
query-path correctness assertions with QueryTest.checkAnswer)."""

import numpy as np
import pytest

from hyperspace_tpu.plan import col, lit, Avg, Count, Max, Min, Sum
from hyperspace_tpu.columnar.table import ColumnBatch
from hyperspace_tpu.columnar import io as cio


@pytest.fixture()
def sample_df(tmp_session, tmp_path):
    data = {
        "id": [1, 2, 3, 4, 5, 6],
        "qty": [10, 20, 30, 40, 50, 60],
        "price": [1.0, 2.0, 3.0, 4.0, 5.0, 6.0],
        "cat": ["a", "b", "a", "b", "a", "c"],
    }
    cio.write_parquet(
        ColumnBatch.from_pydict(data), str(tmp_path / "src" / "part-0.parquet")
    )
    return tmp_session.read.parquet(str(tmp_path / "src"))


class TestFrontend:
    def test_scan_collect(self, sample_df):
        out = sample_df.collect()
        assert out.num_rows == 6
        assert out.to_pydict()["cat"] == ["a", "b", "a", "b", "a", "c"]

    def test_filter(self, sample_df):
        out = sample_df.filter(col("qty") > 30).to_pydict()
        assert out["id"] == [4, 5, 6]

    def test_filter_string_eq(self, sample_df):
        out = sample_df.filter(col("cat") == "a").to_pydict()
        assert out["id"] == [1, 3, 5]

    def test_compound_predicate(self, sample_df):
        out = sample_df.filter((col("qty") >= 20) & (col("cat") == "b")).to_pydict()
        assert out["id"] == [2, 4]

    def test_select_project(self, sample_df):
        out = sample_df.select("id", (col("qty") * col("price")).alias("rev")).to_pydict()
        assert out["rev"] == [10.0, 40.0, 90.0, 160.0, 250.0, 360.0]

    def test_in_and_not(self, sample_df):
        out = sample_df.filter(~col("cat").isin(["a", "c"])).to_pydict()
        assert out["id"] == [2, 4]

    def test_sort_limit(self, sample_df):
        out = sample_df.sort("qty", ascending=False).limit(2).to_pydict()
        assert out["id"] == [6, 5]

    def test_sort_by_string(self, sample_df):
        out = sample_df.sort("cat", "id").to_pydict()
        assert out["cat"] == ["a", "a", "a", "b", "b", "c"]

    def test_global_agg(self, sample_df):
        out = sample_df.agg(
            Sum(col("qty")).alias("s"),
            Min(col("price")).alias("mn"),
            Max(col("price")).alias("mx"),
            Count(lit(1)).alias("n"),
            Avg(col("qty")).alias("avg"),
        ).to_pydict()
        assert out == {"s": [210], "mn": [1.0], "mx": [6.0], "n": [6], "avg": [35.0]}

    def test_group_by(self, sample_df):
        out = (
            sample_df.group_by("cat")
            .agg(Sum(col("qty")).alias("s"), Count(lit(1)).alias("n"))
            .sort("cat")
            .to_pydict()
        )
        assert out["cat"] == ["a", "b", "c"]
        assert out["s"] == [90, 60, 60]
        assert out["n"] == [3, 2, 1]

    def test_join(self, tmp_session):
        left = tmp_session.create_dataframe({"k": [1, 2, 3], "lv": ["x", "y", "z"]})
        right = tmp_session.create_dataframe({"rk": [2, 3, 3, 4], "rv": [20, 30, 31, 40]})
        out = (
            left.join(right, left["k"] == right["rk"])
            .sort("rv")
            .to_pydict()
        )
        assert out["k"] == [2, 3, 3]
        assert out["lv"] == ["y", "z", "z"]
        assert out["rv"] == [20, 30, 31]

    def test_join_with_residual(self, tmp_session):
        left = tmp_session.create_dataframe({"k": [1, 2], "a": [5, 6]})
        right = tmp_session.create_dataframe({"rk": [1, 2], "b": [100, 3]})
        out = left.join(
            right, (left["k"] == right["rk"]) & (col("b") < col("a") * 10)
        ).to_pydict()
        assert out["k"] == [2]

    def test_union(self, tmp_session):
        a = tmp_session.create_dataframe({"x": [1, 2]})
        b = tmp_session.create_dataframe({"x": [3]})
        assert a.union(b).to_pydict()["x"] == [1, 2, 3]

    def test_with_column(self, sample_df):
        out = sample_df.with_column("double_qty", col("qty") * 2).to_pydict()
        assert out["double_qty"] == [20, 40, 60, 80, 100, 120]

    def test_count(self, sample_df):
        assert sample_df.filter(col("cat") == "a").count() == 3

    def test_schema_and_columns(self, sample_df):
        assert sample_df.columns == ["id", "qty", "price", "cat"]
        assert sample_df.schema.field("price").dtype == "float64"

    def test_csv_reader(self, tmp_session, tmp_path):
        (tmp_path / "c").mkdir()
        (tmp_path / "c" / "d.csv").write_text("a,b\n1,p\n2,q\n")
        df = tmp_session.read.csv(str(tmp_path / "c"))
        assert df.filter(col("a") == 2).to_pydict()["b"] == ["q"]

    def test_reader_skips_metadata_dirs(self, tmp_session, tmp_path):
        root = tmp_path / "src"
        cio.write_parquet(ColumnBatch.from_pydict({"a": [1]}), str(root / "p.parquet"))
        (root / "_hyperspace_log").mkdir()
        (root / "_hyperspace_log" / "0").write_text("{}")
        (root / "_SUCCESS").write_text("")
        df = tmp_session.read.parquet(str(root))
        assert df.count() == 1

    def test_enable_disable_hyperspace(self, tmp_session):
        assert not tmp_session.is_hyperspace_enabled()
        tmp_session.enable_hyperspace()
        assert tmp_session.is_hyperspace_enabled()
        tmp_session.disable_hyperspace()
        assert not tmp_session.is_hyperspace_enabled()



class TestTopKAndDenseGrouping:
    """Fast paths must be invisible: identical results to the exact paths."""

    def test_topk_matches_full_sort(self, tmp_session):
        import numpy as np

        rng = np.random.default_rng(3)
        n = 20000
        df = tmp_session.create_dataframe(
            {"a": rng.integers(0, 1000, n).tolist(), "b": rng.uniform(size=n).tolist()}
        )
        topk = df.sort("b", ascending=False).limit(10).to_pydict()
        full = df.sort("b", ascending=False).to_pydict()
        assert topk["b"] == full["b"][:10]

    def test_topk_with_heavy_ties_falls_back(self, tmp_session):
        # primary key constant: boundary ties exceed the candidate buffer
        n = 20000
        df = tmp_session.create_dataframe(
            {"a": [7] * n, "b": list(range(n))}
        )
        out = df.sort("a", "b").limit(5).to_pydict()
        assert out["b"] == [0, 1, 2, 3, 4]

    def test_dense_int_grouping_matches(self, tmp_session):
        import numpy as np

        rng = np.random.default_rng(4)
        n = 30000
        keys = rng.integers(0, n // 2, n).tolist()  # dense domain
        vals = rng.uniform(size=n).tolist()
        df = tmp_session.create_dataframe({"k": keys, "v": vals})
        out = df.group_by("k").agg(Sum(col("v")).alias("s"), Count(lit(1)).alias("n")).sort("k").to_pydict()
        import collections

        sums = collections.defaultdict(float)
        counts = collections.defaultdict(int)
        for k, v in zip(keys, vals):
            sums[k] += v
            counts[k] += 1
        ks = sorted(sums)
        assert out["k"] == ks
        assert np.allclose(out["s"], [sums[k] for k in ks])
        assert out["n"] == [counts[k] for k in ks]

    def test_sparse_int_grouping_matches(self, tmp_session):
        # sparse domain (max >> n): must route through np.unique, stay correct
        keys = [10**9, 5, 10**9, 42]
        df = tmp_session.create_dataframe({"k": keys, "v": [1.0, 2.0, 3.0, 4.0]})
        out = df.group_by("k").agg(Sum(col("v")).alias("s")).sort("k").to_pydict()
        assert out["k"] == [5, 42, 10**9]
        assert out["s"] == [2.0, 4.0, 4.0]


    def test_duplicate_dictionary_groups_by_value(self, tmp_session):
        """A dictionary with the same value under two codes must still group
        by VALUE (falls back to the decode path)."""
        import numpy as np

        from hyperspace_tpu.columnar.table import Column, ColumnBatch
        from hyperspace_tpu.plan.dataframe import DataFrame
        from hyperspace_tpu.plan.nodes import InMemoryScan

        dup = Column(np.array([0, 1, 2, 0], dtype=np.int32), "string", None, ["a", "b", "a"])
        batch = ColumnBatch({"k": dup, "v": Column.from_values([1.0, 2.0, 3.0, 4.0])})
        df = DataFrame(tmp_session, InMemoryScan(batch))
        out = df.group_by("k").agg(Sum(col("v")).alias("s")).sort("k").to_pydict()
        assert out == {"k": ["a", "b"], "s": [8.0, 2.0]}


class TestGlobbing:
    def test_glob_roots_expand(self, tmp_session, tmp_path):
        for y in (2020, 2021):
            cio.write_parquet(
                ColumnBatch.from_pydict({"a": [y]}),
                str(tmp_path / f"y{y}" / "p.parquet"),
            )
        df = tmp_session.read.parquet(str(tmp_path / "y*"))
        assert sorted(df.to_pydict()["a"]) == [2020, 2021]

    def test_glob_no_match_errors(self, tmp_session, tmp_path):
        from hyperspace_tpu.exceptions import HyperspaceError
        import pytest as _pytest

        with _pytest.raises(HyperspaceError, match="matched nothing"):
            tmp_session.read.parquet(str(tmp_path / "nope*"))

    def test_declared_pattern_validated(self, tmp_session, tmp_path):
        from hyperspace_tpu.exceptions import HyperspaceError
        import pytest as _pytest

        cio.write_parquet(
            ColumnBatch.from_pydict({"a": [1]}), str(tmp_path / "data" / "p.parquet")
        )
        # matching declaration passes
        df = tmp_session.read.option(
            "globbingPattern", str(tmp_path / "dat*")
        ).parquet(str(tmp_path / "data"))
        assert df.count() == 1
        # non-matching declaration rejected
        with _pytest.raises(HyperspaceError, match="does not match"):
            tmp_session.read.option(
                "globbingPattern", str(tmp_path / "other*")
            ).parquet(str(tmp_path / "data"))


    def test_literal_bracket_path_loads(self, tmp_session, tmp_path):
        # a directory literally named with brackets must still load
        root = tmp_path / "data[1]"
        cio.write_parquet(ColumnBatch.from_pydict({"a": [7]}), str(root / "p.parquet"))
        df = tmp_session.read.parquet(str(root))
        assert df.to_pydict()["a"] == [7]

    def test_declared_pattern_validates_glob_roots(self, tmp_session, tmp_path):
        from hyperspace_tpu.exceptions import HyperspaceError
        import pytest as _pytest

        cio.write_parquet(ColumnBatch.from_pydict({"a": [1]}), str(tmp_path / "g1" / "p.parquet"))
        # declared pattern that does NOT cover the expanded glob roots
        with _pytest.raises(HyperspaceError, match="does not match"):
            tmp_session.read.option(
                "globbingPattern", str(tmp_path / "other*")
            ).parquet(str(tmp_path / "g*"))

    def test_star_does_not_cross_separators(self, tmp_session, tmp_path):
        from hyperspace_tpu.exceptions import HyperspaceError
        import pytest as _pytest

        deep = tmp_path / "a" / "b"
        cio.write_parquet(ColumnBatch.from_pydict({"x": [1]}), str(deep / "p.parquet"))
        with _pytest.raises(HyperspaceError, match="does not match"):
            tmp_session.read.option(
                "globbingPattern", str(tmp_path / "*")
            ).parquet(str(deep))

    def test_namespaced_globbing_key_honored(self, tmp_session, tmp_path):
        from hyperspace_tpu.exceptions import HyperspaceError
        import pytest as _pytest

        cio.write_parquet(ColumnBatch.from_pydict({"a": [1]}), str(tmp_path / "d" / "p.parquet"))
        with _pytest.raises(HyperspaceError, match="does not match"):
            tmp_session.read.option(
                "hyperspace.source.globbingPattern", str(tmp_path / "zzz*")
            ).parquet(str(tmp_path / "d"))


    def test_glob_skips_metadata_entries(self, tmp_session, tmp_path):
        cio.write_parquet(ColumnBatch.from_pydict({"a": [1]}), str(tmp_path / "gd" / "p.parquet"))
        (tmp_path / "_hyperspace_log").mkdir()
        (tmp_path / "_hyperspace_log" / "0").write_text("{}")
        (tmp_path / "_SUCCESS").write_text("")
        df = tmp_session.read.parquet(str(tmp_path / "*"))
        assert df.to_pydict() == {"a": [1]}

    def test_literal_path_wins_over_glob_sibling(self, tmp_session, tmp_path):
        # both data1 and data[1] exist; reading data[1] must hit the literal dir
        cio.write_parquet(ColumnBatch.from_pydict({"a": [111]}), str(tmp_path / "data1" / "p.parquet"))
        cio.write_parquet(ColumnBatch.from_pydict({"a": [222]}), str(tmp_path / "data[1]" / "p.parquet"))
        df = tmp_session.read.parquet(str(tmp_path / "data[1]"))
        assert df.to_pydict() == {"a": [222]}

    def test_comma_separated_declared_patterns(self, tmp_session, tmp_path):
        cio.write_parquet(ColumnBatch.from_pydict({"a": [1]}), str(tmp_path / "y2020" / "p.parquet"))
        cio.write_parquet(ColumnBatch.from_pydict({"a": [2]}), str(tmp_path / "y2021" / "p.parquet"))
        pat = f"{tmp_path}/y2020*,{tmp_path}/y2021*"
        df = tmp_session.read.option("globbingPattern", pat).parquet(str(tmp_path / "y*"))
        assert sorted(df.to_pydict()["a"]) == [1, 2]

    def test_refresh_picks_up_new_glob_dir(self, tmp_session, tmp_path):
        from hyperspace_tpu import CoveringIndexConfig, Hyperspace

        cio.write_parquet(ColumnBatch.from_pydict({"k": [1], "v": [1.0]}), str(tmp_path / "p2020" / "f.parquet"))
        hs = Hyperspace(tmp_session)
        df = tmp_session.read.parquet(str(tmp_path / "p*"))
        hs.create_index(df, CoveringIndexConfig("gidx", ["k"], ["v"]))
        # a whole new directory matching the glob appears after the build
        cio.write_parquet(ColumnBatch.from_pydict({"k": [2], "v": [2.0]}), str(tmp_path / "p2021" / "f.parquet"))
        hs.refresh_index("gidx", "full")
        entry = hs.get_index("gidx")
        batch = cio.read_parquet(entry.content.files())
        assert sorted(batch.to_pydict()["k"]) == [1, 2]


    def test_reader_reuse_does_not_leak_glob(self, tmp_session, tmp_path):
        cio.write_parquet(ColumnBatch.from_pydict({"a": [1]}), str(tmp_path / "gx" / "p.parquet"))
        cio.write_parquet(ColumnBatch.from_pydict({"a": [2]}), str(tmp_path / "lit" / "p.parquet"))
        r = tmp_session.read
        r.parquet(str(tmp_path / "g*"))
        df = r.parquet(str(tmp_path / "lit"))
        from hyperspace_tpu.plan.nodes import FileScan

        scan = [n for n in df.plan.preorder() if isinstance(n, FileScan)][0]
        assert "globPaths" not in scan.options

    def test_declared_pattern_with_literal_root_enables_refresh_pickup(self, tmp_session, tmp_path):
        from hyperspace_tpu import CoveringIndexConfig, Hyperspace

        cio.write_parquet(ColumnBatch.from_pydict({"k": [1], "v": [1.0]}), str(tmp_path / "y2020" / "f.parquet"))
        hs = Hyperspace(tmp_session)
        df = tmp_session.read.option("globbingPattern", str(tmp_path / "y*")).parquet(str(tmp_path / "y2020"))
        hs.create_index(df, CoveringIndexConfig("dgx", ["k"], ["v"]))
        cio.write_parquet(ColumnBatch.from_pydict({"k": [2], "v": [2.0]}), str(tmp_path / "y2021" / "f.parquet"))
        hs.refresh_index("dgx", "full")
        batch = cio.read_parquet(hs.get_index("dgx").content.files())
        assert sorted(batch.to_pydict()["k"]) == [1, 2]

    def test_wildcard_never_matches_hidden_mid_segment(self, tmp_session, tmp_path):
        cio.write_parquet(ColumnBatch.from_pydict({"a": [1]}), str(tmp_path / "real" / "data" / "p.parquet"))
        cio.write_parquet(ColumnBatch.from_pydict({"a": [99]}), str(tmp_path / "_staging" / "data" / "p.parquet"))
        df = tmp_session.read.parquet(str(tmp_path / "*" / "data"))
        assert df.to_pydict() == {"a": [1]}

    def test_comma_in_directory_name_roundtrips(self, tmp_session, tmp_path):
        from hyperspace_tpu import CoveringIndexConfig, Hyperspace

        root = tmp_path / "da,ta2020"
        cio.write_parquet(ColumnBatch.from_pydict({"k": [1], "v": [1.0]}), str(root / "f.parquet"))
        hs = Hyperspace(tmp_session)
        df = tmp_session.read.parquet(str(tmp_path / "da,ta*"))
        hs.create_index(df, CoveringIndexConfig("cgx", ["k"], ["v"]))
        hs.refresh_index("cgx", "full")  # NoChanges swallowed; must not crash
        assert hs.get_index("cgx").state == "ACTIVE"


    def test_refresh_respects_declared_scope(self, tmp_session, tmp_path):
        """With glob roots AND a narrower declared pattern, refresh expands
        the DECLARED scope only (regression: out-of-scope data absorbed)."""
        from hyperspace_tpu import CoveringIndexConfig, Hyperspace

        cio.write_parquet(ColumnBatch.from_pydict({"k": [1], "v": [1.0]}), str(tmp_path / "y2020" / "f.parquet"))
        hs = Hyperspace(tmp_session)
        df = tmp_session.read.option("globbingPattern", str(tmp_path / "y2020*")).parquet(str(tmp_path / "y*"))
        hs.create_index(df, CoveringIndexConfig("sc", ["k"], ["v"]))
        # out-of-scope dir appears (matches y* but not y2020*)
        cio.write_parquet(ColumnBatch.from_pydict({"k": [2], "v": [2.0]}), str(tmp_path / "y2021" / "f.parquet"))
        # in-scope dir appears too
        cio.write_parquet(ColumnBatch.from_pydict({"k": [3], "v": [3.0]}), str(tmp_path / "y2020b" / "f.parquet"))
        hs.refresh_index("sc", "full")
        batch = cio.read_parquet(hs.get_index("sc").content.files())
        assert sorted(batch.to_pydict()["k"]) == [1, 3]  # 2 stays excluded

    def test_comma_in_declared_pattern_path(self, tmp_session, tmp_path):
        root = tmp_path / "a,b"
        cio.write_parquet(ColumnBatch.from_pydict({"x": [1]}), str(root / "y2020" / "f.parquet"))
        df = tmp_session.read.option(
            "globbingPattern", str(root / "y*")
        ).parquet(str(root / "y2020"))
        assert df.to_pydict() == {"x": [1]}


    def test_refresh_tolerates_empty_scope_component(self, tmp_session, tmp_path):
        from hyperspace_tpu import CoveringIndexConfig, Hyperspace

        cio.write_parquet(ColumnBatch.from_pydict({"k": [1], "v": [1.0]}), str(tmp_path / "y2020" / "f.parquet"))
        hs = Hyperspace(tmp_session)
        # second declared component matches nothing yet
        pat = f"{tmp_path}/y2020*,{tmp_path}/z*"
        df = tmp_session.read.option("globbingPattern", pat).parquet(str(tmp_path / "y*"))
        hs.create_index(df, CoveringIndexConfig("es", ["k"], ["v"]))
        hs.refresh_index("es", "full")  # must not crash on the empty z* scope
        # when z* data appears later, refresh picks it up
        cio.write_parquet(ColumnBatch.from_pydict({"k": [5], "v": [5.0]}), str(tmp_path / "znew" / "f.parquet"))
        hs.refresh_index("es", "full")
        batch = cio.read_parquet(hs.get_index("es").content.files())
        assert sorted(batch.to_pydict()["k"]) == [1, 5]
