"""AggregateIndexRule tests: grouped aggregation over a bare scan rewrites
to a bucketed covering-index scan and aggregates per bucket."""

import numpy as np
import pytest

from hyperspace_tpu import CoveringIndexConfig, Hyperspace
from hyperspace_tpu.columnar import io as cio
from hyperspace_tpu.columnar.table import ColumnBatch
from hyperspace_tpu.plan import col, lit, Avg, Count, Sum
from hyperspace_tpu.plan.nodes import FileScan


def index_scans(plan):
    return [n for n in plan.preorder() if isinstance(n, FileScan) and n.index_info]


@pytest.fixture()
def env(tmp_session, tmp_path):
    rng = np.random.default_rng(23)
    n = 10000
    cio.write_parquet(
        ColumnBatch.from_pydict(
            {
                "k": rng.integers(0, 300, n).tolist(),
                "v": rng.uniform(size=n).tolist(),
                "w": rng.uniform(size=n).tolist(),
            }
        ),
        str(tmp_path / "t" / "p.parquet"),
    )
    hs = Hyperspace(tmp_session)
    df = tmp_session.read.parquet(str(tmp_path / "t"))
    hs.create_index(df, CoveringIndexConfig("aggidx", ["k"], ["v"]))
    return tmp_session, hs, tmp_path


class TestAggregateIndexRule:
    def test_group_by_indexed_col_rewrites(self, env):
        session, hs, tmp = env
        q = lambda d: (
            d.select("k", "v").group_by("k").agg(Avg(col("v")).alias("a")).sort("k")
        )
        df = session.read.parquet(str(tmp / "t"))
        expected = q(df).to_pydict()
        session.enable_hyperspace()
        df2 = session.read.parquet(str(tmp / "t"))
        plan = q(df2).optimized_plan()
        assert index_scans(plan) and index_scans(plan)[0].index_info.index_name == "aggidx"
        got = q(df2).to_pydict()
        assert got["k"] == expected["k"]
        assert np.allclose(got["a"], expected["a"])

    def test_uncovered_agg_column_not_rewritten(self, env):
        session, hs, tmp = env
        session.enable_hyperspace()
        df2 = session.read.parquet(str(tmp / "t"))
        # w is not covered by the index
        plan = (
            df2.select("k", "w").group_by("k").agg(Sum(col("w")).alias("s")).optimized_plan()
        )
        assert not index_scans(plan)

    def test_group_without_indexed_col_not_rewritten(self, env):
        session, hs, tmp = env
        session.enable_hyperspace()
        df2 = session.read.parquet(str(tmp / "t"))
        # grouping only by v: the bucket key k is not in the group keys
        plan = (
            df2.select("k", "v").group_by("v").agg(Count(lit(1)).alias("n")).optimized_plan()
        )
        assert not index_scans(plan)

    def test_filter_rule_wins_over_agg_rule(self, env):
        session, hs, tmp = env
        session.enable_hyperspace()
        df2 = session.read.parquet(str(tmp / "t"))
        # both rules apply; filter rule's higher score keeps the rewrite legal
        q = (
            df2.filter(col("k") == 5)
            .select("k", "v")
            .group_by("k")
            .agg(Sum(col("v")).alias("s"))
        )
        plan = q.optimized_plan()
        assert index_scans(plan)
        session.disable_hyperspace()
        expected = q.to_pydict()
        session.enable_hyperspace()
        got = q.to_pydict()
        assert got["k"] == expected["k"] and np.allclose(got["s"], expected["s"])


    def test_all_buckets_filtered_empty(self, env):
        session, hs, tmp = env
        session.enable_hyperspace()
        df2 = session.read.parquet(str(tmp / "t"))
        out = (
            df2.select("k", "v")
            .filter(col("v") > 10.0)  # uniform(0,1): nothing matches
            .group_by("k")
            .agg(Sum(col("v")).alias("s"))
            .to_pydict()
        )
        assert out == {"k": [], "s": []}
