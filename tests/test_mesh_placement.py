"""Mesh-sharded scale-out execution tests (parallel/placement.py).

Three layers pin the mesh contract:

* the skew-aware placer as a pure function — largest-first bin packing with
  fair-share splitting of hot buckets, deterministic fallback round-robin
  for stats-starved buckets, and strict determinism on fixed inputs;
* end-to-end bit-identity — with the conftest's 8 forced host devices,
  ``HYPERSPACE_MESH=1`` must produce float.hex-identical results to
  ``HYPERSPACE_MESH=0`` on the skewed bucketed-join fixtures and the TPC-H
  join queries (placement moves work, never changes answers);
* per-device memory ledgers — each mesh ordinal holds its own
  ``BudgetAccountant``, a saturated device parks/spills without stalling
  its neighbors, and every ledger conserves exactly (sum of releases ==
  sum of admissions; zero held at quiescence).
"""

import types

import numpy as np
import pytest

from hyperspace_tpu import CoveringIndexConfig, Hyperspace
from hyperspace_tpu import constants as C
from hyperspace_tpu.columnar import io as cio
from hyperspace_tpu.columnar.table import ColumnBatch
from hyperspace_tpu.parallel import placement
from hyperspace_tpu.plan import Count, Max, Min, Sum, col
from hyperspace_tpu.serve import budget as serve_budget
from hyperspace_tpu.telemetry.metrics import REGISTRY

MB = 2**20


def hex_rows(d: dict) -> str:
    """Bit-exact repr: floats rendered via .hex() so f32/f64 accumulation
    differences can never hide behind printing."""
    return repr(
        {
            k: [x.hex() if isinstance(x, float) else x for x in v]
            for k, v in d.items()
        }
    )


# ---------------------------------------------------------------------------
# placer units: planted skewed stats, no jax involved (devices are opaque)
# ---------------------------------------------------------------------------


DEV8 = [f"dev{i}" for i in range(8)]


class TestPlacerBinPacking:
    def test_uniform_stats_spread_over_all_devices(self):
        est = {b: 10 * MB for b in range(8)}
        p = placement.plan_bucket_placement(est, devices=DEV8)
        ordinals = {p.ordinal_for(b) for b in est}
        assert ordinals == set(range(8))
        assert REGISTRY.gauge("mesh.placement.bytes_imbalance_ratio").value == 1.0

    def test_hot_bucket_splits_across_devices(self):
        """One bucket carrying 30% of the bytes exceeds the per-device fair
        share, so the placer splits it into ranges its chunks rotate
        through — without the split one device would hold 30% of the work
        (imbalance ~2.4x on 8 devices)."""
        est = {0: 30 * MB}
        est.update({b: 10 * MB for b in range(1, 8)})
        p = placement.plan_bucket_placement(est, devices=DEV8)
        hot_ordinals = {p.ordinal_for(0, chunk=c) for c in range(8)}
        assert len(hot_ordinals) >= 2, "hot bucket must span devices"
        assert REGISTRY.gauge("mesh.placement.devices_used").value >= 4
        assert REGISTRY.gauge("mesh.placement.bytes_imbalance_ratio").value < 2.0

    def test_placement_deterministic(self):
        rng = np.random.default_rng(7)
        est = {b: int(rng.integers(1, 50)) * MB for b in range(16)}
        a = placement.plan_bucket_placement(dict(est), devices=DEV8)
        b = placement.plan_bucket_placement(dict(est), devices=DEV8)
        for bucket in range(16):
            for chunk in range(4):
                assert a.ordinal_for(bucket, chunk) == b.ordinal_for(
                    bucket, chunk
                )

    def test_unseen_bucket_round_robins_and_counts_fallback(self):
        p = placement.plan_bucket_placement({0: MB, 1: MB}, devices=DEV8)
        before = REGISTRY.counter("mesh.placement.fallbacks").value
        got = [p.ordinal_for(99, chunk=c) for c in range(3)]
        assert got == [(99 + c) % 8 for c in range(3)]
        assert REGISTRY.counter("mesh.placement.fallbacks").value == before + 3

    def test_offset_rotates_packing(self):
        """The query's home device breaks load ties, so two concurrent
        queries with different homes pack onto different devices instead
        of both starting at ordinal 0."""
        est = {0: MB}
        p0 = placement.plan_bucket_placement(dict(est), devices=DEV8, offset=0)
        p3 = placement.plan_bucket_placement(dict(est), devices=DEV8, offset=3)
        assert p0.ordinal_for(0) == 0
        assert p3.ordinal_for(0) == 3

    def test_single_device_mesh_is_none(self):
        assert placement.plan_bucket_placement({0: MB}, devices=["d0"]) is None

    def test_chunk_placer_balances_greedily(self):
        cp = placement.ChunkPlacer(DEV8[:4])
        ordinals = [cp.next(100)[0] for _ in range(8)]
        assert sorted(ordinals) == [0, 0, 1, 1, 2, 2, 3, 3]
        # deterministic: a fresh placer over the same sizes places the same
        cp2 = placement.ChunkPlacer(DEV8[:4])
        assert [cp2.next(100)[0] for _ in range(8)] == ordinals

    def test_mesh_off_means_no_devices(self, monkeypatch):
        monkeypatch.delenv("HYPERSPACE_MESH", raising=False)
        assert placement.mesh_devices() == []
        assert placement.mesh_size() == 0
        assert placement.chunk_placer() is None

    def test_mesh_on_sees_forced_host_devices(self, monkeypatch):
        monkeypatch.setenv("HYPERSPACE_MESH", "1")
        assert placement.mesh_size() >= 2
        monkeypatch.setenv("HYPERSPACE_MESH_DEVICES", "2")
        assert placement.mesh_size() == 2


# ---------------------------------------------------------------------------
# end-to-end bit-identity: mesh on vs off on the forced 8-device CPU mesh
# ---------------------------------------------------------------------------


def _write_sides(tmp_path, left, right):
    cio.write_parquet(
        ColumnBatch.from_pydict(left), str(tmp_path / "l" / "l.parquet")
    )
    cio.write_parquet(
        ColumnBatch.from_pydict(right), str(tmp_path / "r" / "r.parquet")
    )


@pytest.fixture()
def skew_env(tmp_session, tmp_path):
    """Heavily skewed left side (40% of rows on ONE hot key) over 8
    buckets: the shape where naive per-bucket placement pins one device
    and the fair-share split must spread the hot bucket."""
    rng = np.random.default_rng(101)
    n = 24_000
    k = rng.integers(0, 400, n)
    k[: int(n * 0.4)] = 7
    left = {"k": k.tolist(), "p": rng.uniform(0, 100, n).tolist()}
    right = {"rk": list(range(0, 200)), "w": rng.uniform(size=200).tolist()}
    _write_sides(tmp_path, left, right)
    tmp_session.set_conf(C.INDEX_NUM_BUCKETS, 8)
    hs = Hyperspace(tmp_session)
    hs.create_index(
        tmp_session.read.parquet(str(tmp_path / "l")),
        CoveringIndexConfig("jl", ["k"], ["p"]),
    )
    hs.create_index(
        tmp_session.read.parquet(str(tmp_path / "r")),
        CoveringIndexConfig("jr", ["rk"], ["w"]),
    )
    return tmp_session, tmp_path


def _plain_q(session, tmp_path):
    l = session.read.parquet(str(tmp_path / "l")).select("k", "p")
    r = session.read.parquet(str(tmp_path / "r")).select("rk", "w")
    return l.join(r, col("k") == col("rk")).select("k", "p", "w")


def _agg_q(session, tmp_path):
    l = session.read.parquet(str(tmp_path / "l")).select("k", "p")
    r = session.read.parquet(str(tmp_path / "r")).select("rk", "w")
    return (
        l.join(r, col("k") == col("rk"))
        .group_by("k")
        .agg(Sum(col("p")).alias("s"), Count(col("p")).alias("c"),
             Min(col("w")).alias("mn"), Max(col("w")).alias("mx"))
    )


def _mesh_vs_off(session, tmp_path, q, monkeypatch):
    session.enable_hyperspace()
    session.set_conf(C.EXEC_TPU_ENABLED, True)
    try:
        monkeypatch.setenv("HYPERSPACE_MESH", "0")
        off = hex_rows(q(session, tmp_path).to_pydict())
        monkeypatch.setenv("HYPERSPACE_MESH", "1")
        on = hex_rows(q(session, tmp_path).to_pydict())
    finally:
        session.set_conf(C.EXEC_TPU_ENABLED, False)
        session.disable_hyperspace()
    return off, on


class TestMeshBitIdentity:
    def test_plain_join_bit_identical(self, skew_env, monkeypatch):
        session, tmp_path = skew_env
        buckets0 = REGISTRY.counter("mesh.placement.buckets").value
        off, on = _mesh_vs_off(session, tmp_path, _plain_q, monkeypatch)
        assert on == off
        assert REGISTRY.counter("mesh.placement.buckets").value > buckets0

    def test_fused_agg_bit_identical_and_balanced(self, skew_env, monkeypatch):
        session, tmp_path = skew_env
        off, on = _mesh_vs_off(session, tmp_path, _agg_q, monkeypatch)
        assert on == off
        # the skew fixture is the acceptance shape: work must actually
        # spread (>= 4 of 8 devices) and the hot bucket must not pin the
        # balance past 2x
        assert REGISTRY.gauge("mesh.placement.devices_used").value >= 4
        assert REGISTRY.gauge("mesh.placement.bytes_imbalance_ratio").value < 2.0

    def test_mesh_emits_usage_event(self, skew_env, monkeypatch):
        session, tmp_path = skew_env
        before = REGISTRY.counter("rules.usage.MeshBucketedExec").value
        _mesh_vs_off(session, tmp_path, _plain_q, monkeypatch)
        assert REGISTRY.counter("rules.usage.MeshBucketedExec").value > before


@pytest.fixture(scope="module")
def tpch_env(tmp_path_factory):
    from hyperspace_tpu.benchmark import generate_tpch, tpch_indexes
    from hyperspace_tpu.session import HyperspaceSession

    root = str(tmp_path_factory.mktemp("tpch_mesh"))
    session = HyperspaceSession(warehouse_dir=root)
    generate_tpch(root, rows_lineitem=30_000, seed=1)
    hs = Hyperspace(session)
    tpch_indexes(session, hs, root)
    return session, root


class TestMeshTPCH:
    @pytest.mark.parametrize("name", ["q3", "q10", "q17"])
    def test_tpch_bit_identical(self, tpch_env, name, monkeypatch):
        from hyperspace_tpu.benchmark import TPCH_QUERIES

        session, root = tpch_env
        q = TPCH_QUERIES[name]
        session.enable_hyperspace()
        session.set_conf(C.EXEC_TPU_ENABLED, True)
        try:
            monkeypatch.setenv("HYPERSPACE_MESH", "0")
            off = hex_rows(q(session, root).to_pydict())
            monkeypatch.setenv("HYPERSPACE_MESH", "1")
            on = hex_rows(q(session, root).to_pydict())
        finally:
            session.set_conf(C.EXEC_TPU_ENABLED, False)
            session.disable_hyperspace()
        assert on == off, f"{name} diverges under mesh placement"


# ---------------------------------------------------------------------------
# per-device memory ledgers
# ---------------------------------------------------------------------------


@pytest.fixture()
def small_device_budget(monkeypatch):
    monkeypatch.setenv("HYPERSPACE_DEVICE_BUDGET_MB", "1")
    serve_budget.reset_device_budget()
    yield 1 * MB
    monkeypatch.delenv("HYPERSPACE_DEVICE_BUDGET_MB", raising=False)
    serve_budget.reset_device_budget()


class TestPerDeviceLedgers:
    def test_registry_names_and_isolation(self, small_device_budget):
        a0 = serve_budget.device_budget()
        a3 = serve_budget.device_budget(3)
        assert a0 is serve_budget.device_budget(0)
        assert a3 is serve_budget.device_budget(3)
        assert a0 is not a3
        # ordinal 0 keeps the historical metric name; mesh ordinals suffix
        st = a3.state()
        assert serve_budget.device_budgets() == {0: a0, 3: a3}
        assert st["held_bytes"] == 0

    def test_ledger_conservation_across_devices(self, small_device_budget):
        from hyperspace_tpu.plan.join_memory import DeviceLedger

        ledger = DeviceLedger("t-conserve")
        try:
            ledger.admit(300_000, lambda: False, device=1)
            ledger.admit(400_000, lambda: False, device=2)
            ledger.admit(200_000, lambda: False, device=1)
            assert serve_budget.device_budget(1).held_bytes() == 500_000
            assert serve_budget.device_budget(2).held_bytes() == 400_000
            ledger.release(300_000, device=1)
            ledger.release(400_000, device=2)
            ledger.release(200_000, device=1)
            for acct in serve_budget.device_budgets().values():
                assert acct.held_bytes() == 0
                assert acct.check_consistency()
        finally:
            ledger.close()

    def test_saturated_device_parks_neighbors_proceed(
        self, small_device_budget
    ):
        """Filling device 1's ledger must not stall device 2: the park loop
        is per-accountant. The second admit on device 1 spills this join's
        own in-flight wave (the spill_one callback) and then proceeds."""
        from hyperspace_tpu.plan.join_memory import DeviceLedger

        budget = small_device_budget
        ledger = DeviceLedger("t-park")
        spilled = []

        def spill_one():
            if spilled:
                return False
            spilled.append(True)
            ledger.release(budget - 1024, device=1)
            return True

        try:
            ledger.admit(budget - 1024, spill_one, device=1)  # fills d1
            parks0 = REGISTRY.counter("join.spill.parks").value
            # a full neighbor never blocks d2: no park recorded
            ledger.admit(budget // 2, lambda: False, device=2)
            assert REGISTRY.counter("join.spill.parks").value == parks0
            # d1 over budget -> parks once, spills our wave, resumes
            ledger.admit(budget // 2, spill_one, device=1)
            assert REGISTRY.counter("join.spill.parks").value == parks0 + 1
            assert spilled
            assert (
                serve_budget.device_budget(1).held_bytes() == budget // 2
            )
            ledger.release(budget // 2, device=1)
            ledger.release(budget // 2, device=2)
            for acct in serve_budget.device_budgets().values():
                assert acct.held_bytes() == 0
        finally:
            ledger.close()

    def test_reset_clears_mesh_ordinals(self, small_device_budget):
        serve_budget.device_budget(5)
        assert 5 in serve_budget.device_budgets()
        serve_budget.reset_device_budget()
        assert set(serve_budget.device_budgets()) == {0}


# ---------------------------------------------------------------------------
# QoS home-device assignment
# ---------------------------------------------------------------------------


class TestSchedulerHomeDevice:
    def _scheduler(self):
        from hyperspace_tpu.serve.scheduler import QueryScheduler

        return QueryScheduler(max_concurrent=2, queue_depth=8)

    def _fake_active(self, homes, tenant="default"):
        return {
            i: types.SimpleNamespace(
                ctx=types.SimpleNamespace(device_home=h, tenant=tenant)
            )
            for i, h in enumerate(homes)
        }

    def test_home_none_with_mesh_off(self, monkeypatch):
        monkeypatch.delenv("HYPERSPACE_MESH", raising=False)
        sched = self._scheduler()
        try:
            assert sched._home_device_locked() is None
        finally:
            sched.shutdown(wait=True)

    def test_home_is_least_occupied_ordinal(self, monkeypatch):
        monkeypatch.setenv("HYPERSPACE_MESH", "1")
        sched = self._scheduler()
        try:
            n = 8
            sched._active = self._fake_active([0, 0, 1, 3])
            home = sched._home_device_locked()
            assert home == 2  # first zero-occupancy ordinal
            sched._active = self._fake_active(list(range(n)))
            assert sched._home_device_locked() == 0  # all equal: lowest wins
        finally:
            sched._active = {}
            sched.shutdown(wait=True)

    def test_submitted_query_gets_home(self, tmp_session, tmp_path, monkeypatch):
        monkeypatch.setenv("HYPERSPACE_MESH", "1")
        from hyperspace_tpu import serve

        cio.write_parquet(
            ColumnBatch.from_pydict({"a": [1, 2, 3]}),
            str(tmp_path / "t" / "t.parquet"),
        )
        df = tmp_session.read.parquet(str(tmp_path / "t")).select("a")
        sched = serve.QueryScheduler(max_concurrent=1, queue_depth=4)
        try:
            h = sched.submit_query(df, label="home-probe")
            h.result(timeout=60)
            assert h.ctx.device_home is not None
            assert 0 <= h.ctx.device_home < 8
        finally:
            sched.shutdown(wait=True)
