"""E2E query-rewrite tests: build real indexes, run real queries, assert the
plan was rewritten AND row-level results equal the unindexed execution
(ref: E2EHyperspaceRulesTest.scala:33-80 with QueryTest.checkAnswer).
"""

import os

import pytest

from hyperspace_tpu import CoveringIndexConfig, Hyperspace
from hyperspace_tpu import constants as C
from hyperspace_tpu.columnar import io as cio
from hyperspace_tpu.columnar.table import ColumnBatch
from hyperspace_tpu.plan import col, lit, Count, Sum
from hyperspace_tpu.plan.nodes import BucketUnion, FileScan, Union


def sort_pydict(d):
    keys = list(d.keys())
    rows = sorted(zip(*[d[k] for k in keys]), key=repr)
    return rows


def scans(plan):
    return [n for n in plan.preorder() if isinstance(n, FileScan)]


def index_scans(plan):
    return [n for n in scans(plan) if n.index_info is not None]


@pytest.fixture()
def env(tmp_session, tmp_path):
    n = 500
    left = {
        "k": [i % 50 for i in range(n)],
        "a": [float(i) for i in range(n)],
        # high-entropy strings so an index including 's' is measurably bigger
        "s": [f"group-{i}-{'x' * (i % 17)}" for i in range(n)],
    }
    right = {
        "rk": list(range(50)),
        "b": [i * 10.0 for i in range(50)],
    }
    cio.write_parquet(ColumnBatch.from_pydict(left), str(tmp_path / "left" / "l.parquet"))
    cio.write_parquet(ColumnBatch.from_pydict(right), str(tmp_path / "right" / "r.parquet"))
    hs = Hyperspace(tmp_session)
    return tmp_session, hs, tmp_path


class TestFilterIndexRule:
    def test_filter_query_rewritten_and_equal(self, env):
        session, hs, tmp = env
        df = session.read.parquet(str(tmp / "left"))
        hs.create_index(df, CoveringIndexConfig("fidx", ["k"], ["a"]))

        query = lambda d: d.filter(col("k") == 7).select("k", "a")
        expected = query(df).to_pydict()

        session.enable_hyperspace()
        df2 = session.read.parquet(str(tmp / "left"))
        rewritten = query(df2).optimized_plan()
        assert len(index_scans(rewritten)) == 1
        info = index_scans(rewritten)[0].index_info
        assert info.index_name == "fidx"
        got = query(df2).to_pydict()
        assert sort_pydict(got) == sort_pydict(expected)

    def test_filter_not_applied_when_disabled(self, env):
        session, hs, tmp = env
        df = session.read.parquet(str(tmp / "left"))
        hs.create_index(df, CoveringIndexConfig("fidx", ["k"], ["a"]))
        session.disable_hyperspace()
        plan = df.filter(col("k") == 7).select("k", "a").optimized_plan()
        assert not index_scans(plan)

    def test_filter_without_first_indexed_col_not_applied(self, env):
        session, hs, tmp = env
        df = session.read.parquet(str(tmp / "left"))
        hs.create_index(df, CoveringIndexConfig("fidx", ["k"], ["a"]))
        session.enable_hyperspace()
        plan = df.filter(col("a") > 100.0).select("k", "a").optimized_plan()
        assert not index_scans(plan)  # 'a' is included, not leading indexed

    def test_filter_missing_column_not_applied(self, env):
        session, hs, tmp = env
        df = session.read.parquet(str(tmp / "left"))
        hs.create_index(df, CoveringIndexConfig("fidx", ["k"], ["a"]))
        session.enable_hyperspace()
        # query needs 's' which the index does not cover
        plan = df.filter(col("k") == 1).select("k", "s").optimized_plan()
        assert not index_scans(plan)

    def test_source_data_change_invalidates(self, env):
        session, hs, tmp = env
        df = session.read.parquet(str(tmp / "left"))
        hs.create_index(df, CoveringIndexConfig("fidx", ["k"], ["a"]))
        # append a file -> signature mismatch, no hybrid scan
        cio.write_parquet(
            ColumnBatch.from_pydict({"k": [999], "a": [1.0], "s": ["x"]}),
            str(tmp / "left" / "l2.parquet"),
        )
        session.enable_hyperspace()
        df2 = session.read.parquet(str(tmp / "left"))
        plan = df2.filter(col("k") == 7).select("k", "a").optimized_plan()
        assert not index_scans(plan)

    def test_smallest_index_wins(self, env):
        session, hs, tmp = env
        df = session.read.parquet(str(tmp / "left"))
        hs.create_index(df, CoveringIndexConfig("big", ["k"], ["a", "s"]))
        hs.create_index(df, CoveringIndexConfig("small", ["k"], ["a"]))
        session.enable_hyperspace()
        df2 = session.read.parquet(str(tmp / "left"))
        plan = df2.filter(col("k") == 3).select("k", "a").optimized_plan()
        assert index_scans(plan)[0].index_info.index_name == "small"

    def test_aggregate_over_rewritten_filter(self, env):
        session, hs, tmp = env
        df = session.read.parquet(str(tmp / "left"))
        hs.create_index(df, CoveringIndexConfig("fidx", ["k"], ["a"]))
        session.enable_hyperspace()
        df2 = session.read.parquet(str(tmp / "left"))
        q = lambda d: (
            d.filter(col("k") == 7)
            .select("k", "a")
            .agg(Sum(col("a")).alias("s"), Count(lit(1)).alias("n"))
        )
        session.disable_hyperspace()
        expected = q(df).to_pydict()
        session.enable_hyperspace()
        assert q(df2).to_pydict() == expected


class TestJoinIndexRule:
    def _indexes(self, session, hs, tmp):
        ldf = session.read.parquet(str(tmp / "left"))
        rdf = session.read.parquet(str(tmp / "right"))
        hs.create_index(ldf, CoveringIndexConfig("lidx", ["k"], ["a"]))
        hs.create_index(rdf, CoveringIndexConfig("ridx", ["rk"], ["b"]))
        return ldf, rdf

    def test_join_rewritten_and_equal(self, env):
        session, hs, tmp = env
        ldf, rdf = self._indexes(session, hs, tmp)
        q = lambda l, r: l.select("k", "a").join(
            r.select("rk", "b"), col("k") == col("rk")
        ).select("k", "a", "b")
        expected = q(ldf, rdf).to_pydict()

        session.enable_hyperspace()
        l2 = session.read.parquet(str(tmp / "left"))
        r2 = session.read.parquet(str(tmp / "right"))
        plan = q(l2, r2).optimized_plan()
        idx = index_scans(plan)
        assert {s.index_info.index_name for s in idx} == {"lidx", "ridx"}
        # both sides carry the bucket spec => shuffle-free merge join
        assert all(s.bucket_spec is not None for s in idx)
        got = q(l2, r2).to_pydict()
        assert sort_pydict(got) == sort_pydict(expected)

    def test_join_beats_filter_alone(self, env):
        session, hs, tmp = env
        ldf, rdf = self._indexes(session, hs, tmp)
        session.enable_hyperspace()
        l2 = session.read.parquet(str(tmp / "left"))
        r2 = session.read.parquet(str(tmp / "right"))
        # JoinIndexRule (score 140) should win over per-side NoOp
        plan = (
            l2.select("k", "a")
            .join(r2.select("rk", "b"), col("k") == col("rk"))
            .optimized_plan()
        )
        assert len(index_scans(plan)) == 2

    def test_join_requires_indexed_eq_joinkeys(self, env):
        session, hs, tmp = env
        ldf = session.read.parquet(str(tmp / "left"))
        rdf = session.read.parquet(str(tmp / "right"))
        # left index on wrong column set
        hs.create_index(ldf, CoveringIndexConfig("lidx", ["s"], ["k", "a"]))
        hs.create_index(rdf, CoveringIndexConfig("ridx", ["rk"], ["b"]))
        session.enable_hyperspace()
        plan = (
            ldf.select("k", "a")
            .join(rdf.select("rk", "b"), col("k") == col("rk"))
            .optimized_plan()
        )
        assert len(index_scans(plan)) == 0


class TestHybridScan:
    def test_appended_files_union(self, env):
        session, hs, tmp = env
        df = session.read.parquet(str(tmp / "left"))
        hs.create_index(df, CoveringIndexConfig("fidx", ["k"], ["a"]))
        # append small file (under ratio threshold)
        cio.write_parquet(
            ColumnBatch.from_pydict({"k": [7, 8], "a": [1111.0, 2222.0], "s": ["x", "y"]}),
            str(tmp / "left" / "l2.parquet"),
        )
        session.enable_hyperspace()
        session.set_conf(C.HYBRID_SCAN_ENABLED, True)
        df2 = session.read.parquet(str(tmp / "left"))
        q = lambda d: d.filter(col("k") == 7).select("k", "a")
        plan = q(df2).optimized_plan()
        assert len(index_scans(plan)) == 1
        assert any(isinstance(n, Union) for n in plan.preorder())
        session.disable_hyperspace()
        expected = q(session.read.parquet(str(tmp / "left"))).to_pydict()
        session.enable_hyperspace()
        got = q(df2).to_pydict()
        assert sort_pydict(got) == sort_pydict(expected)
        assert 1111.0 in got["a"]  # appended row present

    def test_deleted_files_lineage_filter(self, env):
        session, hs, tmp = env
        session.set_conf(C.INDEX_LINEAGE_ENABLED, True)
        # two source files so one can be deleted
        cio.write_parquet(
            ColumnBatch.from_pydict({"k": [7, 9], "a": [5555.0, 6666.0], "s": ["x", "y"]}),
            str(tmp / "left" / "l2.parquet"),
        )
        df = session.read.parquet(str(tmp / "left"))
        hs.create_index(df, CoveringIndexConfig("fidx", ["k"], ["a"]))
        os.unlink(tmp / "left" / "l2.parquet")
        session.enable_hyperspace()
        session.set_conf(C.HYBRID_SCAN_ENABLED, True)
        df2 = session.read.parquet(str(tmp / "left"))
        q = lambda d: d.filter(col("k") == 7).select("k", "a")
        plan = q(df2).optimized_plan()
        iscan = index_scans(plan)
        assert len(iscan) == 1 and iscan[0].lineage_filter_ids
        got = q(df2).to_pydict()
        session.disable_hyperspace()
        expected = q(session.read.parquet(str(tmp / "left"))).to_pydict()
        assert sort_pydict(got) == sort_pydict(expected)
        assert 5555.0 not in got["a"]  # deleted file's rows are gone

    def test_join_hybrid_uses_bucket_union(self, env):
        session, hs, tmp = env
        ldf = session.read.parquet(str(tmp / "left"))
        rdf = session.read.parquet(str(tmp / "right"))
        hs.create_index(ldf, CoveringIndexConfig("lidx", ["k"], ["a"]))
        hs.create_index(rdf, CoveringIndexConfig("ridx", ["rk"], ["b"]))
        cio.write_parquet(
            ColumnBatch.from_pydict({"k": [7], "a": [7777.0], "s": ["x"]}),
            str(tmp / "left" / "l2.parquet"),
        )
        session.enable_hyperspace()
        session.set_conf(C.HYBRID_SCAN_ENABLED, True)
        l2 = session.read.parquet(str(tmp / "left"))
        r2 = session.read.parquet(str(tmp / "right"))
        q = lambda l, r: l.select("k", "a").join(
            r.select("rk", "b"), col("k") == col("rk")
        )
        plan = q(l2, r2).optimized_plan()
        assert any(isinstance(n, BucketUnion) for n in plan.preorder())
        got = q(l2, r2).to_pydict()
        session.disable_hyperspace()
        expected = q(
            session.read.parquet(str(tmp / "left")),
            session.read.parquet(str(tmp / "right")),
        ).to_pydict()
        assert sort_pydict(got) == sort_pydict(expected)

    def test_too_much_appended_rejected(self, env):
        session, hs, tmp = env
        df = session.read.parquet(str(tmp / "left"))
        hs.create_index(df, CoveringIndexConfig("fidx", ["k"], ["a"]))
        # append a file bigger than 30% of total
        big = {
            "k": list(range(2000)),
            "a": [0.0] * 2000,
            "s": ["z"] * 2000,
        }
        cio.write_parquet(ColumnBatch.from_pydict(big), str(tmp / "left" / "big.parquet"))
        session.enable_hyperspace()
        session.set_conf(C.HYBRID_SCAN_ENABLED, True)
        df2 = session.read.parquet(str(tmp / "left"))
        plan = df2.filter(col("k") == 7).select("k", "a").optimized_plan()
        assert not index_scans(plan)


class TestExplainWhyNot:
    def test_explain_lists_index(self, env):
        session, hs, tmp = env
        df = session.read.parquet(str(tmp / "left"))
        hs.create_index(df, CoveringIndexConfig("fidx", ["k"], ["a"]))
        session.enable_hyperspace()
        df2 = session.read.parquet(str(tmp / "left"))
        q = df2.filter(col("k") == 7).select("k", "a")
        s = hs.explain(q, verbose=True)
        assert "fidx" in s
        assert "Plan with indexes" in s
        assert "Physical operator stats" in s

    def test_why_not_gives_reasons(self, env):
        session, hs, tmp = env
        df = session.read.parquet(str(tmp / "left"))
        hs.create_index(df, CoveringIndexConfig("fidx", ["k"], ["a"]))
        # query that cannot use the index (needs 's')
        q = df.filter(col("k") == 1).select("k", "s")
        s = hs.why_not(q, extended=True)
        assert "MISSING_REQUIRED_COL" in s

    def test_why_not_applied_index(self, env):
        session, hs, tmp = env
        df = session.read.parquet(str(tmp / "left"))
        hs.create_index(df, CoveringIndexConfig("fidx", ["k"], ["a"]))
        q = df.filter(col("k") == 1).select("k", "a")
        s = hs.why_not(q)
        assert "(applied)" in s



class TestExplainDisplayModes:
    def test_console_and_html_modes(self, env):
        session, hs, tmp = env
        df = session.read.parquet(str(tmp / "left"))
        hs.create_index(df, CoveringIndexConfig("fidx", ["k"], ["a"]))
        session.enable_hyperspace()
        q = session.read.parquet(str(tmp / "left")).filter(col("k") == 1).select("k", "a")
        session.set_conf("hyperspace.explain.displayMode", "console")
        s = hs.explain(q)
        # reference ConsoleMode default: green background + reset
        # (DisplayMode.scala:82-87 Console.GREEN_B)
        assert "\033[42m" in s and "Hyperspace(" in s
        session.set_conf("hyperspace.explain.displayMode", "html")
        s = hs.explain(q)
        assert s.startswith("<pre>") and '<b style="background:LightGreen">' in s
        session.set_conf("hyperspace.explain.displayMode.highlight.beginTag", ">>")
        session.set_conf("hyperspace.explain.displayMode.highlight.endTag", "<<")
        s = hs.explain(q)
        assert ">>" in s and "<<" in s
        # empty override falls back to the mode defaults
        session.set_conf("hyperspace.explain.displayMode.highlight.beginTag", "")
        s = hs.explain(q)
        assert '<b style="background:LightGreen">' in s
        session.set_conf("hyperspace.explain.displayMode", "plaintext")
        session.unset_conf("hyperspace.explain.displayMode.highlight.beginTag")
        session.unset_conf("hyperspace.explain.displayMode.highlight.endTag")
        s = hs.explain(q)
        assert "<----" in s and "---->" in s  # reference plaintext markers
