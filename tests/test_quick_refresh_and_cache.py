"""Quick-refresh query path and caching manager behavior.

ref: RefreshQuickAction semantics (metadata-only; Hybrid Scan serves the
delta at query time even when the global hybrid toggle is off) and
CachingIndexCollectionManager (expiry + clear-on-mutation).
"""

import time

import pytest

from hyperspace_tpu import CoveringIndexConfig, Hyperspace
from hyperspace_tpu import constants as C
from hyperspace_tpu.columnar import io as cio
from hyperspace_tpu.columnar.table import ColumnBatch
from hyperspace_tpu.plan import col
from hyperspace_tpu.plan.nodes import FileScan, Union


def index_scans(plan):
    return [n for n in plan.preorder() if isinstance(n, FileScan) and n.index_info]


class TestQuickRefreshQueryPath:
    def test_query_after_quick_refresh_uses_hybrid(self, tmp_session, tmp_path):
        session = tmp_session
        session.set_conf(C.INDEX_LINEAGE_ENABLED, True)
        src = tmp_path / "src"
        cio.write_parquet(
            ColumnBatch.from_pydict({"k": [1, 2], "v": [1.0, 2.0]}),
            str(src / "p1.parquet"),
        )
        hs = Hyperspace(session)
        df = session.read.parquet(str(src))
        hs.create_index(df, CoveringIndexConfig("qidx", ["k"], ["v"]))
        # append, then metadata-only refresh
        cio.write_parquet(
            ColumnBatch.from_pydict({"k": [3], "v": [30.0]}),
            str(src / "p2.parquet"),
        )
        hs.refresh_index("qidx", "quick")
        session.enable_hyperspace()
        # note: hybrid scan NOT enabled globally — the quick-refreshed entry
        # promises query-time handling on its own
        df2 = session.read.parquet(str(src))
        q = df2.filter(col("k") >= 1).select("k", "v")
        plan = q.optimized_plan()
        assert index_scans(plan), "quick-refreshed index should still apply"
        assert any(isinstance(n, Union) for n in plan.preorder())
        out = q.to_pydict()
        assert sorted(out["k"]) == [1, 2, 3]
        assert 30.0 in out["v"]


class TestCachingManager:
    def test_cache_hit_and_clear_on_mutation(self, tmp_session, tmp_path):
        import hyperspace_tpu.index_manager as im

        cio.write_parquet(
            ColumnBatch.from_pydict({"k": [1], "v": [1.0]}),
            str(tmp_path / "s" / "p.parquet"),
        )
        hs = Hyperspace(tmp_session)
        df = tmp_session.read.parquet(str(tmp_path / "s"))
        hs.create_index(df, CoveringIndexConfig("c1", ["k"], ["v"]))
        mgr = im.index_manager_for(tmp_session)
        first = mgr.get_indexes(["ACTIVE"])
        assert [e.name for e in first] == ["c1"]
        # cached: same objects returned without re-reading the log
        second = mgr.get_indexes(["ACTIVE"])
        assert second[0] is first[0]
        # mutation clears the cache
        hs.create_index(df, CoveringIndexConfig("c2", ["k"], ["v"]))
        third = mgr.get_indexes(["ACTIVE"])
        assert sorted(e.name for e in third) == ["c1", "c2"]
        assert all(t is not f for t in third for f in first if t.name == "c1") or True

    def test_cache_expiry(self, tmp_session, tmp_path):
        import hyperspace_tpu.index_manager as im

        tmp_session.set_conf(C.INDEX_CACHE_EXPIRY_SECONDS, 0)  # expire instantly
        cio.write_parquet(
            ColumnBatch.from_pydict({"k": [1], "v": [1.0]}),
            str(tmp_path / "s" / "p.parquet"),
        )
        hs = Hyperspace(tmp_session)
        df = tmp_session.read.parquet(str(tmp_path / "s"))
        hs.create_index(df, CoveringIndexConfig("c1", ["k"], ["v"]))
        mgr = im.index_manager_for(tmp_session)
        a = mgr.get_indexes(["ACTIVE"])
        time.sleep(0.01)
        b = mgr.get_indexes(["ACTIVE"])
        assert a[0] is not b[0]  # expired -> re-read from disk


    def test_quick_refresh_with_deletes(self, tmp_session, tmp_path):
        import os

        session = tmp_session
        session.set_conf(C.INDEX_LINEAGE_ENABLED, True)
        src = tmp_path / "qd"
        cio.write_parquet(
            ColumnBatch.from_pydict({"k": [1, 2], "v": [1.0, 2.0]}),
            str(src / "p1.parquet"),
        )
        cio.write_parquet(
            ColumnBatch.from_pydict({"k": [5], "v": [50.0]}),
            str(src / "p2.parquet"),
        )
        hs = Hyperspace(session)
        df = session.read.parquet(str(src))
        hs.create_index(df, CoveringIndexConfig("qd", ["k"], ["v"]))
        os.unlink(src / "p2.parquet")
        cio.write_parquet(
            ColumnBatch.from_pydict({"k": [9], "v": [90.0]}),
            str(src / "p3.parquet"),
        )
        hs.refresh_index("qd", "quick")
        session.enable_hyperspace()
        df2 = session.read.parquet(str(src))
        q = df2.filter(col("k") >= 1).select("k", "v")
        plan = q.optimized_plan()
        iscans = index_scans(plan)
        assert iscans and iscans[0].lineage_filter_ids  # deleted file filtered
        out = q.to_pydict()
        assert sorted(out["k"]) == [1, 2, 9]
        assert 50.0 not in out["v"] and 90.0 in out["v"]
