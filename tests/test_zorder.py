"""ZOrderCoveringIndex tests (ref: ZOrderFieldTest bit-level checks,
E2E z-order suites)."""

import numpy as np
import pytest

from hyperspace_tpu import Hyperspace, ZOrderCoveringIndexConfig
from hyperspace_tpu import constants as C
from hyperspace_tpu.columnar import io as cio
from hyperspace_tpu.columnar.table import Column, ColumnBatch
from hyperspace_tpu.models.zorder.fields import (
    MinMaxZOrderField,
    PercentileZOrderField,
    ZOrderField,
    build_field,
)
from hyperspace_tpu.plan import col
from hyperspace_tpu.plan.nodes import FileScan


def file_scans(plan):
    return [n for n in plan.preorder() if isinstance(n, FileScan)]


@pytest.fixture()
def env(tmp_session, tmp_path):
    rng = np.random.default_rng(3)
    n = 2000
    data = {
        "x": rng.integers(0, 1000, n).tolist(),
        "y": rng.integers(0, 1000, n).tolist(),
        "payload": rng.uniform(size=n).tolist(),
    }
    src = tmp_path / "src"
    cio.write_parquet(ColumnBatch.from_pydict(data), str(src / "p.parquet"))
    hs = Hyperspace(tmp_session)
    df = tmp_session.read.parquet(str(src))
    return tmp_session, hs, df, src


class TestFields:
    def test_minmax_field_roundtrip(self):
        f = MinMaxZOrderField("x", 0.0, 100.0, 8)
        f2 = ZOrderField.from_dict(f.to_dict())
        assert isinstance(f2, MinMaxZOrderField)
        assert (f2.vmin, f2.vmax, f2.nbits) == (0.0, 100.0, 8)

    def test_percentile_field_handles_skew(self):
        # heavily skewed data: percentile buckets spread codes, min-max doesn't
        vals = np.concatenate([np.ones(990), np.array([1e9] * 10)])
        c = Column.from_values(vals.tolist())
        mm = MinMaxZOrderField.from_column("x", c, 8)
        pc = PercentileZOrderField.from_column("x", c, 8)
        mm_codes = mm.codes(c)
        pc_codes = pc.codes(c)
        assert len(np.unique(mm_codes)) <= 2  # min-max collapses the skew
        assert len(np.unique(pc_codes)) >= 2

    def test_string_field(self):
        c = Column.from_values(["apple", "zebra", "mango"])
        f = build_field("s", c, use_percentile=False, nbits=4)
        codes = f.codes(c)
        assert codes[0] < codes[2] < codes[1]  # lexicographic order preserved


class TestZOrderIndex:
    def test_create_and_layout(self, env, tmp_path):
        session, hs, df, _ = env
        session.set_conf(C.ZORDER_TARGET_SOURCE_BYTES_PER_PARTITION, 8_000)
        hs.create_index(df, ZOrderCoveringIndexConfig("z1", ["x", "y"], ["payload"]))
        entry = hs.get_index("z1")
        assert entry.kind == "ZCI"
        files = entry.content.files()
        assert len(files) > 1  # range-partitioned into multiple files
        stats = entry.derived_dataset.statistics()
        assert len(stats["zOrderFields"]) == 2

    def test_zorder_clusters_ranges(self, env):
        """Each file should see a much smaller x-range than the full domain —
        the clustering property that makes range queries touch few files."""
        session, hs, df, _ = env
        session.set_conf(C.ZORDER_TARGET_SOURCE_BYTES_PER_PARTITION, 6_000)
        hs.create_index(df, ZOrderCoveringIndexConfig("z1", ["x", "y"], ["payload"]))
        entry = hs.get_index("z1")
        spans = []
        for f in entry.content.files():
            b = cio.read_parquet([f])
            spans.append(b.column("x").data.max() - b.column("x").data.min())
        # average per-file span well below the full 0..1000 domain
        assert np.mean(spans) < 700

    def test_query_rewrite_any_indexed_col(self, env, tmp_path):
        session, hs, df, src = env
        hs.create_index(df, ZOrderCoveringIndexConfig("z1", ["x", "y"], ["payload"]))
        session.enable_hyperspace()
        df2 = session.read.parquet(str(src))
        # 'y' is NOT the leading indexed column; ZCI still applies
        q = df2.filter(col("y") < 100).select("x", "y", "payload")
        plan = q.optimized_plan()
        idx = [s for s in file_scans(plan) if s.index_info is not None]
        assert idx and idx[0].index_info.index_kind_abbr == "ZCI"
        session.disable_hyperspace()
        expected = q.to_pydict()
        session.enable_hyperspace()
        got = q.to_pydict()

        def norm(d):
            return sorted(zip(d["x"], d["y"], d["payload"]))

        assert norm(got) == norm(expected)

    def test_single_column_degenerates_to_range_sort(self, env, tmp_path):
        session, hs, df, _ = env
        hs.create_index(df, ZOrderCoveringIndexConfig("z1", ["x"], ["payload"]))
        entry = hs.get_index("z1")
        # rows globally sorted by x across ordered files
        last_max = -1
        for f in sorted(entry.content.files()):
            b = cio.read_parquet([f])
            xs = b.column("x").data
            assert (np.diff(xs) >= 0).all()
            assert xs.min() >= last_max
            last_max = xs.max()

    def test_refresh_full(self, env, tmp_path):
        session, hs, df, src = env
        hs.create_index(df, ZOrderCoveringIndexConfig("z1", ["x", "y"], ["payload"]))
        cio.write_parquet(
            ColumnBatch.from_pydict({"x": [5000], "y": [5000], "payload": [0.5]}),
            str(src / "p2.parquet"),
        )
        hs.refresh_index("z1", "full")
        entry = hs.get_index("z1")
        batch = cio.read_parquet(entry.content.files())
        assert batch.num_rows == 2001
        # fields re-fit to the new domain
        f = entry.derived_dataset.statistics()["zOrderFields"][0]
        assert f["max"] >= 5000

    def test_refresh_incremental_append(self, env, tmp_path):
        session, hs, df, src = env
        hs.create_index(df, ZOrderCoveringIndexConfig("z1", ["x", "y"], ["payload"]))
        cio.write_parquet(
            ColumnBatch.from_pydict({"x": [1], "y": [2], "payload": [0.5]}),
            str(src / "p2.parquet"),
        )
        hs.refresh_index("z1", "incremental")
        entry = hs.get_index("z1")
        batch = cio.read_parquet(entry.content.files())
        assert batch.num_rows == 2001
