"""Concurrent multi-query serving: scheduler, global budget, cancellation.

Covers the PR-8 tentpole guarantees:
- the global budget accountant grants/stalls/force-grants correctly, never
  deadlocks (zero-holder progress guarantee), and both streaming consumers
  (scan chunks AND join pair loads) draw from the ONE ledger — the
  per-stream double-count is gone;
- the scheduler enforces max-concurrency, priority order, and the bounded
  run queue (rejection at admission);
- cancellation resolves queued queries immediately and unwinds running
  ones at the next chunk boundary, draining every budget reservation;
- a stalled low-priority stream never blocks a newly admitted query's
  first chunk; an armed device fault on one query leaves its neighbors'
  results untouched;
- served results are bit-identical to direct collect() under 8-way
  concurrency.
"""

import os
import threading
import time

import numpy as np
import pytest

from hyperspace_tpu import HyperspaceSession, serve
from hyperspace_tpu import constants as C
from hyperspace_tpu.columnar import io as cio
from hyperspace_tpu.columnar.table import ColumnBatch
from hyperspace_tpu.plan import Count, Sum, col, lit
from hyperspace_tpu.serve.budget import BudgetAccountant
from hyperspace_tpu.telemetry.metrics import REGISTRY
from hyperspace_tpu.utils import backend, faults


def _bits(pydict):
    return repr(
        {
            k: [x.hex() if isinstance(x, float) else x for x in v]
            for k, v in pydict.items()
        }
    )


def _write_multifile(root, n_files=6, rows=2500, seed=3):
    rng = np.random.default_rng(seed)
    paths = []
    for i in range(n_files):
        n = rows + i * 97
        data = {
            "k": rng.integers(0, 40, n).tolist(),
            "x": rng.uniform(0, 100, n).tolist(),
            "q": rng.integers(1, 50, n).tolist(),
        }
        p = os.path.join(root, "t", f"part-{i}.parquet")
        cio.write_parquet(ColumnBatch.from_pydict(data), p)
        paths.append(p)
    return paths


@pytest.fixture(autouse=True)
def _pristine_serving_state():
    """Default budget ledger restored around every test (several tests
    shrink HYPERSPACE_GLOBAL_BUDGET_MB and swap the singleton)."""
    yield
    faults.disarm()
    backend._reset_for_testing()
    serve.reset_global_budget()


# ---------------------------------------------------------------------------
# global budget accountant
# ---------------------------------------------------------------------------

class TestBudgetAccountant:
    def test_grants_within_limit_and_releases(self):
        acct = BudgetAccountant(1000)
        s = acct.stream("scan")
        assert s.try_reserve(400) and s.try_reserve(400)
        assert acct.held_bytes() == 800
        s.release(400)
        assert acct.held_bytes() == 400
        s.close()
        assert acct.held_bytes() == 0
        assert acct.check_consistency()

    def test_holder_over_limit_stalls(self):
        acct = BudgetAccountant(1000)
        s = acct.stream("scan")
        assert s.try_reserve(900)
        before = REGISTRY.counter("serve.budget.stalls").value
        assert not s.try_reserve(200)  # would exceed; s already holds
        assert REGISTRY.counter("serve.budget.stalls").value == before + 1
        assert acct.held_bytes() == 900  # failed reserve left no residue
        s.close()

    def test_zero_holder_always_granted(self):
        """The progress guarantee: a stream holding nothing is granted even
        past the limit, so no admission order can deadlock."""
        acct = BudgetAccountant(100)
        hog = acct.stream("join")
        assert hog.try_reserve(100)  # ledger now full
        fresh = acct.stream("scan")
        before = REGISTRY.counter("serve.budget.force_grants").value
        assert fresh.try_reserve(50)  # zero holder: granted over budget
        assert REGISTRY.counter("serve.budget.force_grants").value == before + 1
        assert acct.held_bytes() == 150
        hog.close()
        fresh.close()
        assert acct.held_bytes() == 0

    def test_one_ledger_for_scan_and_join_streams(self):
        """The double-count fix: both consumer kinds draw from the same
        total, so a query's join loader cannot reserve a second full
        budget on top of its scan stream."""
        acct = BudgetAccountant(1000)
        scan = acct.stream("scan")
        join = acct.stream("join")
        assert scan.try_reserve(600)
        assert join.try_reserve(300)  # fits: shared total is 900
        assert not join.try_reserve(300)  # 1200 > limit and join holds bytes
        assert acct.held_bytes() == 900
        state = acct.state()
        assert state["limit_bytes"] == 1000
        assert sorted(s["label"] for s in state["streams"]) == ["join", "scan"]
        scan.close()
        join.close()

    def test_close_is_idempotent_and_releases_remainder(self):
        acct = BudgetAccountant(1000)
        s = acct.stream("scan")
        s.try_reserve(700)
        s.close()
        s.close()
        assert acct.held_bytes() == 0
        assert acct.check_consistency()

    def test_release_clamps_to_held(self):
        acct = BudgetAccountant(1000)
        s = acct.stream("scan")
        s.try_reserve(100)
        s.release(500)  # over-release must not drive the ledger negative
        assert acct.held_bytes() == 0
        s.close()

    def test_legacy_io_budget_knob_carries_over(self, monkeypatch):
        monkeypatch.delenv("HYPERSPACE_GLOBAL_BUDGET_MB", raising=False)
        monkeypatch.setenv("HYPERSPACE_IO_BUDGET_MB", "7")
        assert serve.configured_budget_bytes() == 7 * 2**20
        monkeypatch.setenv("HYPERSPACE_GLOBAL_BUDGET_MB", "3")
        assert serve.configured_budget_bytes() == 3 * 2**20


class TestBudgetedStreaming:
    def test_stream_bit_identical_under_tiny_global_budget(
        self, tmp_path, monkeypatch
    ):
        """A global budget smaller than one chunk still completes (force
        grants keep the stream progressing) and the stream stays
        bit-identical to the monolithic read; the ledger drains to zero."""
        paths = _write_multifile(str(tmp_path))
        monkeypatch.setenv("HYPERSPACE_IO_THREADS", "4")
        monkeypatch.setenv("HYPERSPACE_STREAM_CHUNK_MB", "0.01")
        monkeypatch.setenv("HYPERSPACE_GLOBAL_BUDGET_MB", "0.0001")
        acct = serve.reset_global_budget()
        whole = cio.read_parquet(paths, ["k", "x"])
        chunks = list(cio.iter_chunks(paths, ["k", "x"]))
        assert len(chunks) >= 2
        cat = ColumnBatch.concat([c.batch for c in chunks])
        assert _bits(whole.to_pydict()) == _bits(cat.to_pydict())
        assert acct.held_bytes() == 0
        assert acct.check_consistency()
        assert REGISTRY.counter("serve.budget.force_grants").value > 0

    def test_abandoned_stream_returns_reservations(self, tmp_path, monkeypatch):
        """Dropping a chunk stream mid-iteration (the cancellation unwind
        path) releases every outstanding read-ahead reservation."""
        paths = _write_multifile(str(tmp_path))
        monkeypatch.setenv("HYPERSPACE_IO_THREADS", "4")
        monkeypatch.setenv("HYPERSPACE_STREAM_CHUNK_MB", "0.01")
        acct = serve.reset_global_budget()
        it = cio.iter_chunks(paths, ["k", "x"])
        next(it)  # read-ahead now holds reservations beyond chunk 0
        it.close()
        assert acct.held_bytes() == 0
        assert acct.check_consistency()


# ---------------------------------------------------------------------------
# query context / cancellation primitives
# ---------------------------------------------------------------------------

class TestQueryContext:
    def test_cancelled_error_is_base_exception(self):
        """Pinned contract: the device tier's ``except Exception``
        degrade-to-host handlers must never swallow a cancel into a host
        re-run, so the error must NOT be an Exception subclass."""
        assert issubclass(serve.QueryCancelledError, BaseException)
        assert not issubclass(serve.QueryCancelledError, Exception)

    def test_check_cancelled_outside_serving_is_noop(self):
        serve.check_cancelled()  # no context: never raises

    def test_check_cancelled_raises_inside_cancelled_scope(self):
        ctx = serve.QueryContext(label="t")
        with serve.query_scope(ctx):
            serve.check_cancelled()  # not cancelled yet
            ctx.cancel()
            with pytest.raises(serve.QueryCancelledError):
                serve.check_cancelled()
        serve.check_cancelled()  # scope restored

    def test_current_query_scoping(self):
        assert serve.current_query() is None
        ctx = serve.QueryContext(label="t")
        with serve.query_scope(ctx):
            assert serve.current_query() is ctx
        assert serve.current_query() is None


# ---------------------------------------------------------------------------
# scheduler
# ---------------------------------------------------------------------------

class TestScheduler:
    def test_submit_result_roundtrip(self):
        sched = serve.QueryScheduler(max_concurrent=2, queue_depth=8)
        try:
            hs = [sched.submit(lambda i=i: i * i, label=f"q{i}") for i in range(6)]
            assert [h.result(30) for h in hs] == [0, 1, 4, 9, 16, 25]
            assert all(h.status == "done" for h in hs)
        finally:
            sched.shutdown()

    def test_max_concurrent_enforced(self):
        sched = serve.QueryScheduler(max_concurrent=2, queue_depth=16)
        state = {"active": 0, "peak": 0}
        lock = threading.Lock()
        release = threading.Event()

        def job():
            with lock:
                state["active"] += 1
                state["peak"] = max(state["peak"], state["active"])
            release.wait(30)
            with lock:
                state["active"] -= 1

        try:
            hs = [sched.submit(job) for _ in range(6)]
            time.sleep(0.2)  # let the dispatcher admit what it will
            assert len(sched.state()["active"]) == 2
            release.set()
            for h in hs:
                h.result(30)
            assert state["peak"] == 2
        finally:
            sched.shutdown()

    def test_priority_order(self):
        """With one worker slot, a later high-priority submission runs
        before earlier low-priority ones (FIFO within a priority)."""
        sched = serve.QueryScheduler(max_concurrent=1, queue_depth=16)
        order: list = []
        gate = threading.Event()
        try:
            blocker = sched.submit(lambda: gate.wait(30), label="blocker")
            lows = [
                sched.submit(lambda i=i: order.append(("low", i)),
                             priority=0, label=f"low{i}")
                for i in range(2)
            ]
            high = sched.submit(lambda: order.append(("high", 0)),
                                priority=10, label="high")
            gate.set()
            blocker.result(30)
            high.result(30)
            for h in lows:
                h.result(30)
            assert order[0] == ("high", 0)
            assert order[1:] == [("low", 0), ("low", 1)]
        finally:
            sched.shutdown()

    def test_queue_depth_rejection(self):
        sched = serve.QueryScheduler(max_concurrent=1, queue_depth=2)
        gate = threading.Event()
        try:
            running = sched.submit(lambda: gate.wait(30))
            q1 = sched.submit(lambda: 1)
            q2 = sched.submit(lambda: 2)
            before = REGISTRY.counter("serve.rejected").value
            with pytest.raises(serve.AdmissionRejected):
                sched.submit(lambda: 3)
            assert REGISTRY.counter("serve.rejected").value == before + 1
            gate.set()
            assert running.result(30) is not None or True
            assert q1.result(30) == 1 and q2.result(30) == 2
            assert sched.state()["totals"]["rejected"] == 1
        finally:
            sched.shutdown()

    def test_cancel_queued_resolves_immediately(self):
        sched = serve.QueryScheduler(max_concurrent=1, queue_depth=8)
        gate = threading.Event()
        try:
            sched.submit(lambda: gate.wait(30), label="blocker")
            victim = sched.submit(lambda: 42, label="victim")
            victim.cancel()
            with pytest.raises(serve.QueryCancelledError):
                victim.result(1)  # resolves without waiting for the blocker
            assert victim.status == "cancelled"
            gate.set()
        finally:
            sched.shutdown()

    def test_submit_after_shutdown_raises(self):
        sched = serve.QueryScheduler(max_concurrent=1, queue_depth=2)
        sched.shutdown()
        with pytest.raises(serve.SchedulerShutdown):
            sched.submit(lambda: 1)

    def test_failed_query_reraises_on_result(self):
        sched = serve.QueryScheduler(max_concurrent=1, queue_depth=2)

        def boom():
            raise ValueError("nope")

        try:
            h = sched.submit(boom)
            with pytest.raises(ValueError, match="nope"):
                h.result(30)
            assert h.status == "failed"
            assert sched.state()["totals"]["failed"] == 1
        finally:
            sched.shutdown()

    def test_queue_wait_histogram_recorded(self):
        sched = serve.QueryScheduler(max_concurrent=1, queue_depth=4)
        before = REGISTRY.histogram("serve.queue_wait_ms").value["count"]
        try:
            hs = [sched.submit(lambda: 1) for _ in range(3)]
            for h in hs:
                h.result(30)
        finally:
            sched.shutdown()
        assert REGISTRY.histogram("serve.queue_wait_ms").value["count"] == before + 3


# ---------------------------------------------------------------------------
# scheduler x streaming integration
# ---------------------------------------------------------------------------

class TestServingIntegration:
    def _session_query(self, tmp_path, monkeypatch):
        _write_multifile(str(tmp_path))
        monkeypatch.setenv("HYPERSPACE_IO_THREADS", "4")
        monkeypatch.setenv("HYPERSPACE_STREAM_CHUNK_MB", "0.01")
        session = HyperspaceSession(warehouse_dir=str(tmp_path))
        session.set_conf(C.EXEC_TPU_ENABLED, True)

        def q():
            return (
                session.read.parquet(os.path.join(str(tmp_path), "t"))
                .filter(col("q") > 10)
                .agg(Sum(col("x")).alias("sx"), Count(lit(1)).alias("n"))
            )

        return session, q

    def test_served_results_bit_identical_to_direct(self, tmp_path, monkeypatch):
        session, q = self._session_query(tmp_path, monkeypatch)
        serve.reset_global_budget()
        expected = _bits(q().collect().to_pydict())
        sched = serve.QueryScheduler(max_concurrent=4, queue_depth=64)
        try:
            hs = [sched.submit_query(q(), label=f"c{i}") for i in range(8)]
            for h in hs:
                assert _bits(h.result(60).to_pydict()) == expected
        finally:
            sched.shutdown()

    def test_cancel_running_releases_budget_within_tick(
        self, tmp_path, monkeypatch
    ):
        """A cancelled mid-stream query unwinds at the next chunk boundary,
        raising QueryCancelledError through result() and returning every
        budget reservation and read-ahead future."""
        paths = _write_multifile(str(tmp_path))
        monkeypatch.setenv("HYPERSPACE_IO_THREADS", "4")
        monkeypatch.setenv("HYPERSPACE_STREAM_CHUNK_MB", "0.01")
        acct = serve.reset_global_budget()
        started = threading.Event()
        cancelled = threading.Event()

        def slow_stream():
            out = []
            for chunk in cio.iter_chunks(paths, ["k", "x"]):
                out.append(chunk.batch)
                started.set()
                cancelled.wait(10)  # hold mid-stream until cancel lands
            return out

        sched = serve.QueryScheduler(max_concurrent=1, queue_depth=4)
        try:
            h = sched.submit(slow_stream, label="victim")
            assert started.wait(30)
            h.cancel()
            cancelled.set()
            with pytest.raises(serve.QueryCancelledError):
                h.result(30)
            assert h.status == "cancelled"
            assert sched.state()["totals"]["cancelled"] == 1
            assert acct.held_bytes() == 0
            assert acct.check_consistency()
        finally:
            sched.shutdown()

    def test_stalled_low_priority_never_blocks_high_admission(
        self, tmp_path, monkeypatch
    ):
        """Backpressure isolation: a low-priority stream holding the whole
        ledger cannot stop a newly admitted high-priority query — its
        first reservation force-grants (zero-holder guarantee)."""
        session, q = self._session_query(tmp_path, monkeypatch)
        monkeypatch.setenv("HYPERSPACE_GLOBAL_BUDGET_MB", "0.0001")
        acct = serve.reset_global_budget()
        hog = acct.stream("join", query="hog")
        assert hog.try_reserve(10**6)  # ledger saturated by the low-pri hog
        expected = _bits(q().collect().to_pydict())
        sched = serve.QueryScheduler(max_concurrent=2, queue_depth=8)
        try:
            h = sched.submit_query(q(), priority=10, label="high")
            assert _bits(h.result(60).to_pydict()) == expected
        finally:
            sched.shutdown()
            hog.close()
        assert acct.held_bytes() == 0

    def test_device_fault_on_one_query_spares_neighbors(
        self, tmp_path, monkeypatch
    ):
        """An armed device fault fails ONE query's device path; the
        breaker degrades it to the host tier, neighbors keep answering,
        and every result still matches the fault-free reference."""
        monkeypatch.setenv("HYPERSPACE_DEVICE_STRICT", "0")
        _write_multifile(str(tmp_path))
        monkeypatch.setenv("HYPERSPACE_IO_THREADS", "4")
        monkeypatch.setenv("HYPERSPACE_STREAM_CHUNK_MB", "0.01")
        session = HyperspaceSession(warehouse_dir=str(tmp_path))
        session.set_conf(C.EXEC_TPU_ENABLED, True)

        def q():
            # integer aggregates only: exact on BOTH tiers, so the faulted
            # query's host-degraded answer is bitwise comparable to the
            # neighbors' device answers (f32 float sums legitimately differ
            # cross-tier — the documented exactF64Aggregates property)
            return (
                session.read.parquet(os.path.join(str(tmp_path), "t"))
                .filter(col("q") > 10)
                .agg(Sum(col("q")).alias("sq"), Count(lit(1)).alias("n"))
            )

        serve.reset_global_budget()
        backend._reset_for_testing()
        expected = _bits(q().collect().to_pydict())
        faults.arm("device.dispatch:ioerror:n=1")
        try:
            sched = serve.QueryScheduler(max_concurrent=4, queue_depth=32)
            try:
                hs = [sched.submit_query(q(), label=f"c{i}") for i in range(6)]
                results = [h.result(60) for h in hs]
                for r in results:
                    assert _bits(r.to_pydict()) == expected
                assert all(h.status == "done" for h in hs)
            finally:
                sched.shutdown()
        finally:
            faults.disarm()
            backend._reset_for_testing()


# ---------------------------------------------------------------------------
# serving state surface
# ---------------------------------------------------------------------------

class TestServeState:
    def test_serve_state_idle_shape(self):
        st = serve.serve_state()
        assert "budget" in st and "active" in st and "queued" in st
        assert st["budget"]["limit_bytes"] > 0

    def test_serving_state_string_renders(self):
        from hyperspace_tpu.analysis.explain import serving_state_string

        s = serving_state_string()
        assert "Serving" in s and "budget:" in s

    def test_scheduler_state_reports_active_and_queued(self):
        sched = serve.QueryScheduler(max_concurrent=1, queue_depth=8)
        gate = threading.Event()
        try:
            sched.submit(lambda: gate.wait(30), label="runner")
            sched.submit(lambda: 2, label="waiter", priority=3)
            deadline = time.time() + 5
            while time.time() < deadline:
                st = sched.state()
                if st["active"] and st["queued"]:
                    break
                time.sleep(0.01)
            assert [a["label"] for a in st["active"]] == ["runner"]
            assert [w["label"] for w in st["queued"]] == ["waiter"]
            assert st["queued"][0]["priority"] == 3
            gate.set()
            sched.drain(30)
        finally:
            sched.shutdown()

    def test_default_scheduler_roundtrip(self):
        serve.reset_scheduler()
        try:
            h = serve.submit(lambda: 7, label="default")
            assert h.result(30) == 7
            st = serve.serve_state()
            assert st["totals"]["done"] >= 1
        finally:
            serve.reset_scheduler()
