"""Multi-tenant QoS: weighted-fair scheduling, quotas, SLO admission.

Covers the tentpole guarantees:
- fairness math is deterministic and correct: tenants at weights 1:3
  converge to a 1:3 delivered cost share, an idle tenant's clock never
  accumulates credit, reweighting mid-stream takes effect on the next
  charge, and a single tenant degenerates to exact FIFO+priority order
  (the pinned bit-identity contract with the pre-QoS scheduler);
- the admission door rejects typed: token-bucket rate limits and
  ``max_in_flight`` quotas raise ``TenantQuotaExceeded`` (NOT an
  ``AdmissionRejected``), unmeetable deadlines raise
  ``DeadlineUnmeetable`` fast at submit time;
- queue-wait aging (``HYPERSPACE_SERVE_AGING_MS``) bounds low-priority
  starvation under a sustained high-priority flood;
- the global byte ledger partitions per tenant: a hog tenant saturates
  only its share while a second tenant keeps reserving, and the
  single-tenant path never consults the partition;
- the adversarial integration: 1 hog tenant vs 8 light tenants through
  one scheduler — light-tenant p99 queue wait under QoS is strictly below
  the no-QoS (single-tenant) run, and every served result stays
  bit-identical to serial.
"""

import os
import threading
import time

import numpy as np
import pytest

from hyperspace_tpu import HyperspaceSession, serve
from hyperspace_tpu import constants as C
from hyperspace_tpu.columnar import io as cio
from hyperspace_tpu.columnar.table import ColumnBatch
from hyperspace_tpu.plan import Count, Sum, col, lit
from hyperspace_tpu.serve import qos
from hyperspace_tpu.serve.budget import BudgetAccountant
from hyperspace_tpu.serve.tenant import (
    TENANTS,
    TenantQuotaExceeded,
    TenantSpecError,
    TokenBucket,
    parse_tenant_spec,
)
from hyperspace_tpu.telemetry.metrics import REGISTRY


def _bits(pydict):
    return repr(
        {
            k: [x.hex() if isinstance(x, float) else x for x in v]
            for k, v in pydict.items()
        }
    )


@pytest.fixture(autouse=True)
def _pristine_qos_state():
    """Tenant configuration and the cost model are process-wide; every
    test starts and ends from the zero-config state."""
    TENANTS.reset_for_testing()
    qos.COST_MODEL.reset_for_testing()
    yield
    TENANTS.reset_for_testing()
    qos.COST_MODEL.reset_for_testing()
    serve.reset_global_budget()


class _FakeHandle:
    """Minimal stand-in for QueryHandle in TenantQueues unit tests."""

    __slots__ = ("status", "_submit_t", "tag")

    def __init__(self, tag=None, submit_t=0.0):
        self.status = "queued"
        self._submit_t = submit_t
        self.tag = tag


def _drive(queues, charges, pops, cost=1.0, aging_ms=0.0, aging_cap=0,
           now=None):
    """Emulate the scheduler's dispatch→run→charge cycle with 1 worker
    slot and a fixed per-query cost; returns the tenant dispatch order."""
    order = []
    for _ in range(pops):
        popped = queues.pop_locked(aging_ms, aging_cap, now=now)
        if popped is None:
            break
        name, h = popped
        queues.on_dequeue(name)
        queues.on_activate(name)
        h.status = "done"
        queues.on_deactivate(name)
        queues.note_outcome(name, "done")
        queues.charge(name, charges(name) if callable(charges) else cost)
        order.append(name)
    return order


# ---------------------------------------------------------------------------
# fairness math (deterministic virtual-clock units)
# ---------------------------------------------------------------------------

class TestWeightedFairQueues:
    def test_weights_1_to_3_converge_to_delivered_share(self):
        """Two backlogged tenants at weights 1:3 and equal per-query cost
        receive dispatches — and therefore delivered cost — at 1:3."""
        TENANTS.configure("a", weight=1.0)
        TENANTS.configure("b", weight=3.0)
        q = qos.TenantQueues()
        for i in range(40):
            q.push("a", (0, i, _FakeHandle()))
            q.push("b", (0, 1000 + i, _FakeHandle()))
        order = _drive(q, None, pops=40, cost=1.0)
        na, nb = order.count("a"), order.count("b")
        assert na + nb == 40
        # exact WFQ at equal costs: b gets 3 of every 4 dispatches (±1
        # from clock ties at the start)
        assert 28 <= nb <= 32 and 8 <= na <= 12
        st = q.state()
        assert st["b"]["cost_s"] == pytest.approx(3 * st["a"]["cost_s"], rel=0.15)
        assert st["b"]["delivered_share"] == pytest.approx(0.75, abs=0.05)

    def test_unequal_costs_still_equalize_cost_not_count(self):
        """WFQ equalizes delivered COST per weight: a tenant whose queries
        cost 4x gets ~1/4 the dispatch count at equal weights."""
        TENANTS.configure("cheap", weight=1.0)
        TENANTS.configure("heavy", weight=1.0)
        q = qos.TenantQueues()
        for i in range(64):
            q.push("cheap", (0, i, _FakeHandle()))
            q.push("heavy", (0, 1000 + i, _FakeHandle()))
        order = _drive(
            q, lambda name: 4.0 if name == "heavy" else 1.0, pops=50
        )
        st = q.state()
        assert st["cheap"]["cost_s"] == pytest.approx(
            st["heavy"]["cost_s"], rel=0.25
        )
        assert order.count("cheap") > 2.5 * order.count("heavy")

    def test_idle_tenant_accumulates_no_debt(self):
        """B sits idle while A runs 30 queries; on wake B's clock jumps to
        A's region, so B alternates fairly instead of monopolizing the
        worker to 'repay' the idle period."""
        TENANTS.configure("a", weight=1.0)
        TENANTS.configure("b", weight=1.0)
        q = qos.TenantQueues()
        for i in range(60):
            q.push("a", (0, i, _FakeHandle()))
        assert _drive(q, None, pops=30) == ["a"] * 30
        for i in range(20):
            q.push("b", (0, 1000 + i, _FakeHandle()))
        order = _drive(q, None, pops=10)
        assert 4 <= order.count("b") <= 6  # fair from NOW on, not 10-in-a-row
        st = q.state()
        assert st["b"]["vclock"] >= 30.0 - 5.0  # woke at A's clock region

    def test_reweight_mid_stream_takes_effect(self):
        """Weight is read at charge time: bumping B to 3 mid-stream shifts
        the subsequent dispatch mix to ~3:1 without touching the queues."""
        TENANTS.configure("a", weight=1.0)
        TENANTS.configure("b", weight=1.0)
        q = qos.TenantQueues()
        for i in range(80):
            q.push("a", (0, i, _FakeHandle()))
            q.push("b", (0, 1000 + i, _FakeHandle()))
        first = _drive(q, None, pops=20)
        assert 8 <= first.count("b") <= 12  # ~1:1 at equal weights
        TENANTS.configure("b", weight=3.0)
        second = _drive(q, None, pops=40)
        assert second.count("b") >= 26  # ~3:1 after the reweight

    def test_single_tenant_is_exact_fifo_priority(self):
        """One tenant ⇒ pops follow the old scheduler's (-priority, seq)
        order exactly — the degenerate case the pinned bit-identity test
        at scheduler level relies on."""
        q = qos.TenantQueues()
        entries = [(-1, 0), (0, 1), (-5, 2), (0, 3), (-1, 4), (-5, 5)]
        handles = {}
        for pri_neg, seq in entries:
            h = _FakeHandle(tag=(pri_neg, seq))
            handles[(pri_neg, seq)] = h
            q.push("default", (pri_neg, seq, h))
        got = []
        for _ in range(len(entries)):
            name, h = q.pop_locked()
            q.on_dequeue(name)
            h.status = "done"
            got.append(h.tag)
        assert got == sorted(entries)

    def test_stale_entries_skipped_without_count_drift(self):
        q = qos.TenantQueues()
        h_dead, h_live = _FakeHandle(), _FakeHandle()
        q.push("t", (0, 0, h_dead))
        q.push("t", (0, 1, h_live))
        h_dead.status = "cancelled"  # lazily removed: scheduler released
        q.on_dequeue("t")            # ...its count when it resolved it
        name, h = q.pop_locked()
        assert h is h_live
        q.on_dequeue(name)
        assert q.pop_locked() is None

    def test_max_active_quota_gates_dispatch(self):
        """A tenant at its max_active cap is skipped; other tenants (or
        nobody) dispatch instead — the quota holds queries, it never
        rejects them."""
        TENANTS.configure("capped", max_active=1)
        q = qos.TenantQueues()
        q.push("capped", (0, 0, _FakeHandle()))
        q.push("capped", (0, 1, _FakeHandle()))
        q.push("free", (0, 2, _FakeHandle()))
        name, h = q.pop_locked()
        assert name == "capped"  # clock tie: 'capped' < 'free'
        q.on_dequeue(name)
        q.on_activate(name)
        name2, h2 = q.pop_locked()
        assert name2 == "free"  # capped is at its active cap
        q.on_dequeue(name2)
        q.on_activate(name2)
        assert q.pop_locked() is None
        q.on_deactivate("capped")
        assert q.pop_locked()[0] == "capped"


class TestAgingMath:
    def test_aging_boost_reorders_past_static_priority(self):
        """With aging armed, a long-waiting priority-0 entry outranks a
        fresh high-priority one once its boost crosses the gap; with
        aging off, static order holds."""
        q = qos.TenantQueues()
        old_low = _FakeHandle(submit_t=0.0)
        fresh_high = _FakeHandle(submit_t=9.99)
        q.push("t", (0, 0, old_low))       # priority 0, waited 10s
        q.push("t", (-10, 1, fresh_high))  # priority 10, just arrived
        name, h = q.pop_locked(aging_ms=0, aging_cap=100, now=10.0)
        assert h is fresh_high  # aging off: static priority wins
        q2 = qos.TenantQueues()
        q2.push("t", (0, 0, old_low))
        q2.push("t", (-10, 1, fresh_high))
        name, h = q2.pop_locked(aging_ms=100, aging_cap=100, now=10.0)
        assert h is old_low  # 10s / 100ms = boost 100 >> the 10-level gap
        assert q2.state()["t"]["aging_boosts"] == 1

    def test_aging_boost_is_capped(self):
        q = qos.TenantQueues()
        old_low = _FakeHandle(submit_t=0.0)
        fresh_high = _FakeHandle(submit_t=9.99)
        q.push("t", (0, 0, old_low))
        q.push("t", (-10, 1, fresh_high))
        # cap 5 < the 10-level gap: even a 10s wait cannot outrank
        name, h = q.pop_locked(aging_ms=100, aging_cap=5, now=10.0)
        assert h is fresh_high


# ---------------------------------------------------------------------------
# tenants: token bucket, spec, cost model
# ---------------------------------------------------------------------------

class TestTenantPrimitives:
    def test_token_bucket_deterministic_clock(self):
        clock = {"t": 0.0}
        b = TokenBucket(rate_qps=1.0, burst=2.0, clock=lambda: clock["t"])
        assert b.try_acquire() and b.try_acquire()
        assert not b.try_acquire()  # burst drained, no time passed
        clock["t"] = 1.0
        assert b.try_acquire()  # 1s at 1 qps refilled exactly one token
        assert not b.try_acquire()
        clock["t"] = 100.0
        assert b.tokens() == pytest.approx(2.0)  # refill caps at burst

    def test_spec_parses_and_configures(self, monkeypatch):
        spec = "gold:weight=4,rate_qps=50;bulk:weight=1,max_active=1;plain"
        parsed = parse_tenant_spec(spec)
        assert parsed["gold"] == {"weight": 4.0, "rate_qps": 50.0}
        assert parsed["bulk"] == {"weight": 1.0, "max_active": 1}
        assert parsed["plain"] == {}
        monkeypatch.setenv("HYPERSPACE_TENANTS", spec)
        TENANTS.reset_for_testing()  # re-bootstraps from the env spec
        assert TENANTS.get("gold").weight == 4.0
        assert TENANTS.get("bulk").max_active == 1
        assert "plain" in TENANTS.known()

    def test_bad_spec_raises_typed(self):
        with pytest.raises(TenantSpecError):
            parse_tenant_spec("gold:wieght=4")
        with pytest.raises(TenantSpecError):
            parse_tenant_spec("gold:weight=heavy")
        with pytest.raises(TenantSpecError):
            TENANTS.configure("x", not_a_field=1)

    def test_query_cost_normalization(self, monkeypatch):
        monkeypatch.setenv("HYPERSPACE_QOS_COST_MBPS", "100")
        record = {"total_ms": 500.0, "bytes_read": 50_000_000,
                  "upload_bytes": 25_000_000, "fetch_bytes": 25_000_000}
        # 0.5s wall + 100MB / 100MB/s = 1.5s
        assert qos.query_cost(record) == pytest.approx(1.5)

    def test_cost_model_predicts_after_history(self):
        assert qos.COST_MODEL.predict("q") is None
        qos.COST_MODEL.update("q", 0.2)
        qos.COST_MODEL.update("q", 0.2)
        assert qos.COST_MODEL.predict("q") == pytest.approx(0.2, rel=0.01)
        assert qos.COST_MODEL.mean_run_s() == pytest.approx(0.2, rel=0.01)

    def test_deadline_verdict_shapes(self):
        v = qos.deadline_verdict("novel", 0.001, queued=0, max_concurrent=4)
        assert v["admit"] and v["predicted_s"] is None  # no evidence: admit
        qos.COST_MODEL.update("known", 0.5)
        v = qos.deadline_verdict("known", 0.01, queued=0, max_concurrent=4)
        assert not v["admit"] and v["expected_s"] >= 0.5
        v = qos.deadline_verdict("known", 60.0, queued=8, max_concurrent=4)
        assert v["admit"]


# ---------------------------------------------------------------------------
# scheduler integration: door rejections, SLO, pinned single-tenant order
# ---------------------------------------------------------------------------

class TestSchedulerQoS:
    def test_single_tenant_dispatch_order_pinned_to_fifo_priority(self):
        """The QoS-off contract: with one (default) tenant, execution
        order is EXACTLY the pre-QoS FIFO+priority order."""
        sched = serve.QueryScheduler(max_concurrent=1, queue_depth=16)
        order: list = []
        gate = threading.Event()
        try:
            blocker = sched.submit(lambda: gate.wait(30), label="blocker")
            hs = [
                sched.submit(lambda t=tag: order.append(t), priority=pri,
                             label=str(tag))
                for tag, pri in [
                    ("l0", 0), ("h0", 5), ("l1", 0), ("m0", 3), ("h1", 5),
                ]
            ]
            gate.set()
            blocker.result(30)
            for h in hs:
                h.result(30)
            assert order == ["h0", "h1", "m0", "l0", "l1"]
        finally:
            sched.shutdown()

    def test_quota_rejection_typed_and_distinct(self):
        TENANTS.configure("capped", max_in_flight=1)
        sched = serve.QueryScheduler(max_concurrent=1, queue_depth=16)
        gate = threading.Event()
        try:
            running = sched.submit(lambda: gate.wait(30), tenant="capped")
            before = REGISTRY.counter("serve.tenant.rejected.quota").value
            with pytest.raises(TenantQuotaExceeded) as ei:
                sched.submit(lambda: 2, tenant="capped")
            # distinct from global shedding: NOT an AdmissionRejected
            assert not isinstance(ei.value, serve.AdmissionRejected)
            assert REGISTRY.counter(
                "serve.tenant.rejected.quota"
            ).value == before + 1
            # other tenants are untouched by the capped tenant's quota
            ok = sched.submit(lambda: 3, tenant="other")
            gate.set()
            assert running.result(30) is not None or True
            assert ok.result(30) == 3
            st = sched.state()["tenants"]
            assert st["capped"]["rejected_quota"] == 1
        finally:
            sched.shutdown()

    def test_rate_limit_rejection_typed(self):
        TENANTS.configure("bursty", rate_qps=0.001, burst=1)
        sched = serve.QueryScheduler(max_concurrent=2, queue_depth=16)
        try:
            ok = sched.submit(lambda: 1, tenant="bursty")
            assert ok.result(30) == 1
            before = REGISTRY.counter("serve.tenant.rejected.rate").value
            with pytest.raises(TenantQuotaExceeded):
                sched.submit(lambda: 2, tenant="bursty")  # bucket drained
            assert REGISTRY.counter(
                "serve.tenant.rejected.rate"
            ).value == before + 1
        finally:
            sched.shutdown()

    def test_deadline_unmeetable_rejects_fast_at_submit(self):
        qos.COST_MODEL.update("slow_label", 0.5)  # 500ms observed history
        sched = serve.QueryScheduler(max_concurrent=1, queue_depth=16)
        try:
            before = REGISTRY.counter("serve.tenant.rejected.deadline").value
            t0 = time.perf_counter()
            with pytest.raises(serve.DeadlineUnmeetable) as ei:
                sched.submit(lambda: 1, label="slow_label", deadline_s=0.01)
            assert time.perf_counter() - t0 < 0.2  # rejected at the door
            assert isinstance(ei.value, serve.AdmissionRejected)  # IS shedding
            assert REGISTRY.counter(
                "serve.tenant.rejected.deadline"
            ).value == before + 1
            # a generous deadline admits, runs, and observes its prediction
            h = sched.submit(lambda: 7, label="slow_label", deadline_s=60.0)
            assert h.result(30) == 7
            assert REGISTRY.histogram(
                "estimator.qerror.serve.wall"
            ).value["count"] >= 1
        finally:
            sched.shutdown()

    def test_declined_degrade_feeds_exact_label_not_tier(self, monkeypatch):
        """A degraded admit whose sampled tier never ENGAGES at collect
        time (ineligible plan, missing twins) runs exact — its wall must
        feed the EXACT label's EWMA. An exact wall recorded under the
        tier label would inflate the tier EWMA and skew every future
        choose_degrade_tier pick."""
        monkeypatch.setenv("HYPERSPACE_APPROX", "1")
        qos.COST_MODEL.update("deg_label", 0.5)  # teach a slow exact wall
        sched = serve.QueryScheduler(max_concurrent=1, queue_depth=16)
        try:
            h = sched.submit(lambda: 11, label="deg_label", deadline_s=0.01)
            assert h.result(30) == 11
            f = h.ctx.approx_fraction
            assert f is not None  # degraded at the door, not rejected
            # the callable never engaged the sampled tier: the tier label
            # stays unobserved, the exact label learned the fast run
            assert qos.COST_MODEL.predict(qos.tier_label("deg_label", f)) is None
            assert qos.COST_MODEL.predict("deg_label") < 0.5
        finally:
            sched.shutdown()

    def test_deadline_without_history_admits(self):
        sched = serve.QueryScheduler(max_concurrent=1, queue_depth=4)
        try:
            h = sched.submit(lambda: 5, label="never_seen", deadline_s=1e-6)
            assert h.result(30) == 5
        finally:
            sched.shutdown()

    def test_aging_unstarves_low_priority_under_flood(self, monkeypatch):
        """Regression for the starvation satellite: a priority-0 query
        completes WHILE a high-priority flood is still being sustained,
        because its aged effective priority catches up."""
        monkeypatch.setenv("HYPERSPACE_SERVE_AGING_MS", "5")
        sched = serve.QueryScheduler(max_concurrent=1, queue_depth=256)
        stop = threading.Event()
        low_done = threading.Event()
        flooded = {"n": 0}

        def flood():
            while not stop.is_set() and not low_done.is_set():
                try:
                    sched.submit(lambda: time.sleep(0.002), priority=10,
                                 label="flood")
                    flooded["n"] += 1
                except serve.AdmissionRejected:
                    pass
                time.sleep(0.001)

        t = threading.Thread(target=flood, name="qos-flood")
        try:
            t.start()
            time.sleep(0.05)  # flood established
            low = sched.submit(lambda: low_done.set(), priority=0, label="low")
            low.result(30)  # must complete while the flood is sustained
            assert low_done.is_set()
            assert flooded["n"] > 10  # the flood genuinely ran around it
        finally:
            stop.set()
            t.join(timeout=30)
            sched.shutdown(wait=True, cancel=True)

    def test_tenant_rides_query_record_and_rollups(self):
        from hyperspace_tpu.telemetry.attribution import LEDGER

        sched = serve.QueryScheduler(max_concurrent=2, queue_depth=8)
        try:
            h = sched.submit(lambda: 1, tenant="acme", label="tagged")
            h.result(30)
        finally:
            sched.shutdown()
        recent = LEDGER.recent_records()
        mine = [r for r in recent if r["label"] == "tagged"]
        assert mine and mine[-1]["tenant"] == "acme"
        rollups = LEDGER.tenant_rollups()
        assert rollups["acme"]["queries"] >= 1
        assert rollups["acme"]["outcomes"].get("done", 0) >= 1
        # per-tenant counter sums reproduce the flat aggregate exactly
        by_tenant = LEDGER.aggregate_counters_by_tenant()
        flat = LEDGER.aggregate_counters()
        summed: dict = {}
        for counters in by_tenant.values():
            for k, v in counters.items():
                summed[k] = summed.get(k, 0) + v
        assert summed == flat


# ---------------------------------------------------------------------------
# per-tenant budget partitioning
# ---------------------------------------------------------------------------

class TestBudgetPartition:
    def test_hog_tenant_cannot_pin_whole_ledger(self):
        """With two tenants holding bytes, each is capped at its share: the
        hog stalls at 50% (equal weights) while the light tenant keeps
        reserving within its own partition."""
        acct = BudgetAccountant(1000)
        hog = acct.stream("scan", query=1, tenant="hog")
        light = acct.stream("scan", query=2, tenant="light")
        assert hog.try_reserve(450)  # sole holder: only the global limit
        assert light.try_reserve(100)
        before = REGISTRY.counter("serve.budget.tenant_stalls").value
        assert not hog.try_reserve(200)  # 650 > 500 share, global had room
        assert REGISTRY.counter(
            "serve.budget.tenant_stalls"
        ).value == before + 1
        assert light.try_reserve(200)  # light is within its 500 share
        assert acct.held_bytes() == 750
        st = acct.state()
        assert st["tenants"] == {"hog": 450, "light": 300}
        hog.close()
        light.close()
        assert acct.held_bytes() == 0

    def test_budget_fraction_overrides_weight_share(self):
        TENANTS.configure("vip", budget_fraction=0.9)
        TENANTS.configure("bulk", weight=100.0)  # weight would dwarf vip
        acct = BudgetAccountant(1000)
        vip = acct.stream("scan", tenant="vip")
        bulk = acct.stream("scan", tenant="bulk")
        assert bulk.try_reserve(100)
        assert vip.try_reserve(500)
        assert vip.try_reserve(300)  # 800 <= 900 explicit fraction
        assert not vip.try_reserve(150)  # 950 > 900
        vip.close()
        bulk.close()

    def test_single_tenant_never_consults_partition(self):
        """One tenant (or tenantless streams) ⇒ pre-QoS semantics exactly:
        only the global limit stalls, and never as a tenant stall."""
        acct = BudgetAccountant(1000)
        s1 = acct.stream("scan", tenant="only")
        s2 = acct.stream("join", tenant="only")
        before = REGISTRY.counter("serve.budget.tenant_stalls").value
        assert s1.try_reserve(600)
        assert s2.try_reserve(300)  # 90% by ONE tenant: no partition stall
        assert not s2.try_reserve(200)  # global limit, as before QoS
        assert REGISTRY.counter("serve.budget.tenant_stalls").value == before
        s1.close()
        s2.close()

    def test_zero_holder_progress_grant_survives_partitioning(self):
        """The deadlock-freedom progress guarantee is tenant-blind: a
        zero-holder stream is granted even when its tenant's partition and
        the global ledger are both saturated."""
        acct = BudgetAccountant(100)
        hog = acct.stream("scan", tenant="a")
        other = acct.stream("scan", tenant="b")
        assert hog.try_reserve(100)
        assert other.try_reserve(60)  # zero holder: forced past everything
        assert acct.held_bytes() == 160
        hog.close()
        other.close()


# ---------------------------------------------------------------------------
# adversarial integration: hog vs light tenants through one scheduler
# ---------------------------------------------------------------------------

def _write_multifile(root, n_files=6, rows=2500, seed=3):
    rng = np.random.default_rng(seed)
    for i in range(n_files):
        n = rows + i * 97
        data = {
            "k": rng.integers(0, 40, n).tolist(),
            "x": rng.uniform(0, 100, n).tolist(),
            "q": rng.integers(1, 50, n).tolist(),
        }
        cio.write_parquet(
            ColumnBatch.from_pydict(data),
            os.path.join(root, "t", f"part-{i}.parquet"),
        )


class TestHogVsLightIsolation:
    def test_light_tenant_p99_wait_improves_and_results_exact(
        self, tmp_path, monkeypatch
    ):
        """1 hog tenant floods heavy scans ahead of 8 light tenants. QoS
        off (everyone on the default tenant = the old FIFO scheduler) the
        lights wait behind the whole hog backlog; QoS on (per-tenant WFQ)
        their p99 queue wait must be STRICTLY lower — and every served
        result stays bit-identical to serial either way."""
        _write_multifile(str(tmp_path))
        monkeypatch.setenv("HYPERSPACE_IO_THREADS", "2")
        session = HyperspaceSession(warehouse_dir=str(tmp_path))
        session.set_conf(C.EXEC_TPU_ENABLED, True)

        def heavy():
            return (
                session.read.parquet(os.path.join(str(tmp_path), "t"))
                .filter(col("q") > 2)
                .agg(Sum(col("x")).alias("sx"), Count(lit(1)).alias("n"))
            )

        def light():
            return (
                session.read.parquet(os.path.join(str(tmp_path), "t"))
                .filter(col("q") > 45)
                .agg(Count(lit(1)).alias("n"))
            )

        expected = {
            "heavy": _bits(heavy().collect().to_pydict()),
            "light": _bits(light().collect().to_pydict()),
        }
        n_hog, n_light_tenants = 10, 8

        def run_leg(use_tenants: bool) -> list:
            serve.reset_global_budget()
            sched = serve.QueryScheduler(max_concurrent=1, queue_depth=256)
            try:
                hog_handles = [
                    sched.submit_query(
                        heavy(), label="hog",
                        tenant="hog" if use_tenants else None,
                    )
                    for _ in range(n_hog)
                ]
                light_handles = [
                    sched.submit_query(
                        light(), label=f"light{i}",
                        tenant=f"light{i}" if use_tenants else None,
                    )
                    for i in range(n_light_tenants)
                ]
                for h in hog_handles:
                    assert _bits(h.result(120).to_pydict()) == expected["heavy"]
                waits = []
                for h in light_handles:
                    assert _bits(h.result(120).to_pydict()) == expected["light"]
                    waits.append(h.queue_wait_s)
                return sorted(waits)
            finally:
                sched.shutdown()

        waits_off = run_leg(use_tenants=False)
        waits_on = run_leg(use_tenants=True)
        p99_off = waits_off[-1]
        p99_on = waits_on[-1]
        # off: every light waits behind the full 10-query hog backlog;
        # on: WFQ lets each light run after ~1 hog completion
        assert p99_on < p99_off
        assert sum(waits_on) < sum(waits_off)


# ---------------------------------------------------------------------------
# surfaces: state, profile, exporter, hs_top
# ---------------------------------------------------------------------------

class TestQoSSurfaces:
    def test_scheduler_state_tenants_block(self):
        TENANTS.configure("gold", weight=4.0)
        sched = serve.QueryScheduler(max_concurrent=2, queue_depth=8)
        try:
            sched.submit(lambda: 1, tenant="gold").result(30)
            sched.submit(lambda: 2).result(30)
            st = sched.state()["tenants"]
            assert st["gold"]["weight"] == 4.0
            assert st["gold"]["done"] == 1 and st["default"]["done"] == 1
            assert st["gold"]["cost_s"] > 0
            assert 0 < st["gold"]["delivered_share"] < 1
        finally:
            sched.shutdown()

    def test_tenant_state_string_renders(self):
        from hyperspace_tpu.analysis.explain import tenant_state_string

        sched = serve.QueryScheduler(max_concurrent=1, queue_depth=4)
        try:
            sched.submit(lambda: 1, tenant="renderme").result(30)
        finally:
            sched.shutdown()
        s = tenant_state_string()
        assert "Tenants" in s and "renderme" in s

    def test_snapshot_and_prometheus_carry_tenants(self):
        from hyperspace_tpu.telemetry import exporter

        sched = serve.QueryScheduler(max_concurrent=1, queue_depth=4)
        try:
            sched.submit(lambda: 1, tenant="promtest").result(30)
        finally:
            sched.shutdown()
        snap = exporter.snapshot_dict()
        assert "promtest" in snap["tenants"]["rollups"]
        text = exporter.prometheus_text()
        assert 'hyperspace_serve_tenant_queries{tenant="promtest"}' in text

    def test_hs_top_renders_tenant_table(self):
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "hs_top", os.path.join(
                os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                "tools", "hs_top.py",
            ),
        )
        hs_top = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(hs_top)
        snap = {
            "ts": time.time(),
            "serving": {"active": [], "queued": [], "totals": {},
                        "budget": {}},
            "queries": {"recent": [
                {"query_id": 1, "label": "q", "tenant": "acme",
                 "priority": 0, "outcome": "done", "total_ms": 1.0,
                 "queue_wait_ms": 0.1, "bytes_read": 0,
                 "cache_hit_ratio": None, "budget_stalls": 0,
                 "phases_ms": {}},
            ], "totals": {}},
            "tenants": {
                "scheduler": {"acme": {"weight": 2.0, "vclock": 1.5,
                                       "delivered_share": 1.0, "queued": 0,
                                       "active": 0, "done": 3,
                                       "rejected_rate": 1,
                                       "rejected_quota": 0,
                                       "rejected_deadline": 0}},
                "rollups": {"acme": {"queries": 3, "bytes_read": 1024,
                                     "total_ms": 5.0}},
            },
            "breaker": {"state": "closed"},
        }
        out = hs_top.render(snap)
        assert "TENANTS" in out and "acme" in out
