"""Bit-level Z-order properties (ref: ZOrderFieldTest.scala — 1651 LoC of
per-type bit assertions; here the same guarantees as properties):

- min-max scaling maps vmin->0 and vmax->2^n-1, monotonically;
- percentile bucketing is monotone nondecreasing and respects boundaries;
- interleave_bits matches an independent pure-python big-int reference
  bit-for-bit, MSB-first round-robin with drop-out;
- the device (jnp/uint32) interleave agrees with the host (numpy/uint64)
  variant on shared widths;
- z-ordering clusters: Chebyshev-adjacent points differ less in z than
  distant ones on average (locality property).
"""

import numpy as np
import pytest

from hyperspace_tpu.models.zorder.fields import (
    MinMaxZOrderField,
    PercentileZOrderField,
    build_field,
)
from hyperspace_tpu.columnar.table import Column
from hyperspace_tpu.ops.zorder import interleave_bits, interleave_bits_jnp


def _py_reference_interleave(fields):
    """Independent reference: python big-int, MSB-first round-robin, fields
    drop out of rotation when their bits are exhausted."""
    n = len(fields[0][0])
    total = sum(nb for _, nb in fields)
    out = []
    for i in range(n):
        bits = []
        max_nb = max(nb for _, nb in fields)
        for level in range(max_nb):
            for codes, nb in fields:
                if level < nb:
                    bits.append((int(codes[i]) >> (nb - 1 - level)) & 1)
        v = 0
        for b in bits:
            v = (v << 1) | b
        assert len(bits) == total
        out.append(v)
    return out


class TestMinMaxScaling:
    def test_extremes_and_monotonicity(self):
        f = MinMaxZOrderField("x", vmin=-50.0, vmax=150.0, nbits=10)
        vals = np.linspace(-50.0, 150.0, 1000)
        codes = f.codes(Column(vals, "float64"))
        assert codes[0] == 0
        assert codes[-1] == (1 << 10) - 1
        assert (np.diff(codes.astype(np.int64)) >= 0).all()

    def test_int_column_exact_small_domain(self):
        # a domain smaller than 2^nbits must preserve ORDER exactly
        f = MinMaxZOrderField("x", vmin=0, vmax=7, nbits=3)
        codes = f.codes(Column(np.arange(8, dtype=np.int64), "int64"))
        assert (np.diff(codes.astype(np.int64)) > 0).all()
        assert codes[0] == 0 and codes[-1] == 7

    def test_constant_column(self):
        f = MinMaxZOrderField.from_column(
            "x", Column(np.full(10, 42.0), "float64"), nbits=8
        )
        codes = f.codes(Column(np.full(10, 42.0), "float64"))
        assert (codes == codes[0]).all()

    def test_out_of_range_values_clamp(self):
        # refresh can see values outside the recorded min/max: codes must
        # clamp, not wrap
        f = MinMaxZOrderField("x", vmin=0.0, vmax=100.0, nbits=8)
        codes = f.codes(Column(np.array([-10.0, 200.0]), "float64"))
        assert codes[0] == 0
        assert codes[1] == (1 << 8) - 1


class TestPercentileBuckets:
    def test_monotone_and_skew_resistant(self):
        rng = np.random.default_rng(5)
        # heavy skew: 99% of mass in [0, 1), tail to 1e6
        vals = np.where(rng.random(20000) < 0.99, rng.random(20000), 1e6)
        col = Column(vals, "float64")
        f = PercentileZOrderField.from_column("x", col, nbits=6)
        codes = f.codes(col)
        order = np.argsort(vals, kind="stable")
        assert (np.diff(codes[order].astype(np.int64)) >= 0).all()
        # skew resistance: the dense region must not collapse to one code
        dense = codes[vals < 1.0]
        assert len(np.unique(dense)) > (1 << 6) // 4

    def test_roundtrip_serialization(self):
        rng = np.random.default_rng(6)
        col = Column(rng.random(1000), "float64")
        f = PercentileZOrderField.from_column("x", col, nbits=5)
        d = f.to_dict()
        g = PercentileZOrderField.from_dict(d)
        assert (f.codes(col) == g.codes(col)).all()


class TestInterleave:
    @pytest.mark.parametrize("widths", [(8, 8), (10, 6), (5, 5, 5), (12, 3, 1), (16,)])
    def test_matches_pure_python_reference(self, widths):
        rng = np.random.default_rng(sum(widths))
        fields = [
            (rng.integers(0, 1 << w, 200).astype(np.uint64), w) for w in widths
        ]
        got = interleave_bits(fields)
        expect = _py_reference_interleave(fields)
        assert [int(v) for v in got] == expect

    def test_device_variant_agrees_with_host(self):
        import jax.numpy as jnp

        rng = np.random.default_rng(9)
        a = rng.integers(0, 1 << 10, 500).astype(np.uint64)
        b = rng.integers(0, 1 << 10, 500).astype(np.uint64)
        host = interleave_bits([(a, 10), (b, 10)])
        dev = interleave_bits_jnp(
            [(jnp.asarray(a.astype(np.uint32)), 10), (jnp.asarray(b.astype(np.uint32)), 10)]
        )
        assert (np.asarray(dev).astype(np.uint64) == host).all()

    def test_locality(self):
        """Z-order's point: close points in (x, y) stay close in z."""
        f = [(np.arange(32, dtype=np.uint64).repeat(32), 5),
             (np.tile(np.arange(32, dtype=np.uint64), 32), 5)]
        z = interleave_bits(f).astype(np.int64)
        x, y = f[0][0].astype(int), f[1][0].astype(int)
        rng = np.random.default_rng(11)
        idx = rng.integers(0, len(z), 500)
        jdx = rng.integers(0, len(z), 500)
        cheb = np.maximum(np.abs(x[idx] - x[jdx]), np.abs(y[idx] - y[jdx]))
        zdist = np.abs(z[idx] - z[jdx])
        near = zdist[cheb <= 2]
        far = zdist[cheb >= 16]
        assert len(near) and len(far)
        assert near.mean() < far.mean() / 4


class TestBuildField:
    def test_dispatch_by_quantile_flag(self):
        rng = np.random.default_rng(12)
        col = Column(rng.random(5000), "float64")
        f1 = build_field("x", col, use_percentile=False)
        f2 = build_field("x", col, use_percentile=True)
        assert isinstance(f1, MinMaxZOrderField)
        assert isinstance(f2, PercentileZOrderField)
