"""Pallas kernels (interpreter mode on CPU) and distributed aggregation."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from hyperspace_tpu.ops.pallas_kernels import filter_weighted_sum, masked_min_max
from hyperspace_tpu.parallel.dist_agg import distributed_filter_aggregate, shard_columns
from hyperspace_tpu.parallel.mesh import device_mesh


class TestPallasKernels:
    def test_filter_weighted_sum(self):
        rng = np.random.default_rng(0)
        n = 5000  # not a multiple of the block size: exercises padding
        x = rng.uniform(1, 10, n).astype(np.float32)
        y = rng.uniform(0, 1, n).astype(np.float32)
        pred = rng.random(n) < 0.3
        rev, cnt = filter_weighted_sum(
            jnp.asarray(pred), jnp.asarray(x), jnp.asarray(y)
        )
        expect = float((x[pred] * y[pred]).sum())
        assert abs(float(rev) - expect) / expect < 1e-4
        assert int(cnt) == int(pred.sum())

    def test_filter_weighted_sum_empty_selection(self):
        n = 1024
        z = jnp.zeros(n, dtype=bool)
        rev, cnt = filter_weighted_sum(z, jnp.ones(n), jnp.ones(n))
        assert float(rev) == 0.0 and int(cnt) == 0

    def test_masked_min_max(self):
        rng = np.random.default_rng(1)
        x = rng.uniform(-100, 100, 3000).astype(np.float32)
        valid = rng.random(3000) < 0.5
        mn, mx = masked_min_max(jnp.asarray(x), jnp.asarray(valid))
        assert np.isclose(float(mn), x[valid].min())
        assert np.isclose(float(mx), x[valid].max())


class TestDistributedAggregate:
    def test_q6_shape_over_mesh(self):
        mesh = device_mesh()
        rng = np.random.default_rng(2)
        n = 10_000
        cols_np = {
            "d": rng.integers(0, 100, n).astype(np.int32),
            "x": rng.uniform(1, 10, n).astype(np.float32),
            "y": rng.uniform(0, 1, n).astype(np.float32),
        }
        cols, mask = shard_columns(mesh, cols_np)
        out = distributed_filter_aggregate(
            mesh,
            cols,
            mask,
            pred_fn=lambda c: (c["d"] >= 20) & (c["d"] < 60),
            agg_fns={
                "rev": lambda c, m: jnp.where(m, c["x"] * c["y"], 0).sum(),
                "n": lambda c, m: m.sum(),
            },
        )
        sel = (cols_np["d"] >= 20) & (cols_np["d"] < 60)
        expect = float((cols_np["x"][sel] * cols_np["y"][sel]).sum())
        assert abs(float(out["rev"]) - expect) / expect < 1e-4
        assert int(out["n"]) == int(sel.sum())

    def test_ragged_row_count(self):
        mesh = device_mesh()
        n = 1001  # not divisible by 8: padding + mask must hide pad rows
        cols, mask = shard_columns(mesh, {"v": np.ones(n, dtype=np.float32)})
        out = distributed_filter_aggregate(
            mesh,
            cols,
            mask,
            pred_fn=lambda c: c["v"] > 0,
            agg_fns={"n": lambda c, m: m.sum()},
        )
        assert int(out["n"]) == n


class TestFilterSumKernel:
    def test_filter_sum_matches_numpy(self):
        import numpy as np
        import jax.numpy as jnp

        from hyperspace_tpu.ops.pallas_kernels import filter_sum

        rng = np.random.default_rng(2)
        n = 5000
        pred = rng.uniform(size=n) < 0.3
        x = rng.uniform(0, 100, n).astype(np.float32)
        s, cnt = filter_sum(jnp.asarray(pred), jnp.asarray(x))
        assert int(cnt) == int(pred.sum())
        assert float(s) == pytest.approx(float(x[pred].sum()), rel=1e-5)

    def test_pallas_single_sum_shape_forced(self, tmp_session, tmp_path, monkeypatch):
        """filter -> sum(col)+count routes through the Pallas tier when
        forced, matching the generic path."""
        import numpy as np

        from hyperspace_tpu import constants as C
        from hyperspace_tpu.columnar import io as cio
        from hyperspace_tpu.columnar.table import ColumnBatch
        from hyperspace_tpu.plan import Count, Sum, col, lit
        from hyperspace_tpu.plan import tpu_exec

        rng = np.random.default_rng(9)
        n = 6000
        cio.write_parquet(
            ColumnBatch.from_pydict(
                {
                    "d": rng.integers(0, 100, n).tolist(),
                    "x": rng.uniform(0, 10, n).tolist(),
                }
            ),
            str(tmp_path / "t" / "p.parquet"),
        )
        df = tmp_session.read.parquet(str(tmp_path / "t"))
        q = lambda: df.filter(col("d") < 50).agg(
            Sum(col("x")).alias("s"), Count(lit(1)).alias("n")
        ).to_pydict()
        host = q()
        monkeypatch.setenv("HYPERSPACE_FORCE_PALLAS", "1")
        tpu_exec._KERNEL_CACHE.clear()
        tmp_session.set_conf(C.EXEC_TPU_ENABLED, True)
        dev = q()
        tmp_session.set_conf(C.EXEC_TPU_ENABLED, False)
        tpu_exec._KERNEL_CACHE.clear()
        assert dev["n"] == host["n"]
        assert dev["s"][0] == pytest.approx(host["s"][0], rel=1e-5)

    def test_pallas_grouped_sum_shape_forced(self, tmp_session, tmp_path, monkeypatch):
        """GROUP BY low-cardinality keys with sum+count (the Q1 fragment)
        routes through the Pallas streaming histogram when forced, matching
        the generic segment-sum path."""
        import numpy as np

        from hyperspace_tpu import constants as C
        from hyperspace_tpu.columnar import io as cio
        from hyperspace_tpu.columnar.table import ColumnBatch
        from hyperspace_tpu.plan import Count, Sum, col, lit
        from hyperspace_tpu.plan import tpu_exec

        rng = np.random.default_rng(21)
        n = 9000
        cio.write_parquet(
            ColumnBatch.from_pydict(
                {
                    "g": rng.choice(["a", "b", "c", "d"], n).tolist(),
                    "d": rng.integers(0, 100, n).tolist(),
                    "x": rng.uniform(0, 10, n).tolist(),
                }
            ),
            str(tmp_path / "tg" / "p.parquet"),
        )
        df = tmp_session.read.parquet(str(tmp_path / "tg"))
        q = lambda: (
            df.filter(col("d") < 60)
            .select("g", "x")
            .group_by("g")
            .agg(Sum(col("x")).alias("s"), Count(lit(1)).alias("n"))
            .sort("g")
            .to_pydict()
        )
        host = q()
        monkeypatch.setenv("HYPERSPACE_FORCE_PALLAS", "1")
        tpu_exec._KERNEL_CACHE.clear()
        tmp_session.set_conf(C.EXEC_TPU_ENABLED, True)
        dev = q()
        tmp_session.set_conf(C.EXEC_TPU_ENABLED, False)
        tpu_exec._KERNEL_CACHE.clear()
        assert dev["g"] == host["g"] and dev["n"] == host["n"]
        assert np.allclose(dev["s"], host["s"], rtol=1e-5)

    def test_pallas_grouped_int_sum_stays_exact(self, tmp_session, tmp_path, monkeypatch):
        """Int sums through the forced-Pallas grouped route fall back to the
        exact chunked accumulation at trace time."""
        import numpy as np

        from hyperspace_tpu import constants as C
        from hyperspace_tpu.columnar import io as cio
        from hyperspace_tpu.columnar.table import ColumnBatch
        from hyperspace_tpu.plan import Sum, col
        from hyperspace_tpu.plan import tpu_exec

        rng = np.random.default_rng(22)
        n = 8000
        cio.write_parquet(
            ColumnBatch.from_pydict(
                {
                    "g": rng.integers(0, 3, n).tolist(),
                    "v": rng.integers(16_000_000, 17_000_000, n).astype(int).tolist(),
                }
            ),
            str(tmp_path / "ti" / "p.parquet"),
        )
        df = tmp_session.read.parquet(str(tmp_path / "ti"))
        q = lambda: df.group_by("g").agg(Sum(col("v")).alias("s")).sort("g").to_pydict()
        host = q()
        monkeypatch.setenv("HYPERSPACE_FORCE_PALLAS", "1")
        tpu_exec._KERNEL_CACHE.clear()
        tmp_session.set_conf(C.EXEC_TPU_ENABLED, True)
        dev = q()
        tmp_session.set_conf(C.EXEC_TPU_ENABLED, False)
        tpu_exec._KERNEL_CACHE.clear()
        assert dev == host  # exact int64 equality

    def test_pallas_declines_int_sum(self, tmp_session, tmp_path, monkeypatch):
        """Int sums through the forced-Pallas route must stay EXACT (the
        trace-time dtype guard falls back to chunked accumulation)."""
        import numpy as np

        from hyperspace_tpu import constants as C
        from hyperspace_tpu.columnar import io as cio
        from hyperspace_tpu.columnar.table import ColumnBatch
        from hyperspace_tpu.plan import Count, Sum, col, lit
        from hyperspace_tpu.plan import tpu_exec

        rng = np.random.default_rng(10)
        vals = rng.integers(-(2**30), 2**30, 9000)
        cio.write_parquet(
            ColumnBatch.from_pydict(
                {"d": rng.integers(0, 100, 9000).tolist(), "v": vals.tolist()}
            ),
            str(tmp_path / "t" / "p.parquet"),
        )
        df = tmp_session.read.parquet(str(tmp_path / "t"))
        q = lambda: df.filter(col("d") < 50).agg(
            Sum(col("v")).alias("s"), Count(lit(1)).alias("n")
        ).to_pydict()
        host = q()
        monkeypatch.setenv("HYPERSPACE_FORCE_PALLAS", "1")
        tpu_exec._KERNEL_CACHE.clear()
        tmp_session.set_conf(C.EXEC_TPU_ENABLED, True)
        dev = q()
        tmp_session.set_conf(C.EXEC_TPU_ENABLED, False)
        tpu_exec._KERNEL_CACHE.clear()
        assert dev["s"] == host["s"]  # exact int64 equality
