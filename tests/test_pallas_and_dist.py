"""Pallas kernels (interpreter mode on CPU) and distributed aggregation."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from hyperspace_tpu.ops.pallas_kernels import filter_weighted_sum, masked_min_max
from hyperspace_tpu.parallel.dist_agg import distributed_filter_aggregate, shard_columns
from hyperspace_tpu.parallel.mesh import device_mesh


class TestPallasKernels:
    def test_filter_weighted_sum(self):
        rng = np.random.default_rng(0)
        n = 5000  # not a multiple of the block size: exercises padding
        x = rng.uniform(1, 10, n).astype(np.float32)
        y = rng.uniform(0, 1, n).astype(np.float32)
        pred = rng.random(n) < 0.3
        rev, cnt = filter_weighted_sum(
            jnp.asarray(pred), jnp.asarray(x), jnp.asarray(y)
        )
        expect = float((x[pred] * y[pred]).sum())
        assert abs(float(rev) - expect) / expect < 1e-4
        assert int(cnt) == int(pred.sum())

    def test_filter_weighted_sum_empty_selection(self):
        n = 1024
        z = jnp.zeros(n, dtype=bool)
        rev, cnt = filter_weighted_sum(z, jnp.ones(n), jnp.ones(n))
        assert float(rev) == 0.0 and int(cnt) == 0

    def test_masked_min_max(self):
        rng = np.random.default_rng(1)
        x = rng.uniform(-100, 100, 3000).astype(np.float32)
        valid = rng.random(3000) < 0.5
        mn, mx = masked_min_max(jnp.asarray(x), jnp.asarray(valid))
        assert np.isclose(float(mn), x[valid].min())
        assert np.isclose(float(mx), x[valid].max())


class TestDistributedAggregate:
    def test_q6_shape_over_mesh(self):
        mesh = device_mesh()
        rng = np.random.default_rng(2)
        n = 10_000
        cols_np = {
            "d": rng.integers(0, 100, n).astype(np.int32),
            "x": rng.uniform(1, 10, n).astype(np.float32),
            "y": rng.uniform(0, 1, n).astype(np.float32),
        }
        cols, mask = shard_columns(mesh, cols_np)
        out = distributed_filter_aggregate(
            mesh,
            cols,
            mask,
            pred_fn=lambda c: (c["d"] >= 20) & (c["d"] < 60),
            agg_fns={
                "rev": lambda c, m: jnp.where(m, c["x"] * c["y"], 0).sum(),
                "n": lambda c, m: m.sum(),
            },
        )
        sel = (cols_np["d"] >= 20) & (cols_np["d"] < 60)
        expect = float((cols_np["x"][sel] * cols_np["y"][sel]).sum())
        assert abs(float(out["rev"]) - expect) / expect < 1e-4
        assert int(out["n"]) == int(sel.sum())

    def test_ragged_row_count(self):
        mesh = device_mesh()
        n = 1001  # not divisible by 8: padding + mask must hide pad rows
        cols, mask = shard_columns(mesh, {"v": np.ones(n, dtype=np.float32)})
        out = distributed_filter_aggregate(
            mesh,
            cols,
            mask,
            pred_fn=lambda c: c["v"] > 0,
            agg_fns={"n": lambda c, m: m.sum()},
        )
        assert int(out["n"]) == n
