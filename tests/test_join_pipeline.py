"""Pipelined, skew-aware bucketed join execution tests.

The streamed + banded join path (plan/bucket_join._iter_bucket_pairs feeding
device_join's band-stacked probe / stacked fused aggregate) must be
bit-identical to the ``HYPERSPACE_PIPELINE=0`` barrier + global-pad path on
every fixture — uniform keys, a heavily skewed hot key, empty buckets, and
split oversized buckets — and a warm repeat join must be served entirely
from the kernel cache (zero ``compile:*`` spans)."""

import numpy as np
import pytest

from hyperspace_tpu import CoveringIndexConfig, Hyperspace
from hyperspace_tpu import constants as C
from hyperspace_tpu.columnar import io as cio
from hyperspace_tpu.columnar.table import ColumnBatch
from hyperspace_tpu.plan import Count, Max, Min, Sum, col, lit
from hyperspace_tpu.telemetry.metrics import REGISTRY


def hex_rows(d: dict) -> str:
    """Bit-exact repr: floats rendered via .hex() so f32/f64 accumulation
    differences can never hide behind printing."""
    return repr(
        {
            k: [x.hex() if isinstance(x, float) else x for x in v]
            for k, v in d.items()
        }
    )


def _write_sides(tmp_path, left, right):
    cio.write_parquet(
        ColumnBatch.from_pydict(left), str(tmp_path / "l" / "l.parquet")
    )
    cio.write_parquet(
        ColumnBatch.from_pydict(right), str(tmp_path / "r" / "r.parquet")
    )


def _index_sides(session, tmp_path, buckets=4):
    session.set_conf(C.INDEX_NUM_BUCKETS, buckets)
    hs = Hyperspace(session)
    hs.create_index(
        session.read.parquet(str(tmp_path / "l")),
        CoveringIndexConfig("jl", ["k"], ["p"]),
    )
    hs.create_index(
        session.read.parquet(str(tmp_path / "r")),
        CoveringIndexConfig("jr", ["rk"], ["w"]),
    )
    return hs


@pytest.fixture()
def skew_env(tmp_session, tmp_path):
    """Heavily skewed left side: 40% of rows carry ONE hot key, so one
    bucket dwarfs the rest — the banding/splitting target shape — plus
    right keys missing from a range so some buckets come up empty."""
    rng = np.random.default_rng(101)
    n = 24_000
    k = rng.integers(0, 400, n)
    k[: int(n * 0.4)] = 7  # hot key -> one monster bucket
    left = {"k": k.tolist(), "p": rng.uniform(0, 100, n).tolist()}
    # only low keys on the right: high-key buckets join to nothing
    right = {"rk": list(range(0, 200)), "w": rng.uniform(size=200).tolist()}
    _write_sides(tmp_path, left, right)
    _index_sides(tmp_session, tmp_path)
    return tmp_session, tmp_path


def _plain_q(session, tmp_path):
    l = session.read.parquet(str(tmp_path / "l")).select("k", "p")
    r = session.read.parquet(str(tmp_path / "r")).select("rk", "w")
    return l.join(r, col("k") == col("rk")).select("k", "p", "w")


def _agg_q(session, tmp_path):
    l = session.read.parquet(str(tmp_path / "l")).select("k", "p")
    r = session.read.parquet(str(tmp_path / "r")).select("rk", "w")
    return (
        l.join(r, col("k") == col("rk"))
        .group_by("k")
        .agg(Sum(col("p")).alias("s"))
    )


def _foldable_agg_q(session, tmp_path):
    # count/min/max only: the split-eligible aggregate set
    l = session.read.parquet(str(tmp_path / "l")).select("k", "p")
    r = session.read.parquet(str(tmp_path / "r")).select("rk", "w")
    return (
        l.join(r, col("k") == col("rk"))
        .group_by("k")
        .agg(
            Count(lit(1)).alias("n"),
            Min(col("p")).alias("lo"),
            Max(col("p")).alias("hi"),
        )
    )


def _run_modes(session, tmp_path, q, monkeypatch, **env):
    """The query under HYPERSPACE_PIPELINE=0 (barrier + global pad) and =1
    (streamed + banded), both on the device tier, as pydicts."""
    session.enable_hyperspace()
    session.set_conf(C.EXEC_TPU_ENABLED, True)
    try:
        for key, val in env.items():
            monkeypatch.setenv(key, val)
        monkeypatch.setenv("HYPERSPACE_PIPELINE", "0")
        serial = q(session, tmp_path).to_pydict()
        monkeypatch.setenv("HYPERSPACE_PIPELINE", "1")
        pipelined = q(session, tmp_path).to_pydict()
    finally:
        session.set_conf(C.EXEC_TPU_ENABLED, False)
        session.disable_hyperspace()
    return serial, pipelined


class TestStreamedBandedBitIdentity:
    def test_plain_join_skewed(self, skew_env, monkeypatch):
        session, tmp_path = skew_env
        pairs0 = REGISTRY.counter("pipeline.join.pairs").value
        bands0 = REGISTRY.counter("pipeline.join.bands").value
        serial, pipelined = _run_modes(session, tmp_path, _plain_q, monkeypatch)
        assert hex_rows(pipelined) == hex_rows(serial)
        assert REGISTRY.counter("pipeline.join.pairs").value > pairs0
        assert REGISTRY.counter("pipeline.join.bands").value > bands0

    def test_plain_join_split_buckets(self, skew_env, monkeypatch):
        session, tmp_path = skew_env
        splits0 = REGISTRY.counter("pipeline.join.splits").value
        serial, pipelined = _run_modes(
            session, tmp_path, _plain_q, monkeypatch,
            HYPERSPACE_JOIN_SPLIT_ROWS="1024",
        )
        assert hex_rows(pipelined) == hex_rows(serial)
        assert REGISTRY.counter("pipeline.join.splits").value > splits0

    def test_fused_agg_join_skewed(self, skew_env, monkeypatch):
        session, tmp_path = skew_env
        serial, pipelined = _run_modes(session, tmp_path, _agg_q, monkeypatch)
        assert hex_rows(pipelined) == hex_rows(serial)

    def test_fused_agg_split_folds_exactly(self, skew_env, monkeypatch):
        session, tmp_path = skew_env
        splits0 = REGISTRY.counter("pipeline.join.splits").value
        serial, pipelined = _run_modes(
            session, tmp_path, _foldable_agg_q, monkeypatch,
            HYPERSPACE_JOIN_SPLIT_ROWS="1024",
        )
        assert hex_rows(pipelined) == hex_rows(serial)
        assert REGISTRY.counter("pipeline.join.splits").value > splits0

    def test_sum_agg_never_splits(self, skew_env, monkeypatch):
        """f32 sums are not decomposition-invariant: the split gate must
        keep sum-bearing buckets whole even under a tiny split threshold."""
        session, tmp_path = skew_env
        splits0 = REGISTRY.counter("pipeline.join.splits").value
        serial, pipelined = _run_modes(
            session, tmp_path, _agg_q, monkeypatch,
            HYPERSPACE_JOIN_SPLIT_ROWS="1024",
        )
        assert hex_rows(pipelined) == hex_rows(serial)
        assert REGISTRY.counter("pipeline.join.splits").value == splits0

    def test_empty_buckets_and_disjoint_keys(self, tmp_session, tmp_path, monkeypatch):
        rng = np.random.default_rng(5)
        n = 12_000
        left = {
            "k": rng.integers(0, 64, n).tolist(),
            "p": rng.uniform(0, 10, n).tolist(),
        }
        # two sparse right keys -> most buckets empty on the right
        right = {"rk": [3, 11], "w": [1.5, 2.5]}
        _write_sides(tmp_path, left, right)
        _index_sides(tmp_session, tmp_path)
        serial, pipelined = _run_modes(
            tmp_session, tmp_path, _plain_q, monkeypatch
        )
        assert hex_rows(pipelined) == hex_rows(serial)
        assert set(pipelined["k"]) == {3, 11}

    def test_disjoint_keys_empty_result(self, tmp_session, tmp_path, monkeypatch):
        rng = np.random.default_rng(6)
        n = 9_000
        left = {
            "k": rng.integers(0, 50, n).tolist(),
            "p": rng.uniform(size=n).tolist(),
        }
        right = {"rk": [1000, 2000], "w": [1.0, 2.0]}
        _write_sides(tmp_path, left, right)
        _index_sides(tmp_session, tmp_path)
        serial, pipelined = _run_modes(
            tmp_session, tmp_path, _plain_q, monkeypatch
        )
        assert hex_rows(pipelined) == hex_rows(serial)
        assert pipelined["k"] == []


class TestWarmJoinKernelCache:
    def test_warm_repeat_zero_compile_spans(self, skew_env, monkeypatch):
        """A repeated join (plain AND fused-aggregate) must serve every
        join kernel from the KernelCache: no kernel.retrace growth and no
        compile:* span in the warm trace."""
        from hyperspace_tpu.telemetry import trace

        session, tmp_path = skew_env
        monkeypatch.setenv("HYPERSPACE_PIPELINE", "1")
        session.enable_hyperspace()
        session.set_conf(C.EXEC_TPU_ENABLED, True)
        try:
            _plain_q(session, tmp_path).collect()  # cold: compiles
            _agg_q(session, tmp_path).collect()
            retraces = REGISTRY.counter("kernel.retrace").value
            hits0 = REGISTRY.counter("cache.kernel_join.hits").value
            sink = _ListSink()
            trace.enable(sink)
            try:
                _plain_q(session, tmp_path).collect()
                _agg_q(session, tmp_path).collect()
            finally:
                trace.disable()
        finally:
            session.set_conf(C.EXEC_TPU_ENABLED, False)
            session.disable_hyperspace()
        assert REGISTRY.counter("kernel.retrace").value == retraces
        assert REGISTRY.counter("cache.kernel_join.hits").value > hits0
        names = [s["name"] for s in sink.spans]
        assert not [n for n in names if n.startswith("compile:")]
        assert [n for n in names if n.startswith("join:")]

    def test_per_bucket_probe_kernel_warm(self, skew_env, monkeypatch):
        """With the batched path off, the per-bucket device probe
        (join_probe kind) runs and caches across repeats."""
        from hyperspace_tpu.plan import bucket_join, device_join

        session, tmp_path = skew_env
        monkeypatch.setenv("HYPERSPACE_PIPELINE", "1")
        monkeypatch.setattr(
            device_join, "try_batched_plain_join",
            lambda *a, **k: None,
        )
        monkeypatch.setattr(
            bucket_join, "_try_device_join_paths",
            lambda *a, **k: (None, None, None),
        )
        session.enable_hyperspace()
        session.set_conf(C.EXEC_TPU_ENABLED, True)
        try:
            _plain_q(session, tmp_path).collect()
            retraces = REGISTRY.counter("kernel.retrace").value
            _plain_q(session, tmp_path).collect()
        finally:
            session.set_conf(C.EXEC_TPU_ENABLED, False)
            session.disable_hyperspace()
        assert REGISTRY.counter("kernel.retrace").value == retraces
        assert ("join", "probe", (), "int32", (), (), (), (), ()) in (
            device_join.JOIN_CACHE
        )

    def test_per_bucket_agg_kernel_warm(self, skew_env, monkeypatch):
        """With the eager stacked path gated off, the per-bucket fused
        join+aggregate kernel (join_agg kind) runs and caches."""
        from hyperspace_tpu.plan import bucket_join

        session, tmp_path = skew_env
        monkeypatch.setenv("HYPERSPACE_PIPELINE", "1")
        monkeypatch.setattr(
            bucket_join, "_fused_device_possible", lambda *a, **k: False
        )
        session.enable_hyperspace()
        session.set_conf(C.EXEC_TPU_ENABLED, True)
        try:
            _agg_q(session, tmp_path).collect()
            retraces = REGISTRY.counter("kernel.retrace").value
            _agg_q(session, tmp_path).collect()
        finally:
            session.set_conf(C.EXEC_TPU_ENABLED, False)
            session.disable_hyperspace()
        assert REGISTRY.counter("kernel.retrace").value == retraces


class TestJoinUsageEvents:
    def test_bucketed_exec_emits_usage_event(self, skew_env, monkeypatch):
        """Every bucketed-join execution path emits a uniform
        HyperspaceIndexUsageEvent naming both side indexes (the device
        paths used to emit nothing)."""
        import importlib

        from hyperspace_tpu.telemetry.logger import clear_event_logger_cache

        session, tmp_path = skew_env
        clear_event_logger_cache(session)
        session.set_conf(
            C.EVENT_LOGGER_CLASS, "tests.test_join_pipeline.CapturingLogger"
        )
        canonical = importlib.import_module(
            "tests.test_join_pipeline"
        ).CapturingLogger
        canonical.events.clear()
        monkeypatch.setenv("HYPERSPACE_PIPELINE", "1")
        session.enable_hyperspace()
        session.set_conf(C.EXEC_TPU_ENABLED, True)
        try:
            _plain_q(session, tmp_path).collect()
        finally:
            session.set_conf(C.EXEC_TPU_ENABLED, False)
            session.disable_hyperspace()
            clear_event_logger_cache(session)
            session.unset_conf(C.EVENT_LOGGER_CLASS)
        usage = [
            e for e in canonical.events
            if type(e).__name__ == "HyperspaceIndexUsageEvent"
            and e.rule == "BucketedJoinExec"
        ]
        assert usage, "bucketed join execution must emit a usage event"
        assert usage[0].index_names == ["jl", "jr"]


class CapturingLogger:
    events: list = []

    def log_event(self, event):
        CapturingLogger.events.append(event)


class TestWorkerHelper:
    def test_io_worker_count_honors_env(self, monkeypatch):
        from hyperspace_tpu.utils.workers import io_thread_cap, io_worker_count

        monkeypatch.setenv("HYPERSPACE_IO_THREADS", "3")
        assert io_thread_cap() == 3
        assert io_worker_count(10) == 3
        assert io_worker_count(2) == 2
        assert io_worker_count(10, cap=1) == 1
        assert io_worker_count(0) == 1  # pools need a positive width
        monkeypatch.setenv("HYPERSPACE_IO_THREADS", "not-a-number")
        assert io_thread_cap() == 1

    def test_io_reader_delegates(self, monkeypatch):
        monkeypatch.setenv("HYPERSPACE_IO_THREADS", "5")
        assert cio.io_threads() == 5


class TestSerialModeStreams:
    def test_serial_mode_bit_identical(self, skew_env, monkeypatch):
        """HYPERSPACE_PIPELINE=serial keeps the staged executor without IO
        overlap — still banded, still bit-identical."""
        session, tmp_path = skew_env
        session.enable_hyperspace()
        session.set_conf(C.EXEC_TPU_ENABLED, True)
        try:
            monkeypatch.setenv("HYPERSPACE_PIPELINE", "0")
            serial = _plain_q(session, tmp_path).to_pydict()
            monkeypatch.setenv("HYPERSPACE_PIPELINE", "serial")
            staged = _plain_q(session, tmp_path).to_pydict()
        finally:
            session.set_conf(C.EXEC_TPU_ENABLED, False)
            session.disable_hyperspace()
        assert hex_rows(staged) == hex_rows(serial)


class _ListSink:
    """In-memory TraceSink collecting completed span names."""

    def __init__(self):
        self.spans = []

    def write_span(self, span):
        self.spans.append({"name": span.name})

    def close(self):
        pass
