"""Metadata model tests (ref: IndexLogEntryTest, FileIdTrackerTest)."""

import pytest

from hyperspace_tpu.meta.entry import (
    Content,
    Directory,
    FileIdTracker,
    FileInfo,
    IndexLogEntry,
    LogicalPlanFingerprint,
    Relation,
    Signature,
    Source,
    SourcePlan,
    INDEX_KIND_REGISTRY,
)
from hyperspace_tpu.exceptions import HyperspaceError


def fi(path, size=10, mtime=1000, fid=-1):
    return FileInfo(path, size, mtime, fid)


class FakeIndex:
    kind = "FAKE"
    kind_abbr = "FK"

    def to_dict(self):
        return {"kind": "FAKE"}

    @staticmethod
    def from_dict(d):
        return FakeIndex()


INDEX_KIND_REGISTRY["FAKE"] = FakeIndex.from_dict


def make_entry(files=None, name="idx1", state="ACTIVE"):
    files = files or [fi("/data/a.parquet", 5, 111, 0), fi("/data/b.parquet", 7, 222, 1)]
    content = Content.from_files([fi("/idx/v__=0/part-0.parquet", 3, 9, -1)])
    rel = Relation(
        root_paths=["/data"],
        content=Content.from_files(files),
        schema=[{"name": "a", "type": "int64"}],
        file_format="parquet",
    )
    src = Source(
        SourcePlan([rel], "Scan", LogicalPlanFingerprint([Signature("p", "v")]))
    )
    return IndexLogEntry(name, FakeIndex(), content, src, state=state)


class TestFileInfo:
    def test_equality_ignores_id(self):
        assert fi("/a", 1, 2, 5) == fi("/a", 1, 2, 99)
        assert hash(fi("/a", 1, 2, 5)) == hash(fi("/a", 1, 2, 99))
        assert fi("/a", 1, 2) != fi("/a", 1, 3)

    def test_roundtrip(self):
        f = fi("/x/y.parquet", 42, 777, 3)
        assert FileInfo.from_dict(f.to_dict()) == f
        assert FileInfo.from_dict(f.to_dict()).id == 3


class TestDirectoryContent:
    def test_tree_roundtrip_and_flatten(self):
        files = [
            fi("/data/x/a.parquet", 1, 10, 0),
            fi("/data/x/b.parquet", 2, 20, 1),
            fi("/data/y/c.parquet", 3, 30, 2),
        ]
        c = Content.from_files(files)
        assert sorted(c.files()) == sorted(f.name for f in files)
        assert set(c.file_infos()) == set(files)
        c2 = Content.from_dict(c.to_dict())
        assert set(c2.file_infos()) == set(files)
        assert c.size_in_bytes == 6

    def test_merge_dedups(self):
        a = Content.from_files([fi("/d/a", 1, 1, 0), fi("/d/b", 2, 2, 1)])
        b = Content.from_files([fi("/d/b", 2, 2, 1), fi("/d/c", 3, 3, 2)])
        merged = Directory.merge(a.root, b.root)
        names = sorted(Content(merged).files())
        assert names == ["/d/a", "/d/b", "/d/c"]

    def test_merge_different_roots_fails(self):
        a = Directory("x")
        b = Directory("y")
        with pytest.raises(HyperspaceError):
            Directory.merge(a, b)

    def test_from_directory_path(self, tmp_path):
        (tmp_path / "sub").mkdir()
        (tmp_path / "a.bin").write_bytes(b"123")
        (tmp_path / "sub" / "b.bin").write_bytes(b"4567")
        tracker = FileIdTracker()
        c = Content.from_directory_path(str(tmp_path), tracker)
        assert len(c.files()) == 2
        assert c.size_in_bytes == 7
        ids = sorted(f.id for f in c.file_infos())
        assert ids == [0, 1]


class TestIndexLogEntry:
    def test_json_roundtrip(self):
        e = make_entry()
        e.stamp()
        d = e.to_dict()
        assert d["version"] == "0.1"
        e2 = IndexLogEntry.from_dict(d)
        assert e2 == e
        assert e2.kind == "FAKE"
        assert e2.state == "ACTIVE"

    def test_source_accessors(self):
        e = make_entry()
        assert len(e.source_file_infos()) == 2
        assert e.source_files_size_in_bytes() == 12
        assert e.source_update() is None
        assert e.index_version_dirs() == ["v__=0"]

    def test_with_update(self):
        e = make_entry()
        appended = [fi("/data/new.parquet", 9, 999, 2)]
        deleted = [fi("/data/a.parquet", 5, 111, 0)]
        e2 = e.with_update(appended, deleted)
        assert e2.appended_files() == set(appended)
        assert e2.deleted_files() == set(deleted)
        # original untouched
        assert e.source_update() is None
        # roundtrips
        e3 = IndexLogEntry.from_dict(e2.to_dict())
        assert e3.appended_files() == set(appended)

    def test_tags_runtime_only(self):
        e = make_entry()
        e.set_tag("plan1", "HYBRIDSCAN_REQUIRED", True)
        assert e.get_tag("plan1", "HYBRIDSCAN_REQUIRED") is True
        assert e.get_tag("plan2", "HYBRIDSCAN_REQUIRED") is None
        assert "tags" not in e.to_dict()
        e.unset_tag("plan1", "HYBRIDSCAN_REQUIRED")
        assert e.get_tag("plan1", "HYBRIDSCAN_REQUIRED") is None


class TestFileIdTracker:
    def test_monotonic_assignment(self):
        t = FileIdTracker()
        assert t.add_file("/a", 1, 1) == 0
        assert t.add_file("/b", 1, 1) == 1
        assert t.add_file("/a", 1, 1) == 0  # stable
        assert t.add_file("/a", 2, 1) == 2  # size change => new id
        assert t.max_id == 2

    def test_seed_from_entry(self):
        t = FileIdTracker()
        t.add_file_info([fi("/a", 1, 1, 7), fi("/b", 2, 2, 9)])
        assert t.max_id == 9
        assert t.add_file("/c", 3, 3) == 10
        assert t.get_file_id("/a", 1, 1) == 7

    def test_seed_conflict_raises(self):
        t = FileIdTracker()
        t.add_file_info([fi("/a", 1, 1, 7)])
        with pytest.raises(HyperspaceError):
            t.add_file_info([fi("/a", 1, 1, 8)])

    def test_seed_unknown_id_raises(self):
        t = FileIdTracker()
        with pytest.raises(HyperspaceError):
            t.add_file_info([fi("/a", 1, 1, -1)])
