"""Iceberg-shaped source tests: manifest/snapshot-id metadata model, scans,
index builds, refresh reload, ancestry-based time travel
(ref: IcebergIntegrationTest + IcebergRelation.scala:37-260). Mirrors
tests/test_snapshot_source.py to prove the provider plug point with a
second, structurally different implementation."""

import numpy as np
import pytest

from hyperspace_tpu import CoveringIndexConfig, Hyperspace
from hyperspace_tpu.columnar.table import ColumnBatch
from hyperspace_tpu.plan import col
from hyperspace_tpu.plan.nodes import FileScan
from hyperspace_tpu.sources.iceberg import (
    ICEBERG_FORMAT,
    SNAPSHOT_ID_HISTORY_PROPERTY,
    IcebergStyleTable,
    closest_index_version_by_ancestry,
    parse_snapshot_history,
)


def index_scans(plan):
    return [n for n in plan.preorder() if isinstance(n, FileScan) and n.index_info]


@pytest.fixture()
def table(tmp_path):
    t = IcebergStyleTable(str(tmp_path / "tbl"))
    t.commit(ColumnBatch.from_pydict({"k": [1, 2, 3], "v": [1.0, 2.0, 3.0]}))
    return t


class TestIcebergTable:
    def test_commit_and_scan(self, tmp_session, table):
        sid = table.current_snapshot_id()
        assert sid is not None
        assert table.scan(tmp_session).to_pydict()["k"] == [1, 2, 3]

    def test_append_creates_snapshot_with_ancestry(self, tmp_session, table):
        s0 = table.current_snapshot_id()
        s1 = table.commit(ColumnBatch.from_pydict({"k": [4], "v": [4.0]}))
        assert table.current_snapshot_id() == s1
        assert table.parent_of(s1) == s0
        assert table.scan(tmp_session).count() == 4
        # time travel by snapshot id
        assert table.scan(tmp_session, snapshot_id=s0).count() == 3

    def test_time_travel_by_timestamp(self, tmp_session, table):
        s0 = table.current_snapshot_id()
        ts0 = table._snapshot(s0)["timestamp-ms"]
        table.commit(ColumnBatch.from_pydict({"k": [4], "v": [4.0]}))
        assert table.snapshot_as_of(ts0) == s0
        assert table.scan(tmp_session, as_of_ms=ts0).count() == 3

    def test_delete_files_rewrites_manifests(self, tmp_session, table):
        s1 = table.commit(ColumnBatch.from_pydict({"k": [4], "v": [4.0]}))
        first_file = table.data_files(s1)[0]["path"]
        table.delete_files([first_file])
        assert table.scan(tmp_session).to_pydict()["k"] == [4]

    def test_overwrite_mode(self, tmp_session, table):
        table.commit(ColumnBatch.from_pydict({"k": [9], "v": [9.0]}), mode="overwrite")
        assert table.scan(tmp_session).to_pydict()["k"] == [9]


class TestIcebergIndexing:
    def test_create_index_records_snapshot_history(self, tmp_session, table):
        hs = Hyperspace(tmp_session)
        hs.create_index(table.scan(tmp_session), CoveringIndexConfig("iidx", ["k"], ["v"]))
        entry = hs.get_index("iidx")
        pairs = parse_snapshot_history(entry.properties)
        assert pairs and pairs[0][1] == table.current_snapshot_id()
        assert entry.relation.file_format == ICEBERG_FORMAT

    def test_rewrite_on_iceberg_scan(self, tmp_session, table):
        hs = Hyperspace(tmp_session)
        hs.create_index(table.scan(tmp_session), CoveringIndexConfig("iidx", ["k"], ["v"]))
        tmp_session.enable_hyperspace()
        q = table.scan(tmp_session).filter(col("k") == 2).select("k", "v")
        assert index_scans(q.optimized_plan())
        assert q.to_pydict()["v"] == [2.0]

    def test_refresh_after_append(self, tmp_session, table):
        hs = Hyperspace(tmp_session)
        hs.create_index(table.scan(tmp_session), CoveringIndexConfig("iidx", ["k"], ["v"]))
        table.commit(ColumnBatch.from_pydict({"k": [4], "v": [4.0]}))
        hs.refresh_index("iidx")  # reload routes through IcebergStyleSource
        entry = hs.get_index("iidx")
        pairs = parse_snapshot_history(entry.properties)
        assert len(pairs) == 2
        assert pairs[-1][1] == table.current_snapshot_id()
        tmp_session.enable_hyperspace()
        q = table.scan(tmp_session).filter(col("k") == 4).select("k", "v")
        assert index_scans(q.optimized_plan())
        assert q.to_pydict()["v"] == [4.0]

    def test_ancestry_time_travel_uses_older_index(self, tmp_session, table):
        hs = Hyperspace(tmp_session)
        hs.create_index(table.scan(tmp_session), CoveringIndexConfig("iidx", ["k"], ["v"]))
        s0 = table.current_snapshot_id()
        table.commit(ColumnBatch.from_pydict({"k": [4], "v": [4.0]}))
        hs.refresh_index("iidx")
        tmp_session.enable_hyperspace()
        # query the OLD snapshot: the index log version recorded against s0
        # must substitute (ancestry walk hits s0 directly)
        q = table.scan(tmp_session, snapshot_id=s0).filter(col("k") == 2).select("k", "v")
        scans = index_scans(q.optimized_plan())
        assert scans
        assert q.to_pydict()["v"] == [2.0]
        # intermediate snapshot (no index recorded): walks up to s0's entry
        s2 = table.commit(ColumnBatch.from_pydict({"k": [5], "v": [5.0]}))
        entry = hs.get_index("iidx")
        lv = closest_index_version_by_ancestry(
            table, entry.properties, s2
        )
        assert lv is not None

    def test_ancestry_walk_logic(self, tmp_path):
        t = IcebergStyleTable(str(tmp_path / "t2"))
        s0 = t.commit(ColumnBatch.from_pydict({"k": [1]}))
        s1 = t.commit(ColumnBatch.from_pydict({"k": [2]}))
        s2 = t.commit(ColumnBatch.from_pydict({"k": [3]}))
        props = {SNAPSHOT_ID_HISTORY_PROPERTY: f"2:{s0},4:{s1}"}
        assert closest_index_version_by_ancestry(t, props, s2) == 4
        assert closest_index_version_by_ancestry(t, props, s1) == 4
        assert closest_index_version_by_ancestry(t, props, s0) == 2
        assert closest_index_version_by_ancestry(t, {}, s2) is None

    def test_both_snapshot_providers_coexist(self, tmp_session, tmp_path):
        """The manager dispatches each scan to exactly one provider."""
        from hyperspace_tpu.sources.delta import SnapshotTable

        dt = SnapshotTable(str(tmp_path / "dtbl"))
        dt.commit(ColumnBatch.from_pydict({"k": [1], "v": [1.0]}))
        it = IcebergStyleTable(str(tmp_path / "itbl"))
        it.commit(ColumnBatch.from_pydict({"k": [2], "v": [2.0]}))
        hs = Hyperspace(tmp_session)
        hs.create_index(dt.scan(tmp_session), CoveringIndexConfig("di", ["k"], ["v"]))
        hs.create_index(it.scan(tmp_session), CoveringIndexConfig("ii", ["k"], ["v"]))
        assert hs.get_index("di").relation.file_format == "snapshot-parquet"
        assert hs.get_index("ii").relation.file_format == ICEBERG_FORMAT


class TestSnapshotSchemas:
    def test_time_travel_uses_snapshot_schema(self, tmp_session, tmp_path):
        """An old snapshot must scan with ITS schema, not the newest one
        (schema travels with the snapshot, as in real Iceberg)."""
        t = IcebergStyleTable(str(tmp_path / "tbl"))
        s0 = t.commit(ColumnBatch.from_pydict({"k": [1, 2]}))
        t.commit(
            ColumnBatch.from_pydict({"k": [3], "v": [3.0]}), mode="overwrite"
        )
        old = t.scan(tmp_session, snapshot_id=s0)
        assert old.schema.names == ["k"]
        assert old.to_pydict()["k"] == [1, 2]
        new = t.scan(tmp_session)
        assert new.schema.names == ["k", "v"]
