"""Failure-hardening tests: deterministic fault injection, retry/backoff,
the device breaker state machine, and crash-safe index maintenance.

The contract under test (docs/robustness.md): under ANY injected failure
the engine returns either the exact answer (a full device answer or a full
host recompute — bitwise, never a torn mix) or a typed HyperspaceError;
and any crash mid-action leaves a warehouse that ``recover()`` returns to
a stable, orphan-free state from which the action re-runs to a result
bit-identical to a never-crashed build.
"""

import errno
import os
import time

import numpy as np
import pytest

from hyperspace_tpu import Hyperspace, HyperspaceSession
from hyperspace_tpu import constants as C
from hyperspace_tpu.columnar import io as cio
from hyperspace_tpu.columnar.table import ColumnBatch
from hyperspace_tpu.exceptions import ConcurrentWriteError, HyperspaceError
from hyperspace_tpu.meta.data_manager import IndexDataManager
from hyperspace_tpu.meta.entry import LogEntry
from hyperspace_tpu.meta.log_manager import IndexLogManager, STABLE_STATES
from hyperspace_tpu.models.covering import CoveringIndexConfig
from hyperspace_tpu.plan import col, lit, Count, Max, Min, Sum
from hyperspace_tpu.telemetry.metrics import REGISTRY
from hyperspace_tpu.utils import backend, faults, retry


def _val(name: str) -> int:
    m = REGISTRY.get(name)
    return 0 if m is None else int(m.value)


def _bits(d: dict) -> str:
    return repr(
        {
            k: [x.hex() if isinstance(x, float) else x for x in v]
            for k, v in d.items()
        }
    )


@pytest.fixture(autouse=True)
def _pristine_failure_state():
    """Faults disarmed, breaker closed, real clock — before AND after every
    test in this module (they mutate process-global state)."""
    faults.disarm()
    backend._set_clock_for_testing(time.monotonic)
    backend._reset_for_testing()
    yield
    faults.disarm()
    backend._set_clock_for_testing(time.monotonic)
    backend._reset_for_testing()


# ---------------------------------------------------------------------------
# fault-spec parsing
# ---------------------------------------------------------------------------

class TestFaultSpec:
    def test_nth_rule(self):
        (r,) = faults.parse_spec("io.read_file:ioerror:n=3")
        assert r.point == "io.read_file" and r.kind == "ioerror" and r.nth == 3

    def test_probabilistic_rule_with_seed(self):
        (r,) = faults.parse_spec("device.dispatch:oom:p=0.25,seed=9")
        assert r.p == 0.25 and r.seed == 9 and r.nth is None

    def test_always_and_multi_rule(self):
        rules = faults.parse_spec(
            "log.write:crash_before:always; data.publish:crash_after:n=1"
        )
        assert [r.kind for r in rules] == ["crash_before", "crash_after"]
        assert rules[0].always and rules[1].nth == 1

    def test_wildcard_point(self):
        (r,) = faults.parse_spec("device.*:ioerror:n=1")
        assert r.matches("device.upload") and r.matches("device.fetch")
        assert not r.matches("io.read_file")

    @pytest.mark.parametrize(
        "bad",
        [
            "nope.unknown:ioerror:n=1",         # unknown point
            "io.read_file:frob:n=1",            # unknown kind
            "io.read_file:ioerror",             # missing trigger
            "io.read_file:ioerror:n=1,p=0.5",   # both triggers
            "io.read_file:ioerror:n=0",         # n < 1
            "io.read_file:ioerror:p=1.5",       # p out of range
            "io.read_file:ioerror:k=2",         # unknown trigger key
            "io.read_file:ioerror:n=x",         # non-numeric
        ],
    )
    def test_malformed_specs_fail_loudly(self, bad):
        with pytest.raises(faults.FaultSpecError):
            faults.parse_spec(bad)

    def test_probabilistic_is_deterministic_per_seed(self):
        def fires(seed):
            faults.arm(f"io.read_file:ioerror:p=0.5,seed={seed}")
            out = []
            for _ in range(20):
                try:
                    faults.fire("io.read_file")
                    out.append(False)
                except faults.InjectedIOError:
                    out.append(True)
            faults.disarm()
            return out

        assert fires(7) == fires(7)
        assert fires(7) != fires(8)

    def test_nth_fires_exactly_once(self):
        faults.arm("io.read_file:ioerror:n=2")
        faults.fire("io.read_file")  # hit 1: no fire
        with pytest.raises(faults.InjectedIOError):
            faults.fire("io.read_file")  # hit 2: fires
        faults.fire("io.read_file")  # hit 3: spent
        (snap,) = faults.snapshot()
        assert snap["hits"] == 3 and snap["fired"] == 1

    def test_typed_error_hierarchy(self):
        assert issubclass(faults.InjectedIOError, IOError)
        assert issubclass(faults.InjectedIOError, HyperspaceError)
        assert issubclass(faults.InjectedOOMError, MemoryError)
        assert issubclass(faults.InjectedOOMError, HyperspaceError)
        # crash must be un-swallowable by `except Exception`
        assert issubclass(faults.InjectedCrash, BaseException)
        assert not issubclass(faults.InjectedCrash, Exception)

    def test_crash_before_vs_after(self):
        faults.arm("log.write:crash_before:n=1")
        with pytest.raises(faults.InjectedCrash):
            faults.fire("log.write")
        faults.arm("log.write:crash_after:n=1")
        faults.fire("log.write")  # before phase: crash_after stays quiet
        with pytest.raises(faults.InjectedCrash):
            faults.fire_after("log.write")

    def test_unset_is_zero_overhead(self):
        """Disarmed hooks touch no counters (the clean path stays clean)."""
        faults.disarm()
        before_total = _val("faults.injected")
        before_point = _val("faults.injected.io.read_file")
        for _ in range(1000):
            faults.fire("io.read_file")
            faults.fire_after("io.read_file")
        assert _val("faults.injected") == before_total
        assert _val("faults.injected.io.read_file") == before_point

    def test_injection_is_counted_and_attributed(self):
        faults.arm("io.footer:ioerror:n=1")
        before = _val("faults.injected.io.footer")
        with pytest.raises(faults.InjectedIOError):
            faults.fire("io.footer")
        assert _val("faults.injected.io.footer") == before + 1


# ---------------------------------------------------------------------------
# retry / backoff
# ---------------------------------------------------------------------------

class FakeClock:
    def __init__(self):
        self.sleeps = []

    def __call__(self, s: float) -> None:
        self.sleeps.append(s)


class TestRetry:
    def test_absorbs_transient_then_succeeds(self):
        clock = FakeClock()
        calls = {"n": 0}

        def flaky():
            calls["n"] += 1
            if calls["n"] < 3:
                raise OSError("transient hiccup")
            return "ok"

        before = _val("io.retry.attempts")
        assert retry.retry_call(flaky, "unit", attempts=3, clock=clock) == "ok"
        assert calls["n"] == 3
        assert _val("io.retry.attempts") == before + 2
        # deterministic backoff schedule: exact, reproducible delays
        assert clock.sleeps == [
            retry.backoff_delay("unit", 1),
            retry.backoff_delay("unit", 2),
        ]

    def test_permanent_error_fails_immediately(self):
        clock = FakeClock()
        calls = {"n": 0}

        def missing():
            calls["n"] += 1
            raise FileNotFoundError("gone")

        with pytest.raises(FileNotFoundError):
            retry.retry_call(missing, "unit", attempts=5, clock=clock)
        assert calls["n"] == 1 and clock.sleeps == []

    def test_exhaustion_raises_original_and_counts(self):
        clock = FakeClock()
        before = _val("io.retry.gave_up")

        def always():
            raise OSError("still down")

        with pytest.raises(OSError, match="still down"):
            retry.retry_call(always, "unit", attempts=3, clock=clock)
        assert len(clock.sleeps) == 2
        assert _val("io.retry.gave_up") == before + 1

    def test_backoff_shape(self):
        d1, d2, d3 = (retry.backoff_delay("w", k) for k in (1, 2, 3))
        assert 0 < d1 <= retry.BASE_DELAY_S
        assert d1 < d3  # grows
        for k in range(1, 30):
            assert retry.backoff_delay("w", k) <= retry.MAX_DELAY_S
        # jitter is per-site deterministic, decorrelated across sites
        assert retry.backoff_delay("w", 1) == retry.backoff_delay("w", 1)
        assert retry.backoff_delay("w", 1) != retry.backoff_delay("z", 1)

    def test_classifier(self):
        assert retry.is_transient(OSError("io"))
        assert retry.is_transient(TimeoutError())
        assert retry.is_transient(faults.InjectedIOError("x"))
        assert not retry.is_transient(FileNotFoundError())
        assert not retry.is_transient(PermissionError())
        assert not retry.is_transient(ValueError("parse"))
        assert not retry.is_transient(faults.InjectedOOMError("x"))

    def test_footer_fault_absorbed_by_retry(self, tmp_path):
        cio.write_parquet(
            ColumnBatch.from_pydict({"x": [1.0, 2.0, 3.0]}),
            str(tmp_path / "t" / "p.parquet"),
        )
        path = str(tmp_path / "t" / "p.parquet")
        clean = cio.read_rowgroup_stats(path, ["x"])
        cio._ROWGROUP_STATS_CACHE.clear()
        before = _val("io.retry.attempts")
        faults.arm("io.footer:ioerror:n=1")
        got = cio.read_rowgroup_stats(path, ["x"])
        faults.disarm()
        assert got == clean
        assert _val("io.retry.attempts") == before + 1

    def test_read_file_fault_absorbed_bit_identical(self, tmp_session, tmp_path):
        rng = np.random.default_rng(3)
        data = {"a": rng.integers(0, 9, 500).tolist(), "b": rng.random(500).tolist()}
        cio.write_parquet(ColumnBatch.from_pydict(data), str(tmp_path / "t" / "p.parquet"))
        df = tmp_session.read.parquet(str(tmp_path / "t"))
        clean = _bits(df.filter(col("a") > 4).select("b").to_pydict())
        cio._SOURCE_COL_CACHE.clear()
        cio._INDEX_CHUNK_CACHE.clear()
        faults.arm("io.read_file:ioerror:n=1")
        got = _bits(df.filter(col("a") > 4).select("b").to_pydict())
        snap = faults.snapshot()
        faults.disarm()
        assert sum(r["fired"] for r in snap) == 1  # it actually injected
        assert got == clean


# ---------------------------------------------------------------------------
# device breaker state machine
# ---------------------------------------------------------------------------

class TestBreaker:
    @pytest.fixture(autouse=True)
    def _not_strict(self, monkeypatch):
        monkeypatch.setenv("HYPERSPACE_DEVICE_STRICT", "0")
        monkeypatch.setenv("HYPERSPACE_BREAKER_COOLDOWN", "10")

    def _clock(self):
        t = {"now": 1000.0}
        backend._set_clock_for_testing(lambda: t["now"])
        return t

    def test_transient_opens_then_probe_recovers(self):
        t = self._clock()
        assert backend.breaker_state() == backend.CLOSED
        backend.record_device_failure(OSError("tunnel dropped"))
        assert backend.breaker_state() == backend.OPEN
        assert not backend.device_healthy()  # cooldown running
        t["now"] += 10.5  # past cooldown: exactly one probe admitted
        assert backend.device_healthy()
        assert backend.breaker_state() == backend.HALF_OPEN
        assert not backend.device_healthy()  # second caller stays on host
        backend.record_device_success()
        assert backend.breaker_state() == backend.CLOSED
        assert backend.device_healthy()

    def test_failed_probe_reopens_with_doubled_cooldown(self):
        t = self._clock()
        backend.record_device_failure(TimeoutError("t0"))
        t["now"] += 10.5
        assert backend.device_healthy()  # the probe
        backend.record_device_failure(TimeoutError("t1"))  # probe failed
        assert backend.breaker_state() == backend.OPEN
        t["now"] += 10.5  # base cooldown no longer enough (doubled)
        assert not backend.device_healthy()
        t["now"] += 10.0  # 2x base now elapsed
        assert backend.device_healthy()
        assert backend.breaker_state() == backend.HALF_OPEN

    def test_cooldown_factor_is_capped(self):
        t = self._clock()
        backend.record_device_failure(OSError("x"))
        for _ in range(8):  # reopen far past the 16x cap
            t["now"] += 10 * 16 + 1
            assert backend.device_healthy()
            backend.record_device_failure(OSError("x"))
        t["now"] += 10 * 16 + 1  # capped cooldown always suffices
        assert backend.device_healthy()

    def test_permanent_error_latches(self):
        t = self._clock()
        backend.record_device_failure(ValueError("bad lowering"))
        assert backend.breaker_state() == backend.LATCHED
        t["now"] += 1e9  # no cooldown ever reopens a latch
        assert not backend.device_healthy()
        backend.record_device_success()  # success signal can't unlatch
        assert backend.breaker_state() == backend.LATCHED

    def test_success_when_closed_is_noop(self):
        backend.record_device_success()
        assert backend.breaker_state() == backend.CLOSED

    def test_classifier_policy(self):
        classify = backend.classify_device_failure
        assert classify(OSError("io")) == "transient"
        assert classify(TimeoutError()) == "transient"
        assert classify(MemoryError("RESOURCE_EXHAUSTED")) == "transient"
        assert classify(faults.InjectedIOError("x")) == "transient"
        assert classify(ValueError("shape mismatch")) == "permanent"
        assert classify(TypeError("tracer")) == "permanent"
        assert classify(NotImplementedError()) == "permanent"
        assert classify(RuntimeError("compilation failure")) == "permanent"
        # unknown runtime errors default to transient (latching forever on
        # an unclassified error is the costlier mistake)
        assert classify(RuntimeError("???")) == "transient"

    def test_strict_mode_reraises(self, monkeypatch):
        monkeypatch.setenv("HYPERSPACE_DEVICE_STRICT", "1")
        with pytest.raises(OSError):
            backend.record_device_failure(OSError("surface me"))

    def test_snapshot_surface(self):
        snap = backend.breaker_snapshot()
        assert snap["state"] == backend.CLOSED
        backend.record_device_failure(OSError("x"))
        snap = backend.breaker_snapshot()
        assert snap["state"] == backend.OPEN
        assert snap["last_failure_kind"] == "transient"


# ---------------------------------------------------------------------------
# mid-stream device failure: clean host-recompute degradation
# ---------------------------------------------------------------------------

def _agg_query(d):
    return (
        d.filter((col("d") >= 2) & (col("y") < 0.7))
        .select("d", "x", "y")
        .agg(
            Sum(col("x") * col("y")).alias("s"),
            Count(lit(1)).alias("n"),
            Min(col("x")).alias("mn"),
            Max(col("x")).alias("mx"),
        )
    )


class TestDeviceDegradation:
    @pytest.fixture()
    def multi_file_df(self, tmp_session, tmp_path):
        # several files so the pipelined chunk streamer engages
        rng = np.random.default_rng(17)
        for part in range(4):
            data = {
                "d": rng.integers(0, 10, 2000).astype(int).tolist(),
                "x": rng.uniform(0, 100, 2000).tolist(),
                "y": rng.uniform(0, 1, 2000).tolist(),
            }
            cio.write_parquet(
                ColumnBatch.from_pydict(data),
                str(tmp_path / "t" / f"p{part}.parquet"),
            )
        return tmp_session.read.parquet(str(tmp_path / "t"))

    @pytest.mark.parametrize("point", ["device.dispatch", "device.upload", "device.fetch"])
    def test_mid_stream_failure_degrades_bit_identical(
        self, multi_file_df, monkeypatch, point
    ):
        """A device failure mid-query yields EXACTLY the host executor's
        bits — a full recompute, never a partial device fold."""
        monkeypatch.setenv("HYPERSPACE_DEVICE_STRICT", "0")
        monkeypatch.setenv("HYPERSPACE_STREAM_CHUNK_MB", "0.05")
        session = multi_file_df.session
        host = _bits(_agg_query(multi_file_df).to_pydict())  # device tier off

        session.set_conf(C.EXEC_TPU_ENABLED, True)
        faults.arm(f"{point}:ioerror:n=1")
        degraded = _bits(_agg_query(multi_file_df).to_pydict())
        snap = faults.snapshot()
        faults.disarm()
        assert sum(r["fired"] for r in snap) == 1
        assert degraded == host
        # the transient failure opened (not latched) the breaker
        assert backend.breaker_state() == backend.OPEN

    def test_clean_device_run_unaffected_by_hardening(self, multi_file_df):
        """With faults unset the device path still runs (no behavior change
        from planting the injection points)."""
        session = multi_file_df.session
        session.set_conf(C.EXEC_TPU_ENABLED, True)
        before = _val("faults.injected")
        out = _agg_query(multi_file_df).to_pydict()
        assert out["n"][0] > 0
        assert _val("faults.injected") == before
        assert backend.breaker_state() == backend.CLOSED


# ---------------------------------------------------------------------------
# log CAS portability + temp-file hygiene (satellite)
# ---------------------------------------------------------------------------

def _entry(state, log_id=0):
    e = LogEntry(state=state, id=log_id)
    e.stamp()
    return e


class TestLogCasPortability:
    def _no_tmp(self, m):
        return not [n for n in os.listdir(m.log_dir) if n.startswith(".tmp-")]

    def test_linkless_fs_falls_back_to_o_excl(self, tmp_path, monkeypatch):
        m = IndexLogManager(str(tmp_path / "idx"))

        def no_links(src, dst, **kw):
            raise OSError(errno.EPERM, "hard links not supported")

        monkeypatch.setattr(os, "link", no_links)
        assert m.write_log(0, _entry("CREATING"))
        got = m.get_log(0)
        assert got is not None and got.state == "CREATING"
        assert self._no_tmp(m)
        # lose-if-present semantics survive the fallback
        assert not m.write_log(0, _entry("CREATING"))

    def test_exclusive_create_loses_when_target_exists(self, tmp_path):
        m = IndexLogManager(str(tmp_path / "idx"))
        assert m.write_log(0, _entry("CREATING"))
        tmp = str(tmp_path / "idx" / "_hyperspace_log" / "spool")
        with open(tmp, "w") as f:
            f.write("{}")
        assert not m._exclusive_create(tmp, m._entry_path(0))

    def test_unexpected_link_errno_propagates(self, tmp_path, monkeypatch):
        m = IndexLogManager(str(tmp_path / "idx"))

        def enospc(src, dst, **kw):
            raise OSError(errno.ENOSPC, "disk full")

        monkeypatch.setattr(os, "link", enospc)
        with pytest.raises(OSError, match="disk full"):
            m.write_log(0, _entry("CREATING"))
        assert self._no_tmp(m)  # spool cleaned even on the raise path

    def test_tmp_cleaned_when_fsync_fails(self, tmp_path, monkeypatch):
        m = IndexLogManager(str(tmp_path / "idx"))
        os.makedirs(m.log_dir, exist_ok=True)

        def bad_fsync(fd):
            raise OSError(errno.EIO, "fsync failed")

        monkeypatch.setattr(os, "fsync", bad_fsync)
        with pytest.raises(OSError):
            m.write_log(0, _entry("CREATING"))
        monkeypatch.undo()
        assert self._no_tmp(m)
        assert m.get_latest_id() is None  # nothing half-committed

    def test_tmp_cleaned_on_loss(self, tmp_path):
        m = IndexLogManager(str(tmp_path / "idx"))
        assert m.write_log(0, _entry("CREATING"))
        assert not m.write_log(0, _entry("CREATING"))
        assert self._no_tmp(m)

    def test_stale_temp_age_gate(self, tmp_path):
        m = IndexLogManager(str(tmp_path / "idx"))
        os.makedirs(m.log_dir, exist_ok=True)
        p = os.path.join(m.log_dir, ".tmp-stranded")
        with open(p, "w") as f:
            f.write("x")
        assert m.stale_temp_files(min_age_s=60.0) == []  # fresh: maybe live
        assert m.stale_temp_files(min_age_s=0.0) == [p]
        old = time.time() - 3600
        os.utime(p, (old, old))
        assert m.stale_temp_files(min_age_s=60.0) == [p]
        assert m.clear_temp_files(min_age_s=60.0) == 1


# ---------------------------------------------------------------------------
# action conflict retry (satellite)
# ---------------------------------------------------------------------------

def _make_source(src: str, parts: int, rows: int = 600, start: int = 0) -> None:
    """Write parts [start, parts): existing files must not be rewritten —
    a fresh mtime makes an identical file look deleted+appended."""
    os.makedirs(src, exist_ok=True)
    for part in range(start, parts):
        rng = np.random.default_rng(100 + part)
        data = {
            "k": rng.integers(0, 20, rows).astype(int).tolist(),
            "v": rng.random(rows).tolist(),
            "w": rng.integers(0, 1000, rows).astype(int).tolist(),
        }
        cio.write_parquet(
            ColumnBatch.from_pydict(data), os.path.join(src, f"p{part}.parquet")
        )


class TestConflictRetry:
    def _indexed_session(self, root):
        s = HyperspaceSession(warehouse_dir=root)
        s.set_conf(C.INDEX_NUM_BUCKETS, 4)
        h = Hyperspace(s)
        src = os.path.join(root, "src")
        _make_source(src, 2)
        h.create_index(s.read.parquet(src), CoveringIndexConfig("cidx", ["k"], ["v"]))
        return s, h

    def test_conflict_is_retried_and_succeeds(self, tmp_path, monkeypatch):
        from hyperspace_tpu.actions.lifecycle import DeleteAction
        from hyperspace_tpu.index_manager import index_manager_for

        s, h = self._indexed_session(str(tmp_path))
        lm = IndexLogManager(index_manager_for(s).resolver.get_index_path("cidx"))
        orig = lm.write_log
        losses = {"n": 0}

        def contended(log_id, entry):
            if losses["n"] == 0:
                losses["n"] += 1
                return False  # simulate a concurrent winner at this id
            return orig(log_id, entry)

        monkeypatch.setattr(lm, "write_log", contended)
        before = _val("action.retry.attempts")
        DeleteAction(lm).run()
        assert losses["n"] == 1
        assert _val("action.retry.attempts") == before + 1
        assert lm.get_latest_log().state == "DELETED"

    def test_surviving_conflict_raises_with_attempt_count(self, tmp_path, monkeypatch):
        from hyperspace_tpu.actions.lifecycle import DeleteAction
        from hyperspace_tpu.index_manager import index_manager_for

        monkeypatch.setenv("HYPERSPACE_ACTION_RETRIES", "3")
        s, h = self._indexed_session(str(tmp_path))
        lm = IndexLogManager(index_manager_for(s).resolver.get_index_path("cidx"))
        monkeypatch.setattr(lm, "write_log", lambda log_id, entry: False)
        before = _val("action.retry.gave_up")
        with pytest.raises(ConcurrentWriteError, match="survived 3 attempts"):
            DeleteAction(lm).run()
        assert _val("action.retry.gave_up") == before + 1

    def test_retries_knob_of_one_disables(self, tmp_path, monkeypatch):
        from hyperspace_tpu.actions.lifecycle import DeleteAction
        from hyperspace_tpu.index_manager import index_manager_for

        monkeypatch.setenv("HYPERSPACE_ACTION_RETRIES", "1")
        s, h = self._indexed_session(str(tmp_path))
        lm = IndexLogManager(index_manager_for(s).resolver.get_index_path("cidx"))
        monkeypatch.setattr(lm, "write_log", lambda log_id, entry: False)
        with pytest.raises(ConcurrentWriteError):
            DeleteAction(lm).run()


# ---------------------------------------------------------------------------
# crash-at-every-point recovery matrix (the tentpole's durability proof)
# ---------------------------------------------------------------------------

_LOG_CRASHES = [
    "log.write:crash_before:n=1",
    "log.write:crash_after:n=1",
    "log.write:crash_before:n=2",
    "log.write:crash_after:n=2",
]
_PUBLISH_CRASHES = [
    "data.publish:crash_before:n=1",
    "data.publish:crash_after:n=1",
]
_MATRIX = [
    (action, spec)
    for action in ("create", "refresh", "optimize", "delete")
    for spec in (_LOG_CRASHES + ([] if action == "delete" else _PUBLISH_CRASHES))
]


def _fresh(root):
    s = HyperspaceSession(warehouse_dir=root)
    s.set_conf(C.INDEX_NUM_BUCKETS, 4)
    return s, Hyperspace(s)


def _run_action(h, s, root, action, phase):
    src = os.path.join(root, "src")
    if phase == "setup":
        _make_source(src, 2)
        if action != "create":
            h.create_index(
                s.read.parquet(src), CoveringIndexConfig("cidx", ["k"], ["v", "w"])
            )
        if action == "optimize":
            _make_source(src, 3, start=2)  # adds p2: incremental refresh
            h.refresh_index("cidx", C.REFRESH_MODE_INCREMENTAL)
            # ...and every bucket now holds 2 small files to compact
        return
    if action == "create":
        h.create_index(
            s.read.parquet(src), CoveringIndexConfig("cidx", ["k"], ["v", "w"])
        )
    elif action == "refresh":
        _make_source(src, 3, start=2)
        h.refresh_index("cidx", C.REFRESH_MODE_FULL)
    elif action == "optimize":
        h.optimize_index("cidx")
    elif action == "delete":
        h.delete_index("cidx")


def _query_bits(s, root):
    df = s.read.parquet(os.path.join(root, "src"))
    return _bits(df.filter(df["k"] == 7).select("v", "w").collect().to_pydict())


def _assert_no_debris(root):
    sys_dir = os.path.join(root, C.INDEXES_DIR)
    if not os.path.isdir(sys_dir):
        return
    from hyperspace_tpu.index_manager import IndexCollectionManager

    for name in os.listdir(sys_dir):
        ip = os.path.join(sys_dir, name)
        if not os.path.isdir(ip):
            continue
        lm, dm = IndexLogManager(ip), IndexDataManager(ip)
        latest = lm.get_latest_log()
        assert latest is None or latest.state in STABLE_STATES, (
            f"{name}: unstable tail {latest.state}"
        )
        assert dm.staged_versions() == [], f"{name}: staging left behind"
        assert lm.stale_temp_files() == [], f"{name}: .tmp spool left behind"
        refs = IndexCollectionManager._referenced_versions(lm)
        if latest is not None and latest.state == "DOESNOTEXIST":
            refs = set()
        orphans = [v for v in dm.get_all_versions() if v not in refs]
        assert orphans == [], f"{name}: orphan data versions {orphans}"
        if latest is not None and latest.state in STABLE_STATES:
            assert lm.stable_pointer_id() == latest.id


class TestCrashRecoveryMatrix:
    @pytest.mark.parametrize("action,spec", _MATRIX, ids=[f"{a}-{s}" for a, s in _MATRIX])
    def test_crash_recover_rerun_bit_identical(self, action, spec, tmp_path):
        # never-crashed twin: the reference end state
        twin = str(tmp_path / "twin")
        ts, th = _fresh(twin)
        _run_action(th, ts, twin, action, "setup")
        _run_action(th, ts, twin, action, "act")
        ts.enable_hyperspace()
        want = _query_bits(ts, twin)

        # crashed cell: same build, process dies at the injection point
        cell = str(tmp_path / "cell")
        s, h = _fresh(cell)
        _run_action(h, s, cell, action, "setup")
        faults.arm(spec)
        with pytest.raises(faults.InjectedCrash):
            _run_action(h, s, cell, action, "act")
        faults.disarm()

        # the "restarted process": recover, converge, compare
        s2, h2 = _fresh(cell)
        report = h2.recover(force=True)
        _assert_no_debris(cell)
        try:
            _run_action(h2, s2, cell, action, "act")
        except HyperspaceError:
            # the crash landed AFTER the final commit: action already done
            pass  # hslint: HS402 — convergence retry; the asserts below are the gate
        _assert_no_debris(cell)
        s2.enable_hyperspace()
        assert _query_bits(s2, cell) == want

        # recovery is idempotent: a second forced pass finds nothing
        report2 = h2.recover(force=True)
        assert not report2["repaired"], report2

    def test_recovery_skips_live_transaction(self, tmp_path):
        from hyperspace_tpu.actions import base as action_base
        from hyperspace_tpu.index_manager import index_manager_for

        root = str(tmp_path)
        s, h = _fresh(root)
        src = os.path.join(root, "src")
        _make_source(src, 2)
        h.create_index(s.read.parquet(src), CoveringIndexConfig("cidx", ["k"], ["v"]))
        ip = index_manager_for(s).resolver.get_index_path("cidx")
        # simulate a live in-process transaction holding the index
        action_base._tx_enter(ip)
        try:
            rep = h.recover(force=True)
            assert rep["per_index"]["cidx"]["skipped"] == "live-transaction"
        finally:
            action_base._tx_exit(ip)

    def test_fresh_transient_entry_is_age_gated(self, tmp_path):
        root = str(tmp_path)
        s, h = _fresh(root)
        src = os.path.join(root, "src")
        _make_source(src, 2)
        h.create_index(s.read.parquet(src), CoveringIndexConfig("cidx", ["k"], ["v"]))
        from hyperspace_tpu.index_manager import index_manager_for

        lm = IndexLogManager(index_manager_for(s).resolver.get_index_path("cidx"))
        nxt = lm.get_latest_id() + 1
        assert lm.write_log(nxt, _entry("REFRESHING", nxt))  # freshly stamped
        rep = h.recover()  # not forced: the entry might be another process's
        assert rep["per_index"]["cidx"]["skipped"].startswith("fresh-transient")
        assert lm.get_latest_log().state == "REFRESHING"
        # a stale one (older than HYPERSPACE_STALE_TX_S) IS rolled back
        e = lm.get_log(nxt)
        e.timestamp = int((time.time() - 7200) * 1000)
        os.unlink(lm._entry_path(nxt))
        assert lm.write_log(nxt, e)
        rep = h.recover()
        assert rep["per_index"]["cidx"]["rolled_back"] == "REFRESHING"
        assert lm.get_latest_log().state == "ACTIVE"

    def test_pointer_fix_forward(self, tmp_path):
        root = str(tmp_path)
        s, h = _fresh(root)
        src = os.path.join(root, "src")
        _make_source(src, 2)
        h.create_index(s.read.parquet(src), CoveringIndexConfig("cidx", ["k"], ["v"]))
        from hyperspace_tpu.index_manager import index_manager_for

        lm = IndexLogManager(index_manager_for(s).resolver.get_index_path("cidx"))
        lm.delete_latest_stable_log()  # crash window: final entry, no pointer
        rep = h.recover()
        assert rep["per_index"]["cidx"]["pointer_fixed"]
        assert lm.stable_pointer_id() == lm.get_latest_id()

    def test_auto_recovery_on_manager_construction(self, tmp_path):
        """A NEW session over a crashed warehouse heals it transparently
        (stale transient entry rolled back, staging swept)."""
        root = str(tmp_path)
        s, h = _fresh(root)
        src = os.path.join(root, "src")
        _make_source(src, 2)
        h.create_index(s.read.parquet(src), CoveringIndexConfig("cidx", ["k"], ["v"]))
        from hyperspace_tpu.index_manager import index_manager_for

        ip = index_manager_for(s).resolver.get_index_path("cidx")
        lm, dm = IndexLogManager(ip), IndexDataManager(ip)
        # hand-plant stale crash debris: old transient entry + staging dir
        nxt = lm.get_latest_id() + 1
        e = _entry("REFRESHING", nxt)
        e.timestamp = int((time.time() - 7200) * 1000)
        assert lm.write_log(nxt, e)
        os.makedirs(dm.staging_path(9))
        with open(os.path.join(dm.staging_path(9), "half.parquet"), "w") as f:
            f.write("partial")

        s2, h2 = _fresh(root)  # construction runs the age-gated pass
        assert lm.get_latest_log().state == "ACTIVE"
        assert dm.staged_versions() == []
