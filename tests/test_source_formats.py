"""Default source provider format coverage: orc / text (+ avro gating).

Reference parity: DefaultFileBasedSource.scala:38-95 — the default format
list is avro,csv,json,orc,parquet,text and is conf-gated via
hyperspace.index.sources.defaultFileBasedSource.supportedFileFormats.
"""

import os

import pytest

from hyperspace_tpu import Hyperspace, HyperspaceSession, IndexConfig
from hyperspace_tpu import constants as C
from hyperspace_tpu.columnar import io as cio
from hyperspace_tpu.columnar.table import ColumnBatch
from hyperspace_tpu.exceptions import HyperspaceError
from hyperspace_tpu.plan import col
from hyperspace_tpu.sources.manager import SourceProviderManager


@pytest.fixture
def ws(tmp_path):
    return str(tmp_path)


def _orc_df(session, ws):
    b = ColumnBatch.from_pydict(
        {"k": [3, 1, 2, 1], "v": [1.0, 2.0, 3.0, 4.0], "s": ["a", "b", "c", "d"]}
    )
    cio.write_orc(b, os.path.join(ws, "orc_data", "part-0.orc"))
    return session.read.orc(os.path.join(ws, "orc_data"))


def test_orc_read_roundtrip(ws):
    session = HyperspaceSession(warehouse_dir=ws)
    df = _orc_df(session, ws)
    got = df.collect().to_pydict()
    assert got["k"] == [3, 1, 2, 1]
    assert got["s"] == ["a", "b", "c", "d"]


def test_orc_source_indexable(ws):
    session = HyperspaceSession(warehouse_dir=ws)
    hs = Hyperspace(session)
    df = _orc_df(session, ws)
    hs.create_index(df, IndexConfig("orc_idx", ["k"], ["v"]))
    session.enable_hyperspace()
    q = df.filter(col("k") == 1).select("k", "v")
    assert "orc_idx" in hs.explain(q)
    got = q.collect().to_pydict()
    assert got == {"k": [1, 1], "v": [2.0, 4.0]}


def test_text_read_single_value_column(ws):
    session = HyperspaceSession(warehouse_dir=ws)
    cio.write_text(
        ColumnBatch.from_pydict({"value": ["hello", "world", ""]}),
        os.path.join(ws, "txt", "part-0.txt"),
    )
    df = session.read.text(os.path.join(ws, "txt"))
    assert df.collect().to_pydict() == {"value": ["hello", "world", ""]}
    assert df.schema.names == ["value"]


def test_format_list_conf_gated(ws):
    session = HyperspaceSession(warehouse_dir=ws)
    df = _orc_df(session, ws)
    mgr = SourceProviderManager(session)
    assert mgr.is_supported_relation(df.plan) is True
    session.set_conf(C.DEFAULT_SOURCE_FORMATS, "parquet,csv")
    mgr2 = SourceProviderManager(session)
    assert mgr2.is_supported_relation(df.plan) is not True


def test_default_format_list_matches_reference(ws):
    session = HyperspaceSession(warehouse_dir=ws)
    assert session.conf.default_source_formats == (
        "avro",
        "csv",
        "json",
        "orc",
        "parquet",
        "text",
    )


def test_avro_reader_gated_without_codec(ws):
    try:
        import fastavro  # noqa: F401

        pytest.skip("fastavro present: gating path not reachable")
    except ImportError:
        pass
    with pytest.raises(HyperspaceError, match="fastavro"):
        cio.read_avro([os.path.join(ws, "nope.avro")])
