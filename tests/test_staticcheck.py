"""Static-analysis subsystem tests: plan invariant verifier, kernel/jaxpr
auditor + retrace watchdog, hslint repo cleanliness, env-registry docs sync.

The verifier must accept every plan the engine actually produces (the
plan-stability query set whose renderings live in tests/approved_plans/,
plus all TPC-H bench queries) and reject hand-mutated plans with the right
violation code AND node path — a verifier that cries wolf is disabled
within a week, one that misses a planted bug is decoration.
"""

import os
import subprocess
import sys
from dataclasses import replace

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from hyperspace_tpu import CoveringIndexConfig, Hyperspace
from hyperspace_tpu.benchmark import TPCH_QUERIES, generate_tpch, tpch_indexes
from hyperspace_tpu.columnar import io as cio
from hyperspace_tpu.columnar.table import ColumnBatch
from hyperspace_tpu.meta.entry import FileInfo
from hyperspace_tpu.plan import col
from hyperspace_tpu.plan.kernel_cache import KernelCache
from hyperspace_tpu.plan.nodes import (
    BucketSpec,
    FileScan,
    Join,
    Project,
)
from hyperspace_tpu.plan.expr import Col
from hyperspace_tpu.staticcheck import kernel_audit
from hyperspace_tpu.staticcheck.plan_verifier import (
    DUPLICATE_FILE,
    EMPTY_FILE_SCAN,
    FILE_NOT_IN_INDEX,
    JOIN_BUCKET_MISMATCH,
    PRUNE_SPEC_LAYOUT_MISMATCH,
    UNRESOLVED_COLUMN_REF,
    PlanInvariantError,
    maybe_verify_plan,
    verify_plan,
)
from hyperspace_tpu.telemetry.metrics import REGISTRY
from hyperspace_tpu.utils import env as env_registry

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
HSLINT = os.path.join(REPO_ROOT, "tools", "hslint.py")


def _counter(name: str) -> int:
    m = REGISTRY.get(name)
    return 0 if m is None else m.value


# ---------------------------------------------------------------------------
# fixtures
# ---------------------------------------------------------------------------

@pytest.fixture()
def ci_env(tmp_session, tmp_path):
    """The plan-stability fixture: two tables, two covering indexes — the
    query set whose approved renderings live in tests/approved_plans/."""
    n = 100
    left = {
        "k": [i % 10 for i in range(n)],
        "a": [float(i) for i in range(n)],
        "b": [i * 2 for i in range(n)],
    }
    right = {"rk": list(range(10)), "c": [float(i) for i in range(10)]}
    cio.write_parquet(ColumnBatch.from_pydict(left), str(tmp_path / "L" / "l.parquet"))
    cio.write_parquet(ColumnBatch.from_pydict(right), str(tmp_path / "R" / "r.parquet"))
    hs = Hyperspace(tmp_session)
    ldf = tmp_session.read.parquet(str(tmp_path / "L"))
    rdf = tmp_session.read.parquet(str(tmp_path / "R"))
    hs.create_index(ldf, CoveringIndexConfig("ci_k", ["k"], ["a"]))
    hs.create_index(rdf, CoveringIndexConfig("ci_rk", ["rk"], ["c"]))
    tmp_session.enable_hyperspace()
    return tmp_session, tmp_path


@pytest.fixture(scope="module")
def tpch_env(tmp_path_factory):
    root = str(tmp_path_factory.mktemp("tpch_sc"))
    from hyperspace_tpu.session import HyperspaceSession

    session = HyperspaceSession(warehouse_dir=root)
    generate_tpch(root, rows_lineitem=30_000, seed=3)
    hs = Hyperspace(session)
    tpch_indexes(session, hs, root)
    return session, root


def _approved_plan_queries(session, tmp):
    """The exact query shapes of the approved_plans golden set."""
    from hyperspace_tpu.plan import Count, Sum

    ldf = session.read.parquet(str(tmp / "L"))
    rdf = session.read.parquet(str(tmp / "R"))
    return {
        "filter_index_scan": ldf.filter(col("k") == 3).select("k", "a"),
        "filter_no_index": ldf.filter(col("b") == 4).select("k", "b"),
        "join_index_scan": ldf.join(rdf, col("k") == col("rk")).select("k", "a", "c"),
        "filter_agg": (
            ldf.filter(col("k") == 3).agg(Sum(col("a")), Count(col("a")))
        ),
    }


def _indexed_scan(plan) -> FileScan:
    for n in plan.preorder():
        if isinstance(n, FileScan) and n.index_info is not None:
            return n
    raise AssertionError("no index scan in plan")


# ---------------------------------------------------------------------------
# plan verifier
# ---------------------------------------------------------------------------

class TestPlanVerifierAccepts:
    def test_approved_plan_query_set(self, ci_env):
        session, tmp = ci_env
        for name, q in _approved_plan_queries(session, tmp).items():
            violations = verify_plan(q.optimized_plan(), session)
            assert violations == [], f"{name}: {violations}"

    def test_all_tpch_bench_plans(self, tpch_env):
        session, root = tpch_env
        session.enable_hyperspace()
        try:
            for name, q in TPCH_QUERIES.items():
                plan = q(session, root).optimized_plan()
                violations = verify_plan(plan, session)
                assert violations == [], f"{name}: {violations}"
        finally:
            session.disable_hyperspace()

    def test_tpch_raw_plans_too(self, tpch_env):
        session, root = tpch_env
        session.disable_hyperspace()
        for name, q in TPCH_QUERIES.items():
            violations = verify_plan(q(session, root).optimized_plan(), session)
            assert violations == [], f"{name}: {violations}"

    def test_verified_result_identical(self, ci_env, monkeypatch):
        session, tmp = ci_env
        q = lambda: session.read.parquet(str(tmp / "L")).filter(  # noqa: E731
            col("k") == 3
        ).select("k", "a")
        plain = q().to_pydict()
        runs0 = _counter("staticcheck.plan.runs")
        monkeypatch.setenv("HYPERSPACE_VERIFY_PLAN", "1")
        verified = q().to_pydict()
        assert verified == plain
        assert _counter("staticcheck.plan.runs") > runs0

    def test_hook_noop_when_disabled(self, ci_env, monkeypatch):
        session, tmp = ci_env
        monkeypatch.delenv("HYPERSPACE_VERIFY_PLAN", raising=False)
        plan = _approved_plan_queries(session, tmp)["filter_index_scan"].optimized_plan()
        runs0 = _counter("staticcheck.plan.runs")
        maybe_verify_plan(plan, session)
        assert _counter("staticcheck.plan.runs") == runs0


class TestPlanVerifierRejects:
    def test_dangling_column(self, ci_env):
        session, tmp = ci_env
        plan = _approved_plan_queries(session, tmp)["filter_index_scan"].optimized_plan()
        bad = Project([Col("does_not_exist")], plan)
        with pytest.raises(PlanInvariantError) as ei:
            verify_plan(bad, session)
        err = ei.value
        assert err.code == UNRESOLVED_COLUMN_REF
        assert err.path.startswith("Project")

    def test_stale_prune_spec_num_buckets(self, ci_env):
        session, tmp = ci_env
        plan = _approved_plan_queries(session, tmp)["filter_index_scan"].optimized_plan()
        scan = _indexed_scan(plan)
        assert scan.prune_spec is not None
        scan.prune_spec = replace(
            scan.prune_spec, num_buckets=scan.prune_spec.num_buckets + 3
        )
        with pytest.raises(PlanInvariantError) as ei:
            verify_plan(plan, session)
        assert ei.value.code == PRUNE_SPEC_LAYOUT_MISMATCH
        assert "FileScan" in ei.value.path

    def test_file_not_in_index(self, ci_env, tmp_path):
        session, tmp = ci_env
        plan = _approved_plan_queries(session, tmp)["filter_index_scan"].optimized_plan()
        scan = _indexed_scan(plan)
        stray = FileInfo.from_path(str(tmp / "L" / "l.parquet"))
        scan.files = list(scan.files) + [stray]
        with pytest.raises(PlanInvariantError) as ei:
            verify_plan(plan, session)
        codes = {v.code for v in ei.value.violations}
        assert FILE_NOT_IN_INDEX in codes

    def test_duplicate_file(self, ci_env):
        session, tmp = ci_env
        plan = _approved_plan_queries(session, tmp)["filter_no_index"].optimized_plan()
        for n in plan.preorder():
            if isinstance(n, FileScan):
                n.files = list(n.files) + [n.files[0]]
                break
        with pytest.raises(PlanInvariantError) as ei:
            verify_plan(plan, session)
        assert DUPLICATE_FILE in {v.code for v in ei.value.violations}

    def test_empty_unpruned_scan(self, ci_env):
        session, tmp = ci_env
        plan = _approved_plan_queries(session, tmp)["filter_no_index"].optimized_plan()
        for n in plan.preorder():
            if isinstance(n, FileScan):
                n.files = []
                break
        with pytest.raises(PlanInvariantError) as ei:
            verify_plan(plan, session)
        assert EMPTY_FILE_SCAN in {v.code for v in ei.value.violations}

    def test_join_bucket_mismatch(self, ci_env):
        session, tmp = ci_env
        q = _approved_plan_queries(session, tmp)["join_index_scan"]
        plan = q.optimized_plan()

        joins = [n for n in plan.preorder() if isinstance(n, Join)]
        assert joins, "join plan must contain a Join node"
        scans = [
            n for n in plan.preorder()
            if isinstance(n, FileScan) and n.bucket_spec is not None
        ]
        if len(scans) < 2:
            pytest.skip("join rewrite did not bucket both sides")
        spec = scans[0].bucket_spec
        scans[0].bucket_spec = BucketSpec(
            spec.num_buckets * 2, spec.bucket_columns, spec.sort_columns
        )
        # keep the layout contract consistent with the (mutated) hint so
        # ONLY the cross-side invariant fires
        if scans[0].prune_spec is not None:
            scans[0].prune_spec = replace(
                scans[0].prune_spec, num_buckets=spec.num_buckets * 2
            )
        violations = verify_plan(plan, session=None, raise_on_violation=False)
        assert JOIN_BUCKET_MISMATCH in {v.code for v in violations}


# ---------------------------------------------------------------------------
# kernel audit
# ---------------------------------------------------------------------------

class TestKernelAudit:
    def test_flags_host_callback_kernel(self, monkeypatch):
        monkeypatch.setenv("HYPERSPACE_KERNEL_AUDIT", "1")
        cache = KernelCache("audit_test", 8)

        def build():
            def cb(x):
                return np.asarray(x) * 2

            def kernel(x):
                return jax.pure_callback(
                    cb, jax.ShapeDtypeStruct(x.shape, x.dtype), x
                )

            return jax.jit(kernel)  # hslint: HS201 — synthetic hazard fixture

        before = _counter("staticcheck.kernel.hazard.HOST_CALLBACK")
        k = cache.get_or_build(("hostcb", (("x", "int32"),)), build, "hostcb")
        out = k(jnp.arange(4))
        assert list(np.asarray(out)) == [0, 2, 4, 6]  # behavior unchanged
        assert _counter("staticcheck.kernel.hazard.HOST_CALLBACK") == before + 1

    def test_flags_nondeterministic_primitive(self):
        jaxpr = jax.make_jaxpr(
            lambda: jax.lax.rng_uniform(jnp.float32(0), jnp.float32(1), (4,))
        )()
        hazards = kernel_audit.audit_jaxpr("rng_kind", jaxpr)
        assert any(h.code == kernel_audit.NONDETERMINISTIC for h in hazards)

    def test_flags_implicit_f64_promotion(self):
        try:
            from jax.experimental import enable_x64
        except ImportError:
            pytest.skip("jax.experimental.enable_x64 unavailable")
        with enable_x64():
            jaxpr = jax.make_jaxpr(lambda x: x + 0.5)(np.arange(3, dtype=np.int64))
        hazards = kernel_audit.audit_jaxpr("promo_kind", jaxpr)
        assert any(h.code == kernel_audit.IMPLICIT_F64 for h in hazards)

    def test_clean_kernel_has_no_hazards(self):
        jaxpr = jax.make_jaxpr(lambda x: jnp.where(x > 1, x, 0).sum())(
            np.arange(8, dtype=np.int32)
        )
        assert kernel_audit.audit_jaxpr("clean_kind", jaxpr) == []

    def test_audit_disabled_is_transparent(self, monkeypatch):
        monkeypatch.delenv("HYPERSPACE_KERNEL_AUDIT", raising=False)
        sentinel = object()
        out = kernel_audit.observe_compile(
            "cache", "kind_x", ("kind_x", (("a", "i32"),)), sentinel
        )
        assert out is sentinel

    def test_retrace_watchdog_fires_on_fingerprint_churn(self, monkeypatch):
        monkeypatch.setenv("HYPERSPACE_RETRACE_WARN", "5")
        kernel_audit.reset_watchdog()
        try:
            sig = (("x", "int32"),)
            msg = None
            for i in range(8):
                msg = kernel_audit.WATCHDOG.record(
                    "wd_cache", "wd_kind", ("wd_kind", f"pred_{i}", sig)
                ) or msg
            assert msg is not None, "watchdog must fire past the threshold"
            assert "wd_kind" in msg and "pos 1" in msg  # the varying position
        finally:
            kernel_audit.reset_watchdog()

    def test_watchdog_quiet_across_distinct_signatures(self, monkeypatch):
        monkeypatch.setenv("HYPERSPACE_RETRACE_WARN", "5")
        kernel_audit.reset_watchdog()
        try:
            for i in range(16):
                msg = kernel_audit.WATCHDOG.record(
                    "wd_cache2", "wd_kind2",
                    ("wd_kind2", "pred", (("x", f"dtype_{i}"),)),
                )
                assert msg is None  # each signature group has ONE key
        finally:
            kernel_audit.reset_watchdog()


# ---------------------------------------------------------------------------
# hslint + env registry
# ---------------------------------------------------------------------------

class TestHslint:
    def test_package_is_clean_modulo_baseline(self):
        proc = subprocess.run(
            [sys.executable, HSLINT],
            capture_output=True, text=True, timeout=120,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "0 new violation(s)" in proc.stdout

    def test_catches_planted_violations(self, tmp_path):
        bad = tmp_path / "bad_module.py"
        bad.write_text(
            "import os, time, threading, jax\n"
            "from hyperspace_tpu.telemetry import trace\n"
            "MODE = os.environ.get('HYPERSPACE_WHATEVER', '1')\n"
            "kernel = jax.jit(lambda x: x)\n"
            "def f():\n"
            "    with trace.span('exec:thing'):\n"
            "        return time.time()\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._d = dict()\n"
            "    def put(self, k, v):\n"
            "        self._d[k] = v\n"
        )
        proc = subprocess.run(
            [sys.executable, HSLINT, str(bad), "--no-baseline"],
            capture_output=True, text=True, timeout=120,
        )
        assert proc.returncode == 1
        for code in ("HS201", "HS301", "HS302", "HS303"):
            assert code in proc.stdout, f"{code} missing:\n{proc.stdout}"

    def test_suppression_comment_silences(self, tmp_path):
        ok = tmp_path / "ok_module.py"
        ok.write_text(
            "import jax\n"
            "kernel = jax.jit(lambda x: x)  # hslint: HS201 — fixture\n"
        )
        proc = subprocess.run(
            [sys.executable, HSLINT, str(ok), "--no-baseline"],
            capture_output=True, text=True, timeout=120,
        )
        assert proc.returncode == 0, proc.stdout


class TestEnvRegistry:
    def test_docs_table_in_sync(self):
        assert env_registry.update_docs(
            os.path.join(REPO_ROOT, "docs", "performance.md"), check_only=True
        ), "docs/performance.md env table is stale — run " \
           "python -m hyperspace_tpu.utils.env --update-docs"

    def test_every_scattered_knob_is_registered(self):
        names = {k.name for k in env_registry.all_knobs()}
        for expected in (
            "HYPERSPACE_PIPELINE", "HYPERSPACE_PRUNE", "HYPERSPACE_IO_THREADS",
            "HYPERSPACE_JOIN_SPLIT_ROWS", "HYPERSPACE_TRACE",
            "HYPERSPACE_DEVICE_STRICT", "HYPERSPACE_VERIFY_PLAN",
            "HYPERSPACE_KERNEL_AUDIT", "HYPERSPACE_RETRACE_WARN",
        ):
            assert expected in names

    def test_typed_reads(self, monkeypatch):
        assert env_registry.env_int("HYPERSPACE_PIPELINE_DEPTH") == 2
        monkeypatch.setenv("HYPERSPACE_PIPELINE_DEPTH", "5")
        assert env_registry.env_int("HYPERSPACE_PIPELINE_DEPTH") == 5
        monkeypatch.delenv("HYPERSPACE_VERIFY_PLAN", raising=False)
        assert env_registry.env_bool("HYPERSPACE_VERIFY_PLAN") is False
        monkeypatch.setenv("HYPERSPACE_VERIFY_PLAN", "1")
        assert env_registry.env_bool("HYPERSPACE_VERIFY_PLAN") is True
        # unregistered names need an explicit default
        with pytest.raises(KeyError):
            env_registry.env_int("HYPERSPACE_NOT_A_KNOB")
        assert env_registry.env_int("HYPERSPACE_NOT_A_KNOB", 7) == 7
