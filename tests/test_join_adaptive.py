"""Memory-adaptive spilling join execution under the device-memory ledger.

Covers the adaptive half of the bucketed join (plan/join_memory +
device_join._BandScheduler): per-bucket strategy selection from planted
footer stats, grant-derived split sizing with the
``HYPERSPACE_JOIN_SPLIT_ROWS`` override, park/spill/resume ordering on the
shared device ledger, cancellation of a PARKED wave releasing both the
host and device ledgers, ledger conservation (reservations drain to zero
after over-budget joins that stay bit-identical), and warm-repeat
zero-compile behavior across grant sizes."""

import threading
import time
import types

import numpy as np
import pytest

from hyperspace_tpu import CoveringIndexConfig, Hyperspace
from hyperspace_tpu import constants as C
from hyperspace_tpu.columnar import io as cio
from hyperspace_tpu.columnar.table import ColumnBatch
from hyperspace_tpu.plan import Count, Max, Min, Sum, col, lit
from hyperspace_tpu.plan import join_memory
from hyperspace_tpu.serve import budget as serve_budget
from hyperspace_tpu.serve.context import (
    QueryCancelledError,
    QueryContext,
    query_scope,
)
from hyperspace_tpu.telemetry.metrics import REGISTRY


def hex_rows(d: dict) -> str:
    return repr(
        {
            k: [x.hex() if isinstance(x, float) else x for x in v]
            for k, v in d.items()
        }
    )


def _write_sides(tmp_path, left, right):
    cio.write_parquet(
        ColumnBatch.from_pydict(left), str(tmp_path / "l" / "l.parquet")
    )
    cio.write_parquet(
        ColumnBatch.from_pydict(right), str(tmp_path / "r" / "r.parquet")
    )


def _index_sides(session, tmp_path, buckets=4):
    session.set_conf(C.INDEX_NUM_BUCKETS, buckets)
    hs = Hyperspace(session)
    hs.create_index(
        session.read.parquet(str(tmp_path / "l")),
        CoveringIndexConfig("jl", ["k"], ["p"]),
    )
    hs.create_index(
        session.read.parquet(str(tmp_path / "r")),
        CoveringIndexConfig("jr", ["rk"], ["w"]),
    )
    return hs


@pytest.fixture()
def join_env(tmp_session, tmp_path):
    """Mid-size uniform join: big enough that several band waves dispatch,
    so a tiny device grant forces parks + spills."""
    rng = np.random.default_rng(7)
    n = 48_000
    left = {
        "k": rng.integers(0, 1200, n).tolist(),
        "p": rng.uniform(0, 100, n).tolist(),
    }
    right = {"rk": list(range(0, 900)), "w": rng.uniform(size=900).tolist()}
    _write_sides(tmp_path, left, right)
    _index_sides(tmp_session, tmp_path)
    return tmp_session, tmp_path


def _plain_q(session, tmp_path):
    l = session.read.parquet(str(tmp_path / "l")).select("k", "p")
    r = session.read.parquet(str(tmp_path / "r")).select("rk", "w")
    return l.join(r, col("k") == col("rk")).select("k", "p", "w")


def _agg_q(session, tmp_path):
    l = session.read.parquet(str(tmp_path / "l")).select("k", "p")
    r = session.read.parquet(str(tmp_path / "r")).select("rk", "w")
    return (
        l.join(r, col("k") == col("rk"))
        .group_by("k")
        .agg(
            Count(lit(1)).alias("n"),
            Min(col("p")).alias("lo"),
            Max(col("p")).alias("hi"),
        )
    )


def _set_grant(monkeypatch, mb: str):
    monkeypatch.setenv("HYPERSPACE_DEVICE_BUDGET_MB", mb)
    return serve_budget.reset_device_budget()


@pytest.fixture(autouse=True)
def _fresh_device_ledger():
    """Each test reads its own grant; restore the default ledger after."""
    yield
    serve_budget.reset_device_budget()


# ---------------------------------------------------------------------------
# strategy selection from planted footer stats
# ---------------------------------------------------------------------------


class _FakeSide:
    """Duck-typed BucketedSide over planted per-bucket parquet files: the
    planner reads only ``spec.num_buckets`` and ``files_for_bucket`` (file
    objects with ``name``/``size``), so real footers drive the stats."""

    def __init__(self, files_by_bucket: dict, num_buckets: int):
        self._files = files_by_bucket
        self.spec = types.SimpleNamespace(num_buckets=num_buckets)

    def files_for_bucket(self, b):
        return self._files.get(b, [])


def _plant_bucket(tmp_path, name: str, rows: int):
    import os

    path = str(tmp_path / "planted" / f"{name}.parquet")
    rng = np.random.default_rng(rows)
    cio.write_parquet(
        ColumnBatch.from_pydict(
            {
                "k": rng.integers(0, 1000, rows).tolist(),
                "p": rng.uniform(size=rows).tolist(),
            }
        ),
        path,
    )
    return types.SimpleNamespace(name=path, size=os.path.getsize(path))


class TestStrategySelection:
    def test_strategies_from_planted_footer_stats(self, tmp_path, monkeypatch):
        """Tiny pair -> broadcast, mid -> banded, oversized probe side ->
        split, with the split threshold derived from the grant (1 MB grant
        -> the 4096-row floor) — all decided from footer stats alone."""
        monkeypatch.delenv("HYPERSPACE_JOIN_SPLIT_ROWS", raising=False)
        monkeypatch.setenv("HYPERSPACE_JOIN_BROADCAST_ROWS", "100")
        _set_grant(monkeypatch, "1")
        left = _FakeSide(
            {
                0: [_plant_bucket(tmp_path, "l0", 50)],
                1: [_plant_bucket(tmp_path, "l1", 2000)],
                2: [_plant_bucket(tmp_path, "l2", 6000)],
            },
            num_buckets=3,
        )
        right = _FakeSide(
            {
                0: [_plant_bucket(tmp_path, "r0", 40)],
                1: [_plant_bucket(tmp_path, "r1", 500)],
                2: [_plant_bucket(tmp_path, "r2", 500)],
            },
            num_buckets=3,
        )
        plan = join_memory.plan_join_memory(left, right, session=None)
        assert plan is not None
        assert plan.strategy(0) == "broadcast"
        assert plan.strategy(1) == "banded"
        assert plan.strategy(2) == "split"
        assert plan.split_rows(0) == 0  # broadcast never splits
        assert plan.split_rows(2) == plan.derived_split_rows > 0
        assert plan.override_split_rows is None

    def test_explicit_knob_overrides_grant(self, tmp_path, monkeypatch):
        """An explicitly-set HYPERSPACE_JOIN_SPLIT_ROWS wins over the
        derived value (the documented precedence)."""
        monkeypatch.setenv("HYPERSPACE_JOIN_BROADCAST_ROWS", "100")
        monkeypatch.setenv("HYPERSPACE_JOIN_SPLIT_ROWS", "10000")
        _set_grant(monkeypatch, "1")
        left = _FakeSide(
            {0: [_plant_bucket(tmp_path, "lo", 6000)]}, num_buckets=1
        )
        right = _FakeSide(
            {0: [_plant_bucket(tmp_path, "ro", 500)]}, num_buckets=1
        )
        plan = join_memory.plan_join_memory(left, right, session=None)
        assert plan.override_split_rows == 10000
        # 6000 rows under the 10000 override: banded, not split
        assert plan.strategy(0) == "banded"
        assert plan.split_rows(0) == 10000

    def test_disabled_ledger_disables_planning(self, tmp_path, monkeypatch):
        _set_grant(monkeypatch, "0")
        left = _FakeSide(
            {0: [_plant_bucket(tmp_path, "ld", 50)]}, num_buckets=1
        )
        assert join_memory.plan_join_memory(left, left, session=None) is None

    def test_derive_split_rows_shape(self):
        assert join_memory.derive_split_rows(0, 16.0) == 0
        small = join_memory.derive_split_rows(1 << 20, 16.0)
        big = join_memory.derive_split_rows(1 << 30, 16.0)
        assert small == join_memory._SPLIT_ROWS_FLOOR
        assert big > small
        assert big & (big - 1) == 0  # power of two: stable pad classes


# ---------------------------------------------------------------------------
# park / spill / resume ordering on the band scheduler
# ---------------------------------------------------------------------------


class TestParkResumeOrdering:
    def test_second_wave_parks_and_spills_first(self, monkeypatch):
        """With a grant that fits exactly one wave, dispatching the second
        wave must park, spill wave 1 (retire fetch + release), then
        dispatch — and the spilled wave's results survive on the wave."""
        from hyperspace_tpu.plan.device_join import _BandScheduler
        from hyperspace_tpu.plan.join_memory import DeviceLedger

        monkeypatch.setenv("HYPERSPACE_PARK_WAIT_MS", "1")
        _set_grant(monkeypatch, str(150 / 2**20))  # 150-byte grant
        events = []
        ledger = DeviceLedger("t")
        parks0 = REGISTRY.counter("join.spill.parks").value
        spills0 = REGISTRY.counter("join.spill.spills").value
        resumes0 = REGISTRY.counter("join.spill.resumes").value
        try:
            sched = _BandScheduler(
                lambda pads, items: events.append(("dispatch", tuple(items)))
                or f"rec-{items[0]}",
                banded=True,
                wave=1,
                ledger=ledger,
                estimate=lambda pads, items: 100,
                retire=lambda w: events.append(("spill", tuple(w.items)))
                or f"done-{w.items[0]}",
            )
            sched.add("a", 10, 10)  # wave 1: fits (100 <= 150)
            sched.add("b", 10, 10)  # wave 2: parks, spills wave 1, resumes
            waves = sched.finish()
        finally:
            ledger.close()
        assert events == [
            ("dispatch", ("a",)),
            ("spill", ("a",)),
            ("dispatch", ("b",)),
        ]
        assert [w.done for w in waves] == ["done-a", None]
        assert REGISTRY.counter("join.spill.parks").value == parks0 + 1
        assert REGISTRY.counter("join.spill.spills").value == spills0 + 1
        assert REGISTRY.counter("join.spill.resumes").value == resumes0 + 1

    def test_fitting_waves_never_park(self, monkeypatch):
        from hyperspace_tpu.plan.device_join import _BandScheduler
        from hyperspace_tpu.plan.join_memory import DeviceLedger

        _set_grant(monkeypatch, "64")
        parks0 = REGISTRY.counter("join.spill.parks").value
        ledger = DeviceLedger("t")
        try:
            sched = _BandScheduler(
                lambda pads, items: "rec",
                banded=True,
                wave=1,
                ledger=ledger,
                estimate=lambda pads, items: 100,
                retire=lambda w: pytest.fail("must not spill under budget"),
            )
            for item in ("a", "b", "c"):
                sched.add(item, 10, 10)
            sched.finish()
        finally:
            ledger.close()
        assert REGISTRY.counter("join.spill.parks").value == parks0


# ---------------------------------------------------------------------------
# cancellation of a parked wave releases both ledgers
# ---------------------------------------------------------------------------


class TestParkedCancellation:
    def test_cancel_parked_admission_releases_both_ledgers(self, monkeypatch):
        """A wave parked behind ANOTHER query's device reservations (its
        own stream fully drained, courtesy-waiting on the release
        condition) must observe check_cancelled() and unwind, returning
        its host-ledger bytes and closing its device stream."""
        from hyperspace_tpu.plan.join_memory import DeviceLedger

        monkeypatch.setenv("HYPERSPACE_PARK_WAIT_MS", "60000")
        acct = _set_grant(monkeypatch, str(1000 / 2**20))  # 1000-byte grant
        other = acct.stream("other-query")
        assert other.try_reserve(1000)  # the ledger is FULL with other's bytes
        host = serve_budget.global_budget().stream("join")
        assert host.try_reserve(4096)
        ctx = QueryContext(label="parked-join")
        state = {}
        parks0 = REGISTRY.counter("join.spill.parks").value

        def worker():
            ledger = DeviceLedger("join_agg")
            try:
                with query_scope(ctx):
                    ledger.admit(500, lambda: False)
                state["outcome"] = "granted"
            except QueryCancelledError:
                state["outcome"] = "cancelled"
            finally:
                # the join wrappers' finally blocks: both ledgers release
                ledger.close()
                host.close()

        t = threading.Thread(target=worker)
        t.start()
        deadline = time.time() + 10
        while (
            REGISTRY.counter("join.spill.parks").value == parks0
            and time.time() < deadline
            and t.is_alive()
        ):
            time.sleep(0.01)
        ctx.cancel()
        t.join(timeout=10)
        assert not t.is_alive()
        assert state["outcome"] == "cancelled"
        # device ledger: only the other query's bytes remain; host: drained
        assert acct.held_bytes() == 1000
        assert serve_budget.global_budget().held_bytes() == 0
        other.close()
        assert acct.held_bytes() == 0
        assert acct.check_consistency()


# ---------------------------------------------------------------------------
# end-to-end: over-budget joins complete, bit-identical, ledger conserved
# ---------------------------------------------------------------------------


class TestLedgerConservation:
    def test_overbudget_join_completes_and_drains(self, join_env, monkeypatch):
        session, tmp_path = join_env
        monkeypatch.setenv("HYPERSPACE_PIPELINE", "1")
        session.enable_hyperspace()
        session.set_conf(C.EXEC_TPU_ENABLED, True)
        try:
            _set_grant(monkeypatch, "4096")
            ref_plain = _plain_q(session, tmp_path).to_pydict()
            ref_agg = _agg_q(session, tmp_path).to_pydict()
            acct = _set_grant(monkeypatch, "0.1")
            parks0 = REGISTRY.counter("join.spill.parks").value
            spills0 = REGISTRY.counter("join.spill.spills").value
            got_plain = _plain_q(session, tmp_path).to_pydict()
            got_agg = _agg_q(session, tmp_path).to_pydict()
        finally:
            session.set_conf(C.EXEC_TPU_ENABLED, False)
            session.disable_hyperspace()
        assert hex_rows(got_plain) == hex_rows(ref_plain)
        assert hex_rows(got_agg) == hex_rows(ref_agg)
        assert REGISTRY.counter("join.spill.parks").value > parks0
        assert REGISTRY.counter("join.spill.spills").value > spills0
        # conservation: every wave reservation drained back to zero
        assert acct.held_bytes() == 0
        assert acct.check_consistency()
        assert not acct.state()["streams"]

    def test_pipeline_off_matches_adaptive(self, join_env, monkeypatch):
        """HYPERSPACE_PIPELINE=0 (barrier + global pad) stays the
        bit-identity reference for the spilling run."""
        session, tmp_path = join_env
        session.enable_hyperspace()
        session.set_conf(C.EXEC_TPU_ENABLED, True)
        try:
            monkeypatch.setenv("HYPERSPACE_PIPELINE", "0")
            _set_grant(monkeypatch, "4096")
            serial = _agg_q(session, tmp_path).to_pydict()
            monkeypatch.setenv("HYPERSPACE_PIPELINE", "1")
            _set_grant(monkeypatch, "0.1")
            adaptive = _agg_q(session, tmp_path).to_pydict()
        finally:
            session.set_conf(C.EXEC_TPU_ENABLED, False)
            session.disable_hyperspace()
        assert hex_rows(adaptive) == hex_rows(serial)


# ---------------------------------------------------------------------------
# warm repeats stay zero-compile at every grant size
# ---------------------------------------------------------------------------


class _ListSink:
    def __init__(self):
        self.spans = []

    def write_span(self, span):
        self.spans.append({"name": span.name})

    def close(self):
        pass


class TestWarmRepeatAcrossGrants:
    def test_zero_compile_spans_per_grant(self, join_env, monkeypatch):
        """At each grant size the first run traces whatever new pad
        classes the grant implies — once; the warm repeat must serve every
        kernel from the cache (no retrace, no compile:* span), spilling or
        not."""
        from hyperspace_tpu.telemetry import trace

        session, tmp_path = join_env
        monkeypatch.setenv("HYPERSPACE_PIPELINE", "1")
        session.enable_hyperspace()
        session.set_conf(C.EXEC_TPU_ENABLED, True)
        try:
            for grant in ("0.1", "64"):
                _set_grant(monkeypatch, grant)
                _plain_q(session, tmp_path).collect()  # cold at this grant
                _agg_q(session, tmp_path).collect()
                retraces = REGISTRY.counter("kernel.retrace").value
                sink = _ListSink()
                trace.enable(sink)
                try:
                    _plain_q(session, tmp_path).collect()
                    _agg_q(session, tmp_path).collect()
                finally:
                    trace.disable()
                assert REGISTRY.counter("kernel.retrace").value == retraces, (
                    f"warm repeat retraced at grant {grant}MB"
                )
                names = [s["name"] for s in sink.spans]
                assert not [n for n in names if n.startswith("compile:")]
                assert [n for n in names if n.startswith("join:")]
        finally:
            session.set_conf(C.EXEC_TPU_ENABLED, False)
            session.disable_hyperspace()
