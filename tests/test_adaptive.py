"""Mid-query adaptive re-optimization (``HYPERSPACE_ADAPTIVE``).

Pins the PR-18 tentpole guarantees:

- default-off is bit-identical off: every hook is one mode read returning
  the static answer, and no ``adaptive.*`` counter ever moves,
- conjunct reordering produces the exact static filter mask (including
  Kleene NULL semantics) in any observed order, and records the switch,
- join re-planning is fed by a NON-destructive ``observe_actual`` (the
  estimate map survives), flips banded→split from decoded actuals after
  the warmup window, and stays bit-identical end-to-end under planted
  footer-stats mis-estimates,
- an index scan that underdelivers its prune prediction aborts at a chunk
  boundary, is vetoed, and the replanned query completes bit-identical to
  the raw scan — driven both by an honest prediction with a sub-1 abort
  factor and by a planted sketch-NDV tamper under the default factor,
- ``HYPERSPACE_ADAPTIVE=verify`` re-runs the final plan statically and
  raises on any planted divergence (and stays silent on honest runs).
"""

import json
import glob
import os

import numpy as np
import pytest

from hyperspace_tpu import CoveringIndexConfig, Hyperspace
from hyperspace_tpu import constants as C
from hyperspace_tpu.columnar import io as cio
from hyperspace_tpu.columnar.table import Column, ColumnBatch
from hyperspace_tpu.exceptions import HyperspaceError
from hyperspace_tpu.models import covering
from hyperspace_tpu.models.dataskipping import sketch_store
from hyperspace_tpu.plan import Count, Max, Min, col, lit
from hyperspace_tpu.plan import adaptive, join_memory
from hyperspace_tpu.serve import budget as serve_budget
from hyperspace_tpu.telemetry import plan_stats
from hyperspace_tpu.telemetry.metrics import REGISTRY


def _bits(d: dict) -> str:
    return repr(
        {
            k: [x.hex() if isinstance(x, float) else x for x in v]
            for k, v in d.items()
        }
    )


def _counter(name: str) -> float:
    return REGISTRY.counter(name).value


@pytest.fixture(autouse=True)
def _fresh_device_ledger():
    yield
    serve_budget.reset_device_budget()


# ---------------------------------------------------------------------------
# mode plumbing + the default-off pin
# ---------------------------------------------------------------------------


class TestModeAndOff:
    def test_mode_parsing(self, monkeypatch):
        monkeypatch.delenv("HYPERSPACE_ADAPTIVE", raising=False)
        assert adaptive.mode() == "0"
        assert not adaptive.active()
        for raw, want in (
            ("0", "0"), ("off", "0"), ("", "0"), ("no", "0"),
            ("1", "1"), ("true", "1"), ("ON", "1"),
            ("verify", "verify"), (" Verify ", "verify"),
        ):
            monkeypatch.setenv("HYPERSPACE_ADAPTIVE", raw)
            assert adaptive.mode() == want, raw

    def test_force_mode_overrides_knob(self, monkeypatch):
        monkeypatch.setenv("HYPERSPACE_ADAPTIVE", "1")
        with adaptive.force_mode("0"):
            assert adaptive.mode() == "0"
            with adaptive.force_mode("verify"):
                assert adaptive.mode() == "verify"
            assert adaptive.mode() == "0"
        assert adaptive.mode() == "1"

    def test_knob_defaults(self, monkeypatch):
        monkeypatch.delenv("HYPERSPACE_ADAPTIVE_ABORT_FACTOR", raising=False)
        monkeypatch.delenv("HYPERSPACE_ADAPTIVE_WARMUP_CHUNKS", raising=False)
        assert adaptive.abort_factor() == 4.0
        assert adaptive.warmup_chunks() == 2
        monkeypatch.setenv("HYPERSPACE_ADAPTIVE_WARMUP_CHUNKS", "0")
        assert adaptive.warmup_chunks() == 1  # floored: never zero warmup

    def test_off_hooks_return_static_answers(self, monkeypatch):
        monkeypatch.delenv("HYPERSPACE_ADAPTIVE", raising=False)
        rng = np.random.default_rng(0)
        batch = ColumnBatch.from_pydict(
            {"a": rng.integers(0, 10, 4000).tolist()}
        )
        cond = (col("a") > 1) & (col("a") < 8)
        assert adaptive.conjunct_mask(cond, batch) is None
        chunks = iter(())
        assert adaptive.monitor_scan_chunks(
            chunks, _FakeScan(), ({}, [])
        ) is chunks
        assert adaptive.vetoed_indexes() == frozenset()

    def test_off_query_is_bit_identical_and_counter_silent(
        self, tmp_session, tmp_path, monkeypatch
    ):
        """The acceptance pin: unset vs explicit 0 — same bits, and the
        whole adaptive counter family stays untouched."""
        rng = np.random.default_rng(3)
        n = 6000
        cio.write_parquet(
            ColumnBatch.from_pydict(
                {
                    "a": rng.integers(0, 50, n).tolist(),
                    "b": rng.integers(0, 50, n).tolist(),
                    "c": rng.integers(0, 50, n).tolist(),
                }
            ),
            str(tmp_path / "t" / "p.parquet"),
        )
        # col-vs-col conjuncts never push to arrow: the host Filter sees
        # the full batch, so the off-path pin exercises the real site
        q = lambda: (
            tmp_session.read.parquet(str(tmp_path / "t"))
            .filter(
                (col("a") != col("c"))
                & (col("a") > col("b"))
                & (col("b") >= col("c"))
            )
            .select("a", "b", "c")
        )
        monkeypatch.setattr(adaptive, "_REORDER_CHUNK_ROWS", 1024)
        before = {
            k: v
            for k, v in REGISTRY.snapshot().items()
            if k.startswith("adaptive.")
        }
        monkeypatch.delenv("HYPERSPACE_ADAPTIVE", raising=False)
        unset = q().to_pydict()
        monkeypatch.setenv("HYPERSPACE_ADAPTIVE", "0")
        explicit = q().to_pydict()
        assert _bits(unset) == _bits(explicit)
        after = {
            k: v
            for k, v in REGISTRY.snapshot().items()
            if k.startswith("adaptive.")
        }
        assert after == before


class _FakeScan:
    prune_spec = None
    index_info = None
    plan_id = -1


# ---------------------------------------------------------------------------
# site 2: observed-selectivity conjunct reordering
# ---------------------------------------------------------------------------


def _nullable_int(values):
    data = np.array([0 if v is None else v for v in values], dtype=np.int64)
    validity = np.array([v is not None for v in values], dtype=bool)
    return Column(data, "int64", validity)


@pytest.fixture()
def reorder_env(monkeypatch):
    """Small chunks + 1-chunk warmup so a few thousand rows adapt."""
    monkeypatch.setattr(adaptive, "_REORDER_CHUNK_ROWS", 1024)
    monkeypatch.setenv("HYPERSPACE_ADAPTIVE_WARMUP_CHUNKS", "1")
    monkeypatch.setenv("HYPERSPACE_ADAPTIVE", "1")


class TestConjunctReorder:
    def _batch(self, n=6000, seed=11):
        rng = np.random.default_rng(seed)
        return ColumnBatch.from_pydict(
            {
                "a": rng.integers(0, 100, n).tolist(),
                "b": rng.integers(0, 100, n).tolist(),
                "c": rng.uniform(0, 1, n).tolist(),
            }
        )

    def test_mask_identical_to_static_and_switch_recorded(self, reorder_env):
        batch = self._batch()
        # written worst-first: keep 90%, 50%, 5% — the reorder must flip
        cond = (col("a") >= 10) & (col("b") < 50) & (col("c") < 0.05)
        static = np.asarray(cond.eval(batch).data, dtype=bool)
        before = _counter("adaptive.reorder")
        got = adaptive.conjunct_mask(cond, batch)
        assert got is not None
        assert np.array_equal(got, static)
        assert _counter("adaptive.reorder") == before + 1

    def test_null_kleene_mask_identical(self, reorder_env):
        rng = np.random.default_rng(5)
        n = 6000
        vals_a = [
            None if rng.uniform() < 0.2 else int(rng.integers(0, 40))
            for _ in range(n)
        ]
        vals_b = [
            None if rng.uniform() < 0.3 else int(rng.integers(0, 40))
            for _ in range(n)
        ]
        batch = ColumnBatch(
            {
                "a": _nullable_int(vals_a),
                "b": _nullable_int(vals_b),
                "c": Column(
                    rng.integers(0, 40, n).astype(np.int64), "int64", None
                ),
            }
        )
        cond = (col("a") > 5) & (col("b") < 30) & (col("c") != 7)
        static = np.asarray(cond.eval(batch).data, dtype=bool)
        got = adaptive.conjunct_mask(cond, batch)
        assert got is not None
        assert np.array_equal(got, static)

    def test_static_cases_return_none(self, reorder_env):
        batch = self._batch(n=6000)
        # single conjunct: nothing to reorder
        assert adaptive.conjunct_mask(col("a") > 3, batch) is None
        # OR at the top: not a conjunction
        assert adaptive.conjunct_mask(
            (col("a") > 3) | (col("b") > 3), batch
        ) is None
        # all-warmup batch: too small to learn anything worth applying
        small = self._batch(n=1500)
        assert adaptive.conjunct_mask(
            (col("a") > 3) & (col("b") > 3), small
        ) is None

    def test_e2e_filter_query_bit_identical(
        self, tmp_session, tmp_path, reorder_env, monkeypatch
    ):
        rng = np.random.default_rng(9)
        n = 9000
        cio.write_parquet(
            ColumnBatch.from_pydict(
                {
                    "a": rng.integers(0, 100, n).tolist(),
                    "b": rng.integers(0, 100, n).tolist(),
                    "c": rng.integers(0, 100, n).tolist(),
                }
            ),
            str(tmp_path / "t" / "p.parquet"),
        )
        # col-vs-col: no arrow pushdown, the Filter node sees all 9000 rows
        q = lambda: (
            tmp_session.read.parquet(str(tmp_path / "t"))
            .filter(
                (col("a") != col("c"))
                & (col("a") > col("b"))
                & (col("b") >= col("c"))
            )
            .select("a", "b", "c")
        )
        before = _counter("adaptive.reorder")
        on = q().to_pydict()
        assert _counter("adaptive.reorder") > before  # the site engaged
        monkeypatch.setenv("HYPERSPACE_ADAPTIVE", "0")
        off = q().to_pydict()
        assert _bits(on) == _bits(off)

    def test_switch_renders_in_explain_analyze_summary(
        self, tmp_session, tmp_path, reorder_env
    ):
        rng = np.random.default_rng(13)
        n = 9000
        cio.write_parquet(
            ColumnBatch.from_pydict(
                {
                    "a": rng.integers(0, 100, n).tolist(),
                    "b": rng.integers(0, 100, n).tolist(),
                    "c": rng.integers(0, 100, n).tolist(),
                }
            ),
            str(tmp_path / "t" / "p.parquet"),
        )
        df = (
            tmp_session.read.parquet(str(tmp_path / "t"))
            .filter(
                (col("a") != col("c"))
                & (col("a") > col("b"))
                & (col("b") >= col("c"))
            )
            .select("a", "b")
        )
        with plan_stats.collect_scope() as colr:
            df.to_pydict()
        assert colr.switches, "no switch event recorded"
        sw = colr.switches[0]
        assert sw["site"] == "reorder"
        rendered = plan_stats.summary_string(colr)
        assert "[adapted:" in rendered and "@chunk" in rendered

    def test_verify_mode_clean(
        self, tmp_session, tmp_path, reorder_env, monkeypatch
    ):
        monkeypatch.setenv("HYPERSPACE_ADAPTIVE", "verify")
        rng = np.random.default_rng(17)
        n = 9000
        cio.write_parquet(
            ColumnBatch.from_pydict(
                {
                    "a": rng.integers(0, 100, n).tolist(),
                    "b": rng.integers(0, 100, n).tolist(),
                }
            ),
            str(tmp_path / "t" / "p.parquet"),
        )
        before = _counter("adaptive.verified")
        out = (
            tmp_session.read.parquet(str(tmp_path / "t"))
            .filter((col("a") > col("b")) & (col("a") != 3))
            .select("a", "b")
            .to_pydict()
        )
        assert out["a"]  # non-empty: verify compared real rows
        assert _counter("adaptive.verified") == before + 1

    def test_verify_catches_planted_divergence(
        self, tmp_session, tmp_path, reorder_env, monkeypatch
    ):
        """Corrupt the adaptive mask path only — the verify baseline runs
        under force_mode("0") and never calls it, so the comparison must
        blow up (the HYPERSPACE_PRUNE=verify discipline)."""
        real = adaptive._conjunct_data_mask

        def corrupted(conj, batch):
            m = real(conj, batch)
            if m.size:
                m = m.copy()
                m[0] = not m[0]
            return m

        monkeypatch.setattr(adaptive, "_conjunct_data_mask", corrupted)
        monkeypatch.setenv("HYPERSPACE_ADAPTIVE", "verify")
        rng = np.random.default_rng(19)
        n = 9000
        cio.write_parquet(
            ColumnBatch.from_pydict(
                {
                    "a": rng.integers(0, 100, n).tolist(),
                    "b": rng.integers(0, 100, n).tolist(),
                }
            ),
            str(tmp_path / "t" / "p.parquet"),
        )
        with pytest.raises(HyperspaceError, match="verify mismatch"):
            (
                tmp_session.read.parquet(str(tmp_path / "t"))
                .filter((col("a") > col("b")) & (col("a") != col("b")))
                .select("a", "b")
                .collect()
            )


# ---------------------------------------------------------------------------
# site 1: per-bucket-pair join re-planning
# ---------------------------------------------------------------------------


def _mem_plan(grant=1 << 20, estimates=None, strategies=None):
    estimates = estimates or {}
    strategies = strategies or {b: "banded" for b in estimates}
    split_by = {
        b: (0 if s == "broadcast" else 4096) for b, s in strategies.items()
    }
    return join_memory.JoinMemoryPlan(
        strategies, split_by, grant, 4096, None,
        estimates=estimates, index_name="jx",
    )


class TestJoinReplan:
    def test_observe_actual_is_non_destructive(self):
        plan = _mem_plan(estimates={0: (1000, 16000.0), 1: (2000, 32000.0)})
        plan.observe_actual(0, 5000, 80000)
        assert plan.estimates == {0: (1000, 16000.0), 1: (2000, 32000.0)}
        assert plan.observed[0] == (5000, 80000)
        plan.observe_actual(0, 9, 9)  # one observation per bucket, ever
        assert plan.observed[0] == (5000, 80000)
        # unknown bucket: ignored, never invents an estimate
        plan.observe_actual(7, 1, 1)
        assert 7 not in plan.observed

    def test_split_rows_static_when_off(self, monkeypatch):
        monkeypatch.delenv("HYPERSPACE_ADAPTIVE", raising=False)
        plan = _mem_plan(estimates={0: (1000, 16000.0), 1: (2000, 32000.0)})
        plan.observe_actual(0, 500_000, 8_000_000)
        assert plan.split_rows(1) == 4096  # planned threshold untouched

    def test_flip_banded_to_split_from_correction(self, monkeypatch):
        """Warmup pair observes 50x the estimated bytes; the NEXT pair's
        threshold re-derives from the geometric-mean correction and the
        flip is recorded exactly once."""
        monkeypatch.setenv("HYPERSPACE_ADAPTIVE", "1")
        monkeypatch.setenv("HYPERSPACE_ADAPTIVE_WARMUP_CHUNKS", "1")
        plan = _mem_plan(
            grant=1 << 20,
            estimates={0: (1000, 16000.0), 1: (2000, 32000.0)},
        )
        plan.observe_actual(0, 50_000, 800_000)
        before = _counter("adaptive.replan")
        got = plan.split_rows(1)
        assert got == join_memory.derive_split_rows(1 << 20, 16.0)
        assert 0 < got < 100_000  # corrected act_rows ≈ 100k: split engages
        assert _counter("adaptive.replan") == before + 1
        assert plan.split_rows(1) == got  # idempotent: one event per bucket
        assert _counter("adaptive.replan") == before + 1

    def test_observed_bucket_uses_its_own_actuals(self, monkeypatch):
        monkeypatch.setenv("HYPERSPACE_ADAPTIVE", "1")
        monkeypatch.setenv("HYPERSPACE_ADAPTIVE_WARMUP_CHUNKS", "1")
        plan = _mem_plan(
            grant=1 << 20,
            estimates={0: (1000, 16000.0), 1: (100, 1600.0)},
        )
        plan.observe_actual(0, 1000, 16000)   # honest pair: no correction
        plan.observe_actual(1, 60_000, 960_000)  # this pair blew up 600x
        got = plan.split_rows(1)
        assert got == join_memory.derive_split_rows(1 << 20, 16.0)
        assert got < 60_000  # its own decoded truth drove the re-derive

    def test_broadcast_and_unsplittable_never_flip(self, monkeypatch):
        monkeypatch.setenv("HYPERSPACE_ADAPTIVE", "1")
        monkeypatch.setenv("HYPERSPACE_ADAPTIVE_WARMUP_CHUNKS", "1")
        plan = _mem_plan(
            estimates={0: (10, 160.0), 1: (1000, 16000.0)},
            strategies={0: "broadcast", 1: "banded"},
        )
        plan.observe_actual(0, 90_000, 1_440_000)
        assert plan.split_rows(0) == 0  # broadcast pairs never split
        before = _counter("adaptive.replan")
        plan.split_rows(1, splittable=False)  # agg state can't fold: no event
        assert _counter("adaptive.replan") == before

    def test_e2e_join_bit_identical_under_planted_misestimate(
        self, tmp_session, tmp_path, monkeypatch
    ):
        """Footer byte stats tampered 64x low: the static plan under-sizes
        its waves; adaptive corrects mid-join and flips to split — results
        stay bit-identical and the ledger never parks MORE than static."""
        rng = np.random.default_rng(7)
        n = 30_000
        cio.write_parquet(
            ColumnBatch.from_pydict(
                {
                    "k": rng.integers(0, 600, n).tolist(),
                    "p": rng.uniform(0, 100, n).tolist(),
                }
            ),
            str(tmp_path / "l" / "l.parquet"),
        )
        cio.write_parquet(
            ColumnBatch.from_pydict(
                {
                    "rk": list(range(0, 500)),
                    "w": rng.uniform(size=500).tolist(),
                }
            ),
            str(tmp_path / "r" / "r.parquet"),
        )
        tmp_session.set_conf(C.INDEX_NUM_BUCKETS, 4)
        hs = Hyperspace(tmp_session)
        hs.create_index(
            tmp_session.read.parquet(str(tmp_path / "l")),
            CoveringIndexConfig("jl", ["k"], ["p"]),
        )
        hs.create_index(
            tmp_session.read.parquet(str(tmp_path / "r")),
            CoveringIndexConfig("jr", ["rk"], ["w"]),
        )
        tmp_session.enable_hyperspace()
        tmp_session.set_conf(C.EXEC_TPU_ENABLED, True)

        real = join_memory._bucket_estimates

        def tampered(side, b):
            rows, nbytes = real(side, b)
            return rows, nbytes / 64.0

        monkeypatch.setattr(join_memory, "_bucket_estimates", tampered)
        monkeypatch.setenv("HYPERSPACE_JOIN_BROADCAST_ROWS", "10")
        monkeypatch.setenv("HYPERSPACE_DEVICE_BUDGET_MB", "0.25")
        monkeypatch.setenv("HYPERSPACE_PARK_WAIT_MS", "1")
        monkeypatch.setenv("HYPERSPACE_ADAPTIVE_WARMUP_CHUNKS", "1")
        serve_budget.reset_device_budget()

        def q():
            l = tmp_session.read.parquet(str(tmp_path / "l")).select("k", "p")
            r = tmp_session.read.parquet(str(tmp_path / "r")).select(
                "rk", "w"
            )
            return (
                l.join(r, col("k") == col("rk"))
                .group_by("k")
                .agg(
                    Count(lit(1)).alias("n"),
                    Min(col("p")).alias("lo"),
                    Max(col("p")).alias("hi"),
                )
            )

        monkeypatch.setenv("HYPERSPACE_ADAPTIVE", "0")
        parks0 = _counter("join.spill.parks")
        off = q().to_pydict()
        parks_static = _counter("join.spill.parks") - parks0

        monkeypatch.setenv("HYPERSPACE_ADAPTIVE", "1")
        replans0 = _counter("adaptive.replan")
        parks0 = _counter("join.spill.parks")
        try:
            on = q().to_pydict()
        finally:
            tmp_session.set_conf(C.EXEC_TPU_ENABLED, False)
        parks_adaptive = _counter("join.spill.parks") - parks0

        assert _bits(on) == _bits(off)
        assert _counter("adaptive.replan") > replans0  # the flip happened
        assert parks_adaptive <= parks_static


# ---------------------------------------------------------------------------
# site 3: scan abort-and-replan
# ---------------------------------------------------------------------------

N = 12_000
N_FILES = 4
RGS = 512


def _events(i, n_per, base):
    rng = np.random.default_rng(100 + i)
    return {
        "ev_k": list(range(base, base + n_per)),
        "ev_id": [10_000_000 + base + j for j in range(n_per)],
        "ev_cat": [f"c{(base + j) % 3}" for j in range(n_per)],
        "ev_v": rng.uniform(0, 1, n_per).tolist(),
    }


@pytest.fixture()
def scan_env(tmp_session, tmp_path, monkeypatch):
    """Covering index with sketch sidecars, several row groups per bucket,
    streaming execution in small chunks — the abort monitor's habitat."""
    monkeypatch.setenv("HYPERSPACE_SKETCHES", "1")
    monkeypatch.setattr(covering, "INDEX_ROW_GROUP_SIZE", RGS)
    src = str(tmp_path / "events")
    per = N // N_FILES
    for i in range(N_FILES):
        cio.write_parquet(
            ColumnBatch.from_pydict(_events(i, per, i * per)),
            os.path.join(src, f"part-{i:02d}.parquet"),
        )
    tmp_session.set_conf(C.INDEX_NUM_BUCKETS, 2)
    hs = Hyperspace(tmp_session)
    hs.create_index(
        tmp_session.read.parquet(src),
        CoveringIndexConfig("ev_idx", ["ev_k"], ["ev_id", "ev_cat", "ev_v"]),
    )
    tmp_session.enable_hyperspace()
    tmp_session.set_conf(C.EXEC_TPU_ENABLED, True)
    monkeypatch.setenv("HYPERSPACE_STREAM_CHUNK_MB", "0.02")
    monkeypatch.setenv("HYPERSPACE_ADAPTIVE_WARMUP_CHUNKS", "1")
    yield tmp_session, hs, src
    tmp_session.set_conf(C.EXEC_TPU_ENABLED, False)
    tmp_session.disable_hyperspace()


def _sidecars(session, name="ev_idx"):
    root = os.path.join(session.warehouse_dir, "indexes", name)
    return sorted(
        glob.glob(os.path.join(root, "**", "_sketch.*.json"), recursive=True)
    )


def _agg_q(session, src):
    return (
        session.read.parquet(src)
        .filter(col("ev_cat") == "c1")
        .group_by("ev_cat")
        .agg(
            Count(lit(1)).alias("n"),
            Min(col("ev_v")).alias("lo"),
            Max(col("ev_v")).alias("hi"),
        )
    )


def _raw_bits(session, src):
    session.disable_hyperspace()
    try:
        return _bits(_agg_q(session, src).to_pydict())
    finally:
        session.enable_hyperspace()


class TestScanAbortReplan:
    def test_monitor_pass_through_outside_replan_scope(self, monkeypatch):
        monkeypatch.setenv("HYPERSPACE_ADAPTIVE", "1")
        chunks = iter(())
        # active, but no execute_collect scope installed: disarmed
        assert adaptive.monitor_scan_chunks(
            chunks, _FakeScan(), ({}, [])
        ) is chunks

    def test_abort_replans_to_raw_bit_identical(self, scan_env, monkeypatch):
        """Honest prediction + sub-1 abort factor: any pruned streamed scan
        'underdelivers', aborts after the warmup chunk, the index is
        vetoed, and the replanned (raw) run matches the raw scan bit for
        bit."""
        session, hs, src = scan_env
        raw = _raw_bits(session, src)
        monkeypatch.setenv("HYPERSPACE_ADAPTIVE", "1")
        monkeypatch.setenv("HYPERSPACE_ADAPTIVE_ABORT_FACTOR", "0.1")
        aborts0 = _counter("adaptive.abort")
        replans0 = _counter("adaptive.scan_replans")
        got = _bits(_agg_q(session, src).to_pydict())
        assert _counter("adaptive.abort") == aborts0 + 1
        assert _counter("adaptive.scan_replans") == replans0 + 1
        assert got == raw
        # outside the replan scope again: the veto does not leak
        assert adaptive.vetoed_indexes() == frozenset()

    def test_tampered_ndv_triggers_abort_at_default_factor(
        self, scan_env, monkeypatch
    ):
        """Planted mis-estimate: sidecar NDV for ev_cat tampered 1e9 so the
        sketch stage promises to keep almost nothing, while the honest
        blooms keep every group — a >4x underdelivery at the DEFAULT
        abort factor."""
        session, hs, src = scan_env
        raw = _raw_bits(session, src)
        sides = _sidecars(session)
        assert sides, "fixture must have sketch sidecars"
        for side in sides:
            rawd = json.load(open(side))
            if "ev_cat" in rawd.get("ndv", {}):
                rawd["ndv"]["ev_cat"] = 10**9
                json.dump(rawd, open(side, "w"))
        sketch_store._SIDECAR_CACHE.clear()
        monkeypatch.setenv("HYPERSPACE_ADAPTIVE", "1")
        monkeypatch.delenv("HYPERSPACE_ADAPTIVE_ABORT_FACTOR", raising=False)
        aborts0 = _counter("adaptive.abort")
        got = _bits(_agg_q(session, src).to_pydict())
        assert _counter("adaptive.abort") == aborts0 + 1
        assert got == raw

    def test_abort_disarmed_when_off(self, scan_env, monkeypatch):
        session, hs, src = scan_env
        monkeypatch.setenv("HYPERSPACE_ADAPTIVE", "0")
        monkeypatch.setenv("HYPERSPACE_ADAPTIVE_ABORT_FACTOR", "0.1")
        aborts0 = _counter("adaptive.abort")
        _agg_q(session, src).to_pydict()
        assert _counter("adaptive.abort") == aborts0

    def test_hs_top_renders_adaptive_column(self):
        import importlib.util

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        spec = importlib.util.spec_from_file_location(
            "hs_top", os.path.join(repo, "tools", "hs_top.py")
        )
        hs_top = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(hs_top)
        snap = {
            "ts": 0,
            "queries": {
                "recent": [
                    {
                        "query_id": 1,
                        "label": "adapted-q",
                        "counters": {
                            "adaptive.replan": 2,
                            "adaptive.abort": 1,
                            "adaptive.verified": 9,  # not a site: excluded
                        },
                    },
                    {"query_id": 2, "label": "static-q", "counters": {}},
                ]
            },
        }
        out = hs_top.render(snap)
        assert "adapt" in out
        row1 = next(l for l in out.splitlines() if "adapted-q" in l)
        row2 = next(l for l in out.splitlines() if "static-q" in l)
        assert " 3 " in row1
        assert " - " in row2

    def test_verify_mode_clean_across_abort(self, scan_env, monkeypatch):
        """verify adapts (abort + replan) AND re-runs the final plan
        statically — clean, because the switches change scheduling, never
        values."""
        session, hs, src = scan_env
        monkeypatch.setenv("HYPERSPACE_ADAPTIVE", "verify")
        monkeypatch.setenv("HYPERSPACE_ADAPTIVE_ABORT_FACTOR", "0.1")
        verified0 = _counter("adaptive.verified")
        aborts0 = _counter("adaptive.abort")
        out = _agg_q(session, src).to_pydict()
        assert out["n"] == [N // 3]
        assert _counter("adaptive.abort") == aborts0 + 1
        assert _counter("adaptive.verified") == verified0 + 1
