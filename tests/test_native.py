"""Native kernel tests: build, bit-parity with numpy, partition correctness."""

import numpy as np
import pytest

from hyperspace_tpu import native
from hyperspace_tpu.ops import hashing as H


pytestmark = pytest.mark.skipif(
    not native.available(), reason="native toolchain unavailable"
)


class TestNativeParity:
    def test_hash_words_parity_int64(self):
        keys = np.array([0, 1, -1, 2**40, -(2**40), 2**62, -(2**63)], dtype=np.int64)
        words = H._words_np(keys)
        nat = native.hash32_words(words)
        # numpy reference path (force by computing manually)
        h = np.full(len(keys), 42, dtype=np.uint32)
        with np.errstate(over="ignore"):
            for w in words:
                h = H._mix_round(h, w, np)
            h = H._fmix32(h, np)
        assert np.array_equal(nat, h)

    def test_hash32_np_uses_native_consistently(self):
        # large input (native path) must equal small input (numpy path) per value
        big = np.arange(5000, dtype=np.int64)
        h_big = H.hash32_np([big])
        h_small = np.concatenate(
            [H.hash32_np([big[i: i + 10]]) for i in range(0, 5000, 10)]
        )
        assert np.array_equal(h_big, h_small)

    def test_single_column_fast_variants(self):
        k64 = np.arange(-500, 500, dtype=np.int64) * (2**33 + 7)
        k32 = np.arange(-500, 500, dtype=np.int32)
        assert np.array_equal(native.hash32(k64), H.hash32_np([k64]))
        assert np.array_equal(native.hash32(k32), H.hash32_np([k32]))

    def test_jnp_agreement_via_native(self):
        import jax.numpy as jnp

        x = np.arange(2000, dtype=np.int32)
        assert np.array_equal(
            H.hash32_np([x]), np.asarray(H.hash32_jnp([jnp.asarray(x)]))
        )


class TestNativePartition:
    def test_partition_matches_argsort(self):
        rng = np.random.default_rng(0)
        hashes = rng.integers(0, 2**32, 10000, dtype=np.uint32)
        ids, order, offsets = native.bucket_partition(hashes, 16)
        assert np.array_equal(ids, (hashes % np.uint32(16)).astype(np.int32))
        # stable grouping identical to stable argsort
        ref_order = np.argsort(ids, kind="stable")
        assert np.array_equal(order, ref_order)
        assert offsets[0] == 0 and offsets[-1] == len(hashes)
        for b in range(16):
            assert (ids[order[offsets[b]: offsets[b + 1]]] == b).all()

    def test_partition_batch_native_path(self):
        from hyperspace_tpu.columnar.table import ColumnBatch
        from hyperspace_tpu.ops.bucketize import bucket_ids_for_batch, partition_batch

        batch = ColumnBatch.from_pydict({"k": list(range(5000))})
        parts = partition_batch(batch, ["k"], 8)
        ids = bucket_ids_for_batch(batch, ["k"], 8)
        total = 0
        for b, rows in parts:
            assert (ids[rows] == b).all()
            assert np.array_equal(rows, np.sort(rows))  # stable
            total += len(rows)
        assert total == 5000


class TestNativeJoin:
    def test_join_matches_numpy_pair_order(self):
        import numpy as np

        from hyperspace_tpu import native
        from hyperspace_tpu.ops.join import expand_runs

        if not native.available():
            import pytest

            pytest.skip("no native toolchain")
        rng = np.random.default_rng(3)
        l = rng.integers(0, 500, 20_000).astype(np.int64)
        r = rng.integers(0, 500, 3_000).astype(np.int64)
        l[3], r[11] = -1, -2  # NULL sentinels never match
        li, ri = native.join_i64(l, r)
        order = np.argsort(r, kind="stable")
        sr = r[order]
        st = np.searchsorted(sr, l, "left")
        en = np.searchsorted(sr, l, "right")
        cn = en - st
        li2 = np.repeat(np.arange(len(l)), cn)
        ri2 = order[expand_runs(st, cn)]
        np.testing.assert_array_equal(li, li2)
        np.testing.assert_array_equal(ri, ri2)

    def test_join_empty_sides(self):
        import numpy as np

        from hyperspace_tpu import native

        if not native.available():
            import pytest

            pytest.skip("no native toolchain")
        li, ri = native.join_i64(np.array([1, 2], np.int64), np.empty(0, np.int64))
        assert len(li) == 0 and len(ri) == 0
        li, ri = native.join_i64(np.empty(0, np.int64), np.array([1], np.int64))
        assert len(li) == 0
