"""Differential testing: randomized query plans must return identical rows
with indexes enabled vs disabled.

This is the broad-spectrum net over every rewrite (filter/join/zorder/
data-skipping/aggregate, hybrid scan) — the property the whole framework
promises: `enable_hyperspace()` never changes results.
"""

import numpy as np
import pytest

from hyperspace_tpu import (
    CoveringIndexConfig,
    DataSkippingIndexConfig,
    Hyperspace,
    MinMaxSketch,
    ZOrderCoveringIndexConfig,
)
from hyperspace_tpu import constants as C
from hyperspace_tpu.columnar import io as cio
from hyperspace_tpu.columnar.table import ColumnBatch
from hyperspace_tpu.plan import col, lit, Avg, Count, Max, Min, Sum
from hyperspace_tpu.plan.expr import Not


def canon(d: dict) -> list:
    keys = sorted(d.keys())
    rows = [
        tuple(round(v, 7) if isinstance(v, float) else v for v in row)
        for row in zip(*[d[k] for k in keys])
    ]
    return sorted(rows, key=repr)


def rows_close(got: list, expected: list, rel: float = 1e-6) -> bool:
    """Row-wise comparison at the engine's float contract (1e-6 relative,
    like bench.py): device tiers accumulate in f32, and the index scan's row
    order differs from raw, so last-bit sums legitimately differ."""
    if len(got) != len(expected):
        return False
    for g, e in zip(got, expected):
        if len(g) != len(e):
            return False
        for a, b in zip(g, e):
            if isinstance(a, float) and isinstance(b, float):
                if abs(a - b) > rel * max(1.0, abs(b)):
                    return False
            elif a != b:
                return False
    return True


@pytest.fixture(scope="module")
def world(tmp_path_factory):
    root = tmp_path_factory.mktemp("diff")
    rng = np.random.default_rng(99)
    n = 5000
    # facts spread over 4 files; dims in 1
    for i in range(4):
        sl = n // 4
        cio.write_parquet(
            ColumnBatch.from_pydict(
                {
                    "k": rng.integers(0, 200, sl).tolist(),
                    "d": rng.integers(i * 600, (i + 1) * 600, sl).tolist(),
                    "x": rng.uniform(0, 100, sl).tolist(),
                    "cat": rng.choice(["red", "green", "blue"], sl).tolist(),
                }
            ),
            str(root / "fact" / f"f{i}.parquet"),
        )
    cio.write_parquet(
        ColumnBatch.from_pydict(
            {"rk": list(range(200)), "w": rng.uniform(size=200).tolist()}
        ),
        str(root / "dim" / "d.parquet"),
    )
    from hyperspace_tpu.session import HyperspaceSession

    session = HyperspaceSession(warehouse_dir=str(root))
    session.set_conf(C.INDEX_LINEAGE_ENABLED, True)
    hs = Hyperspace(session)
    fact = session.read.parquet(str(root / "fact"))
    dim = session.read.parquet(str(root / "dim"))
    hs.create_index(fact, CoveringIndexConfig("ci_k", ["k"], ["x", "d"]))
    hs.create_index(fact, ZOrderCoveringIndexConfig("z_d", ["d"], ["x", "k"]))
    hs.create_index(fact, DataSkippingIndexConfig("ds_d", [MinMaxSketch("d")]))
    hs.create_index(dim, CoveringIndexConfig("ci_rk", ["rk"], ["w"]))
    return session, str(root)


def random_predicate(rng):
    choices = [
        lambda: col("k") == int(rng.integers(0, 200)),
        lambda: col("k") > int(rng.integers(0, 200)),
        lambda: col("d") < int(rng.integers(0, 2400)),
        lambda: (col("d") >= int(rng.integers(0, 1200)))
        & (col("d") < int(rng.integers(1200, 2400))),
        lambda: col("x") > float(rng.uniform(0, 100)),
        lambda: col("cat") == str(rng.choice(["red", "green", "blue"])),
        lambda: col("k").isin([int(v) for v in rng.integers(0, 200, 5)]),
        lambda: Not(col("k") == int(rng.integers(0, 200))),
        lambda: (col("k") > int(rng.integers(0, 100)))
        | (col("d") < int(rng.integers(0, 600))),
    ]
    return choices[rng.integers(0, len(choices))]()


def random_query(session, root, rng):
    fact = session.read.parquet(root + "/fact")
    df = fact
    for _ in range(int(rng.integers(0, 3))):
        df = df.filter(random_predicate(rng))
    shape = rng.integers(0, 7)
    if shape == 0:
        return df.select("k", "d", "x")
    if shape == 1:
        dim = session.read.parquet(root + "/dim")
        return df.select("k", "x").join(
            dim.select("rk", "w"), col("k") == col("rk")
        )
    if shape == 2:
        return df.select("k", "x").group_by("k").agg(
            Sum(col("x")).alias("s"), Count(lit(1)).alias("n")
        )
    if shape == 3:
        # ORDER BY ... LIMIT over a grouped aggregate (top-k path)
        return (
            df.select("k", "x")
            .group_by("k")
            .agg(Sum(col("x")).alias("s"))
            .sort("s", ascending=bool(rng.integers(0, 2)))
            .limit(int(rng.integers(1, 30)))
        )
    if shape == 4:
        # multi-key sort incl. string column
        return df.select("k", "cat", "x").sort("cat", "x").limit(50)
    if shape == 5:
        # union of two filtered halves
        lo = df.filter(col("d") < 1200).select("k", "x")
        hi = df.filter(col("d") >= 1200).select("k", "x")
        return lo.union(hi).group_by("k").agg(Count(lit(1)).alias("n"))
    dim = session.read.parquet(root + "/dim")
    return (
        df.select("k", "x")
        .join(dim.select("rk", "w"), col("k") == col("rk"))
        .group_by("k")
        .agg(Sum(col("x")).alias("s"), Min(col("w")).alias("mw"))
    )


class TestDifferential:
    @pytest.mark.parametrize("seed", range(40))
    def test_indexed_matches_raw(self, world, seed):
        session, root = world
        rng = np.random.default_rng(seed)
        q = random_query(session, root, rng)
        session.disable_hyperspace()
        expected = canon(q.to_pydict())
        session.enable_hyperspace()
        try:
            got = canon(q.to_pydict())
        finally:
            session.disable_hyperspace()
        assert got == expected, f"divergence at seed {seed}"

    @pytest.mark.parametrize("seed", range(100, 140))
    def test_indexed_matches_raw_device_tiers(self, world, seed):
        """Same property with the device / mesh execution tiers on (fused
        XLA kernels, device+host fused join-aggregate, mesh fragments).
        Floats compare at the engine's 1e-6 relative contract."""
        session, root = world
        rng = np.random.default_rng(seed)
        session.set_conf(C.EXEC_TPU_ENABLED, True)
        session.set_conf(C.EXEC_MESH_DEVICES, 8 if seed % 2 else 0)
        # half the mesh seeds (odd seeds with seed % 4 == 1) run 2-slice
        session.set_conf(C.EXEC_MESH_SLICES, 2 if seed % 4 == 1 else 1)
        q = random_query(session, root, rng)
        session.disable_hyperspace()
        expected = canon(q.to_pydict())
        session.enable_hyperspace()
        try:
            got = canon(q.to_pydict())
        finally:
            session.disable_hyperspace()
            session.set_conf(C.EXEC_TPU_ENABLED, False)
            session.set_conf(C.EXEC_MESH_DEVICES, 0)
            session.set_conf(C.EXEC_MESH_SLICES, 1)
        assert rows_close(got, expected), f"device-tier divergence at seed {seed}"

    @pytest.mark.parametrize("seed", range(40, 60))
    def test_indexed_matches_raw_hybrid(self, world, seed, tmp_path):
        """Same property with hybrid scan enabled and a mutated source."""
        session, root = world
        import os

        appended = root + "/fact/appended.parquet"
        if not os.path.exists(appended):
            cio.write_parquet(
                ColumnBatch.from_pydict(
                    {"k": [5, 6], "d": [100, 200], "x": [1.5, 2.5], "cat": ["red", "blue"]}
                ),
                appended,
            )
        session.set_conf(C.HYBRID_SCAN_ENABLED, True)
        rng = np.random.default_rng(seed)
        q = random_query(session, root, rng)
        session.disable_hyperspace()
        expected = canon(q.to_pydict())
        session.enable_hyperspace()
        try:
            got = canon(q.to_pydict())
        finally:
            session.disable_hyperspace()
            session.set_conf(C.HYBRID_SCAN_ENABLED, False)
        assert got == expected, f"hybrid divergence at seed {seed}"


class TestDifferentialNestedAndSnapshot:
    """The differential property extended to round-2 surfaces: nested-column
    sources and snapshot (iceberg-style) tables."""

    @pytest.fixture(scope="class")
    def nested_world(self, tmp_path_factory):
        import pyarrow as pa
        import pyarrow.parquet as pq

        from hyperspace_tpu.session import HyperspaceSession

        root = tmp_path_factory.mktemp("diffn")
        rng = np.random.default_rng(5)
        n = 4000
        t = pa.table(
            {
                "id": pa.array(np.arange(n)),
                "m": pa.StructArray.from_arrays(
                    [
                        pa.array(rng.integers(0, 50, n)),
                        pa.array(rng.uniform(0, 100, n)),
                    ],
                    names=["k", "x"],
                ),
            }
        )
        (root / "src").mkdir()
        pq.write_table(t, str(root / "src" / "p.parquet"))
        session = HyperspaceSession(warehouse_dir=str(root))
        hs = Hyperspace(session)
        df = session.read.parquet(str(root / "src"))
        hs.create_index(df, CoveringIndexConfig("nci", ["m.k"], ["m.x", "id"]))
        return session, str(root / "src")

    @pytest.mark.parametrize("seed", range(200, 215))
    def test_nested_indexed_matches_raw(self, nested_world, seed):
        session, src = nested_world
        rng = np.random.default_rng(seed)
        lo = int(rng.integers(0, 40))

        def q():
            df = session.read.parquet(src)
            df = df.filter(
                (col("m.k") >= lo) & (col("m.k") < lo + int(rng.integers(2, 10)))
            )
            if rng.integers(0, 2):
                return df.select("id", "m.k", "m.x")
            return df.group_by("m.k").agg(
                Sum(col("m.x")).alias("s"), Count(lit(1)).alias("n")
            )

        rng = np.random.default_rng(seed)
        session.disable_hyperspace()
        expected = canon(q().to_pydict())
        rng = np.random.default_rng(seed)
        session.enable_hyperspace()
        try:
            got = canon(q().to_pydict())
        finally:
            session.disable_hyperspace()
        assert rows_close(got, expected), f"nested divergence at seed {seed}"

    @pytest.mark.parametrize("seed", range(215, 225))
    def test_iceberg_snapshot_indexed_matches_raw(self, tmp_path, seed):
        from hyperspace_tpu import IcebergStyleTable
        from hyperspace_tpu.session import HyperspaceSession

        rng = np.random.default_rng(seed)
        session = HyperspaceSession(warehouse_dir=str(tmp_path))
        hs = Hyperspace(session)
        t = IcebergStyleTable(str(tmp_path / "tbl"))
        n = 800
        t.commit(
            ColumnBatch.from_pydict(
                {
                    "k": rng.integers(0, 50, n).tolist(),
                    "x": rng.uniform(size=n).tolist(),
                }
            )
        )
        hs.create_index(t.scan(session), CoveringIndexConfig("ici", ["k"], ["x"]))
        s0 = t.current_snapshot_id()
        t.commit(
            ColumnBatch.from_pydict(
                {"k": [1, 2], "x": [9.0, 9.5]}
            )
        )
        hs.refresh_index("ici", "incremental")
        kv = int(rng.integers(0, 50))

        def q(snapshot_id=None):
            return (
                t.scan(session, snapshot_id=snapshot_id)
                .filter(col("k") == kv)
                .select("k", "x")
            )

        for sid in (None, s0):
            session.disable_hyperspace()
            expected = canon(q(sid).to_pydict())
            session.enable_hyperspace()
            try:
                got = canon(q(sid).to_pydict())
            finally:
                session.disable_hyperspace()
            assert got == expected, f"snapshot divergence seed {seed} sid {sid}"
