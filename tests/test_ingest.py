"""Continuous ingestion: log-structured appends, snapshot-pinned reads,
refcount-gated compaction/vacuum, and crash recovery of the new
``ingest.append`` / ``ingest.compact`` fault points.

The refcount edge cases the subsystem exists for are pinned explicitly:
a query pinned to version K survives K being compacted away and vacuumed;
a cancelled query releases its pin; ``recover()`` never deletes a pinned
version; a protected (in-flight) staged build survives ``clear_staging``.
"""

import os
import time

import numpy as np
import pytest

from hyperspace_tpu import CoveringIndexConfig, Hyperspace, HyperspaceSession
from hyperspace_tpu import constants as C
from hyperspace_tpu import ingest
from hyperspace_tpu.columnar import io as cio
from hyperspace_tpu.columnar.table import ColumnBatch
from hyperspace_tpu.exceptions import HyperspaceError
from hyperspace_tpu.index_manager import IndexCollectionManager
from hyperspace_tpu.meta.data_manager import IndexDataManager
from hyperspace_tpu.meta.log_manager import IndexLogManager, STABLE_STATES
from hyperspace_tpu.plan import Count, Max, Min, Sum, col, lit
from hyperspace_tpu.utils import faults


def _batch(seed: int, n: int = 1200) -> dict:
    r = np.random.default_rng(seed)
    return {
        "k": r.integers(0, 40, n).tolist(),
        "v": r.integers(0, 1000, n).tolist(),
        "w": r.integers(0, 50, n).tolist(),
    }


def _mk(tmp_path, name="ev", buckets=4, lineage=False):
    ws = str(tmp_path)
    src = os.path.join(ws, "events")
    os.makedirs(src, exist_ok=True)
    cio.write_parquet(
        ColumnBatch.from_pydict(_batch(0)), os.path.join(src, "part0.parquet")
    )
    session = HyperspaceSession(warehouse_dir=ws)
    session.set_conf(C.INDEX_NUM_BUCKETS, buckets)
    if lineage:
        session.set_conf(C.INDEX_LINEAGE_ENABLED, True)
    hs = Hyperspace(session)
    hs.create_index(
        session.read.parquet(src), CoveringIndexConfig(name, ["k"], ["v", "w"])
    )
    session.enable_hyperspace()
    return session, hs, src


def _q(session, src):
    """Order-insensitive reference query (sorted grouped int aggregates)."""
    df = session.read.parquet(src)
    return (
        df.filter(df["k"] < 30)
        .group_by("k")
        .agg(
            Sum(col("v")).alias("sv"),
            Count(lit(1)).alias("n"),
            Min(col("w")).alias("mn"),
            Max(col("w")).alias("mx"),
        )
        .sort("k")
        .collect()
        .to_pydict()
    )


def _raw(session, src):
    session.disable_hyperspace()
    try:
        return _q(session, src)
    finally:
        session.enable_hyperspace()


def _index_path(session, name="ev"):
    return os.path.join(session.warehouse_dir, C.INDEXES_DIR, name)


# ---------------------------------------------------------------------------
# append
# ---------------------------------------------------------------------------


def test_append_indexes_only_the_delta(tmp_path):
    session, hs, src = _mk(tmp_path)
    before = {f.name: f for f in hs.get_index("ev").index_data_files()}
    p = ingest.append_batch(session, "ev", _batch(1))
    assert os.path.exists(p)
    entry = hs.get_index("ev")
    after = {f.name: f for f in entry.index_data_files()}
    # old snapshot files untouched (append-only: same size/mtime)
    for name, fi in before.items():
        assert after[name] == fi
    # delta runs landed in a NEW version dir
    assert set(entry.index_version_dirs()) == {"v__=0", "v__=1"}
    assert len(after) > len(before)
    # query over the grown source matches raw AND uses the index
    with ingest.observe_pins() as obs:
        got = _q(session, src)
    assert got == _raw(session, src)
    assert any(s.index_name == "ev" for s in obs.pins)


def test_append_many_batches_bit_identical(tmp_path):
    session, hs, src = _mk(tmp_path)
    for i in range(1, 5):
        ingest.append_batch(session, "ev", _batch(i))
    assert _q(session, src) == _raw(session, src)
    entry = hs.get_index("ev")
    assert set(entry.index_version_dirs()) == {f"v__={i}" for i in range(5)}


def test_append_no_new_files_is_noop(tmp_path):
    session, hs, src = _mk(tmp_path)
    before = hs.get_index("ev").id
    # same files: NoChangesError is absorbed by the action runner (noop)
    hs.append("ev", session.read.parquet(src))
    assert hs.get_index("ev").id == before


def test_append_rejects_unresolvable_columns(tmp_path):
    session, hs, src = _mk(tmp_path)
    bad = os.path.join(str(tmp_path), "bad")
    os.makedirs(bad)
    cio.write_parquet(
        ColumnBatch.from_pydict({"x": [1, 2, 3]}), os.path.join(bad, "b.parquet")
    )
    with pytest.raises(HyperspaceError):
        hs.append("ev", session.read.parquet(bad))


def test_append_rejects_pending_quick_refresh_delta(tmp_path):
    session, hs, src = _mk(tmp_path, lineage=True)
    cio.write_parquet(
        ColumnBatch.from_pydict(_batch(9)), os.path.join(src, "late.parquet")
    )
    hs.refresh_index("ev", C.REFRESH_MODE_QUICK)
    cio.write_parquet(
        ColumnBatch.from_pydict(_batch(10)), os.path.join(src, "later.parquet")
    )
    with pytest.raises(HyperspaceError, match="quick-refresh"):
        hs.append("ev", session.read.parquet(os.path.join(src, "later.parquet")))


def test_append_lineage_rows_carry_file_ids(tmp_path):
    session, hs, src = _mk(tmp_path, lineage=True)
    p = ingest.append_batch(session, "ev", _batch(3))
    entry = hs.get_index("ev")
    # the appended file got a stable id in the relation content
    appended = [f for f in entry.relation.content.file_infos() if f.name == p]
    assert appended and appended[0].id >= 0
    # and incremental refresh (which needs lineage) still works on top
    os.unlink(p)
    hs.refresh_index("ev", C.REFRESH_MODE_INCREMENTAL)
    assert _q(session, src) == _raw(session, src)


def test_appended_entry_signature_matches_exactly(tmp_path):
    """Queries must exact-match the appended entry (no hybrid-scan ratios):
    the recomputed fingerprint over the extended file set equals what the
    query-time leaf signing produces."""
    from hyperspace_tpu.meta.signatures import get_provider
    from hyperspace_tpu.models.covering import _single_file_scan
    from hyperspace_tpu.rules.collector import _LeafPlan

    session, hs, src = _mk(tmp_path)
    ingest.append_batch(session, "ev", _batch(2))
    entry = hs.get_index("ev")
    sig = entry.signature.signatures[0]
    session.disable_hyperspace()
    leaf = _single_file_scan(session.read.parquet(src))
    session.enable_hyperspace()
    assert get_provider(sig.provider).sign(_LeafPlan(leaf)) == sig.value


# ---------------------------------------------------------------------------
# compaction
# ---------------------------------------------------------------------------


def test_compact_merges_runs_and_preserves_results(tmp_path):
    session, hs, src = _mk(tmp_path)
    for i in range(1, 4):
        ingest.append_batch(session, "ev", _batch(i))
    ref = _raw(session, src)
    entry = hs.get_index("ev")
    assert max(ingest.runs_per_bucket(entry).values()) >= 3
    hs.compact_index("ev", min_runs=2)
    entry2 = hs.get_index("ev")
    # one file per bucket, single fresh version, results identical
    assert max(ingest.runs_per_bucket(entry2).values()) == 1
    assert entry2.index_version_dirs() == ["v__=4"]
    assert _q(session, src) == ref


def test_compact_output_is_sorted_for_rowgroup_skipping(tmp_path):
    """Compaction re-sorts merged runs (PR-4 row-group skipping relies on
    sorted buckets + footer stats)."""
    session, hs, src = _mk(tmp_path)
    for i in range(1, 4):
        ingest.append_batch(session, "ev", _batch(i))
    hs.compact_index("ev", min_runs=2)
    for f in hs.get_index("ev").index_data_files():
        ks = cio.read_parquet([f.name]).column("k").data
        assert (np.diff(ks) >= 0).all(), f.name


def test_compact_below_threshold_is_noop(tmp_path):
    session, hs, src = _mk(tmp_path)
    ingest.append_batch(session, "ev", _batch(1))
    before = hs.get_index("ev").id
    hs.compact_index("ev", min_runs=8)
    assert hs.get_index("ev").id == before


def test_background_compaction_triggers_past_threshold(tmp_path, monkeypatch):
    from hyperspace_tpu.telemetry.metrics import REGISTRY as METRICS

    def runs():
        m = METRICS.get("ingest.compact.runs")
        return 0 if m is None else int(m.value)

    monkeypatch.setenv("HYPERSPACE_COMPACT_RUNS", "3")
    session, hs, src = _mk(tmp_path)
    before = runs()
    for i in range(1, 4):
        ingest.append_batch(session, "ev", _batch(i))
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline and not (
        ingest.maintenance_idle() and runs() > before
    ):
        time.sleep(0.05)
    assert ingest.maintenance_idle()
    # a background compaction demonstrably ran; an append may legitimately
    # have landed a fresh delta run AFTER it, so assert the counter (and
    # that the bucket run counts came back under the trigger threshold),
    # not a perfectly-compacted end state
    assert runs() > before
    entry = hs.get_index("ev")
    assert max(ingest.runs_per_bucket(entry).values()) < 3
    assert _q(session, src) == _raw(session, src)


def test_vacuum_retires_superseded_versions(tmp_path):
    session, hs, src = _mk(tmp_path)
    for i in range(1, 3):
        ingest.append_batch(session, "ev", _batch(i))
    hs.compact_index("ev", min_runs=2)
    dm = IndexDataManager(_index_path(session))
    assert set(dm.get_all_versions()) == {0, 1, 2, 3}
    hs.vacuum_outdated_index("ev")
    assert dm.get_all_versions() == [3]
    assert _q(session, src) == _raw(session, src)


def test_vacuum_grace_defers_then_retires(tmp_path, monkeypatch):
    session, hs, src = _mk(tmp_path)
    ingest.append_batch(session, "ev", _batch(1))
    hs.compact_index("ev", min_runs=2)
    dm = IndexDataManager(_index_path(session))
    monkeypatch.setenv("HYPERSPACE_VACUUM_GRACE_S", "3600")
    hs.vacuum_outdated_index("ev")
    assert set(dm.get_all_versions()) == {0, 1, 2}  # grace window: deferred
    monkeypatch.setenv("HYPERSPACE_VACUUM_GRACE_S", "0")
    hs.vacuum_outdated_index("ev")
    assert dm.get_all_versions() == [2]


# ---------------------------------------------------------------------------
# snapshot pinning / refcount edge cases
# ---------------------------------------------------------------------------


def test_pinned_version_survives_compaction_and_vacuum(tmp_path):
    """THE isolation contract: a query pinned to version K keeps K's files
    on disk while K+1 publishes and K is compacted away; release drains
    the refcount and the next vacuum retires K."""
    session, hs, src = _mk(tmp_path)
    ingest.append_batch(session, "ev", _batch(1))
    ip = _index_path(session)
    snap = ingest.REGISTRY.pin(ip, hs.get_index("ev"))
    ingest.append_batch(session, "ev", _batch(2))  # K+1 publishes
    hs.compact_index("ev", min_runs=2)  # K compacted away
    hs.vacuum_outdated_index("ev")
    dm = IndexDataManager(ip)
    assert set(snap.versions) <= set(dm.get_all_versions())
    assert all(os.path.exists(f) for f in snap.files)
    ingest.REGISTRY.release(snap)
    hs.vacuum_outdated_index("ev")
    assert set(dm.get_all_versions()) == {3}
    assert not any(os.path.exists(f) for f in snap.files if "v__=0" in f)


def test_query_planned_before_append_reads_its_snapshot(tmp_path):
    """Snapshot isolation end to end: a plan resolved before an append —
    and before the superseding compaction+vacuum — still executes against
    its pinned file set and returns the OLD answer."""
    from hyperspace_tpu.plan.executor import execute_plan

    session, hs, src = _mk(tmp_path)
    ingest.append_batch(session, "ev", _batch(1))
    old_ref = _q(session, src)
    df = session.read.parquet(src)
    shaped = (
        df.filter(df["k"] < 30)
        .group_by("k")
        .agg(
            Sum(col("v")).alias("sv"),
            Count(lit(1)).alias("n"),
            Min(col("w")).alias("mn"),
            Max(col("w")).alias("mx"),
        )
        .sort("k")
    )
    with ingest.pin_scope():
        plan = shaped.optimized_plan()  # resolves + pins the old snapshot
        ingest.append_batch(session, "ev", _batch(2))
        hs.compact_index("ev", min_runs=2)
        hs.vacuum_outdated_index("ev")
        got = execute_plan(plan, session).to_pydict()
    assert got == old_ref
    assert ingest.REGISTRY.active_pins() == 0
    # now that the pin drained, vacuum retires the old versions
    hs.vacuum_outdated_index("ev")
    dm = IndexDataManager(_index_path(session))
    assert set(dm.get_all_versions()) == {3}


def test_pin_scope_releases_on_exception(tmp_path):
    session, hs, src = _mk(tmp_path)
    with pytest.raises(RuntimeError):
        with ingest.pin_scope():
            ingest.pin_current(session, hs.get_index("ev"))
            assert ingest.REGISTRY.active_pins() > 0
            raise RuntimeError("query died")
    assert ingest.REGISTRY.active_pins() == 0


def test_cancelled_query_releases_its_pin(tmp_path):
    """A scheduler-cancelled query (QueryCancelledError is a BaseException)
    unwinds through collect()'s pin scope and drains its refcounts."""
    import threading

    from hyperspace_tpu import serve

    session, hs, src = _mk(tmp_path)
    pinned = threading.Event()

    def query():
        from hyperspace_tpu.serve.context import check_cancelled

        with ingest.pin_scope():
            ingest.pin_current(session, hs.get_index("ev"))
            pinned.set()
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                check_cancelled()  # raises once the handle is cancelled
                time.sleep(0.01)
            raise AssertionError("cancel never arrived")

    sched = serve.QueryScheduler(max_concurrent=1)
    try:
        h = sched.submit(query, label="pinned")
        assert pinned.wait(timeout=30)
        assert ingest.REGISTRY.active_pins() > 0
        h.cancel()
        with pytest.raises(serve.QueryCancelledError):
            h.result(timeout=30)
        assert ingest.REGISTRY.active_pins() == 0
    finally:
        sched.shutdown(wait=True, cancel=True)


def test_recover_never_deletes_a_pinned_version(tmp_path):
    session, hs, src = _mk(tmp_path)
    ingest.append_batch(session, "ev", _batch(1))
    ip = _index_path(session)
    snap = ingest.REGISTRY.pin(ip, hs.get_index("ev"))
    hs.compact_index("ev", min_runs=2)
    # make the pinned versions true orphans: drop every log entry that
    # references them (only the latest, compacted entry remains)
    latest_id = hs.get_index("ev").id
    log_dir = os.path.join(ip, C.HYPERSPACE_LOG)
    for n in list(os.listdir(log_dir)):
        if n.isdigit() and int(n) < latest_id:
            os.unlink(os.path.join(log_dir, n))
    hs.recover(force=True)
    dm = IndexDataManager(ip)
    assert set(snap.versions) <= set(dm.get_all_versions())
    ingest.REGISTRY.release(snap)
    report = hs.recover(force=True)
    assert sorted(report["per_index"]["ev"]["orphan_versions"]) == sorted(
        snap.versions
    )


def test_clear_staging_spares_protected_builds(tmp_path):
    session, hs, _src = _mk(tmp_path)
    ip = _index_path(session)
    dm = IndexDataManager(ip)
    dm.stage_version(7)
    dm.stage_version(8)
    with ingest.protected_version(ip, 7):
        assert dm.clear_staging() == 1  # only the unprotected one swept
        assert dm.staged_versions() == [7]
    assert dm.clear_staging() == 1  # protection released: now sweepable
    assert dm.staged_versions() == []


def test_orphan_version_dirs_spares_protected_and_pinned(tmp_path):
    session, hs, _src = _mk(tmp_path)
    ip = _index_path(session)
    dm = IndexDataManager(ip)
    os.makedirs(dm.version_path(5))
    os.makedirs(dm.version_path(6))
    with ingest.protected_version(ip, 5):
        orphans = dm.orphan_version_dirs(set())
        assert 5 not in orphans and 6 in orphans
    ingest.REGISTRY.protect_version(ip, 6)
    try:
        assert 6 not in dm.orphan_version_dirs(set())
    finally:
        ingest.REGISTRY.unprotect_version(ip, 6)
    assert set(dm.orphan_version_dirs(set())) >= {5, 6}
    # cleanup so other assertions on this warehouse stay meaningful
    dm.delete_version(5)
    dm.delete_version(6)


# ---------------------------------------------------------------------------
# crash recovery at the new fault points
# ---------------------------------------------------------------------------


def _debris(ip: str) -> list:
    lm, dm = IndexLogManager(ip), IndexDataManager(ip)
    bad = []
    latest = lm.get_latest_log()
    if latest is not None and latest.state not in STABLE_STATES:
        bad.append(f"unstable:{latest.state}")
    if dm.staged_versions():
        bad.append(f"staging:{dm.staged_versions()}")
    refs = IndexCollectionManager._referenced_versions(lm)
    orph = [v for v in dm.get_all_versions() if v not in refs]
    if orph:
        bad.append(f"orphans:{orph}")
    return bad


@pytest.mark.parametrize(
    "spec",
    [
        "ingest.append:crash_before:n=1",
        "ingest.append:crash_after:n=1",
        "ingest.compact:crash_before:n=1",
        "ingest.compact:crash_after:n=1",
    ],
)
def test_crash_at_ingest_fault_points_recovers_clean(tmp_path, spec):
    """Crash at either new fault point: recover() leaves a stable,
    orphan-free index, and re-running the op converges bit-identically to
    a never-crashed twin."""
    # twin
    twin_dir = tmp_path / "twin"
    twin_dir.mkdir()
    ts, th, tsrc = _mk(twin_dir)
    tp = os.path.join(tsrc, "p1.parquet")
    cio.write_parquet(ColumnBatch.from_pydict(_batch(1)), tp)
    th.append("ev", ts.read.parquet(tp))
    if spec.startswith("ingest.compact"):
        th.compact_index("ev", min_runs=2)
    twin_bits = repr(_q(ts, tsrc))

    cell_dir = tmp_path / "cell"
    cell_dir.mkdir()
    session, hs, src = _mk(cell_dir)
    p = os.path.join(src, "p1.parquet")
    cio.write_parquet(ColumnBatch.from_pydict(_batch(1)), p)
    if spec.startswith("ingest.compact"):
        hs.append("ev", session.read.parquet(p))
    faults.arm(spec)
    crashed = False
    try:
        if spec.startswith("ingest.compact"):
            hs.compact_index("ev", min_runs=2)
        else:
            hs.append("ev", session.read.parquet(p))
    except faults.InjectedCrash:
        crashed = True
    finally:
        faults.disarm()
    assert crashed
    # "restarted process": fresh manager repairs, then the op converges
    s2 = HyperspaceSession(warehouse_dir=str(cell_dir))
    h2 = Hyperspace(s2)
    h2.recover(force=True)
    ip = _index_path(s2)
    assert _debris(ip) == []
    if spec.startswith("ingest.compact"):
        h2.compact_index("ev", min_runs=2)
    else:
        h2.append("ev", s2.read.parquet(p))
    s2.enable_hyperspace()
    assert repr(_q(s2, src)) == twin_bits


def test_disarmed_fault_points_are_overhead_free(tmp_path):
    """The new hooks add zero metrics / behavior when disarmed."""
    from hyperspace_tpu.telemetry.metrics import REGISTRY as METRICS

    session, hs, src = _mk(tmp_path)
    before = METRICS.get("faults.injected")
    before_v = before.value if before else 0
    ingest.append_batch(session, "ev", _batch(1))
    hs.compact_index("ev", min_runs=2)
    after = METRICS.get("faults.injected")
    assert (after.value if after else 0) == before_v


# ---------------------------------------------------------------------------
# counters / observability
# ---------------------------------------------------------------------------


def test_ingest_counters_account_the_stream(tmp_path):
    from hyperspace_tpu.telemetry.metrics import REGISTRY as METRICS

    def val(n):
        m = METRICS.get(n)
        return 0 if m is None else int(m.value)

    session, hs, src = _mk(tmp_path)
    a0, r0, c0 = val("ingest.appends"), val("ingest.rows_appended"), val(
        "ingest.compact.runs"
    )
    ingest.append_batch(session, "ev", _batch(1, n=500))
    ingest.append_batch(session, "ev", _batch(2, n=700))
    hs.compact_index("ev", min_runs=2)
    assert val("ingest.appends") == a0 + 2
    assert val("ingest.rows_appended") == r0 + 1200
    assert val("ingest.compact.runs") == c0 + 1


def test_snapshot_registry_state_shape(tmp_path):
    state = ingest.REGISTRY.state()
    for key in (
        "active_pins",
        "pinned_versions",
        "protected_versions",
        "pins_total",
        "releases_total",
    ):
        assert key in state
