"""Columnar substrate tests: ColumnBatch and parquet/csv/json IO."""

import numpy as np
import pytest

from hyperspace_tpu.columnar import io as cio
from hyperspace_tpu.columnar.table import Column, ColumnBatch, Field, Schema
from hyperspace_tpu.exceptions import HyperspaceError


class TestColumnBatch:
    def test_from_pydict_infers_types(self):
        b = ColumnBatch.from_pydict(
            {"i": [1, 2, 3], "f": [1.5, 2.5, 3.5], "s": ["a", "b", "a"], "b": [True, False, True]}
        )
        assert b.num_rows == 3
        assert b.schema.field("i").dtype == "int64"
        assert b.schema.field("f").dtype == "float64"
        assert b.schema.field("s").dtype == "string"
        assert b.schema.field("b").dtype == "bool"
        assert b.to_pydict()["s"] == ["a", "b", "a"]

    def test_string_nulls(self):
        b = ColumnBatch.from_pydict({"s": ["x", None, "y"]})
        assert b.to_pydict()["s"] == ["x", None, "y"]

    def test_filter_take(self):
        b = ColumnBatch.from_pydict({"a": [1, 2, 3, 4], "s": ["p", "q", "r", "s"]})
        f = b.filter(np.array([True, False, True, False]))
        assert f.to_pydict() == {"a": [1, 3], "s": ["p", "r"]}
        t = b.take(np.array([3, 0]))
        assert t.to_pydict() == {"a": [4, 1], "s": ["s", "p"]}

    def test_concat_merges_dictionaries(self):
        b1 = ColumnBatch.from_pydict({"s": ["a", "b"]})
        b2 = ColumnBatch.from_pydict({"s": ["c", "a"]})
        c = ColumnBatch.concat([b1, b2])
        assert c.to_pydict()["s"] == ["a", "b", "c", "a"]

    def test_ragged_raises(self):
        with pytest.raises(HyperspaceError):
            ColumnBatch(
                {
                    "a": Column.from_values([1, 2]),
                    "b": Column.from_values([1]),
                }
            )

    def test_schema_select_missing(self):
        s = Schema([Field("a", "int64")])
        with pytest.raises(HyperspaceError):
            s.field("zzz")


class TestIO:
    def test_parquet_roundtrip(self, tmp_path):
        b = ColumnBatch.from_pydict(
            {"a": [1, 2, 3], "f": [0.5, 1.5, 2.5], "s": ["x", "y", "x"]}
        )
        p = str(tmp_path / "t" / "f.parquet")
        cio.write_parquet(b, p)
        b2 = cio.read_parquet([p])
        assert b2.to_pydict() == b.to_pydict()
        assert cio.read_parquet_schema(p).names == ["a", "f", "s"]

    def test_parquet_column_pruning(self, tmp_path):
        b = ColumnBatch.from_pydict({"a": [1], "b": [2], "c": [3]})
        p = str(tmp_path / "f.parquet")
        cio.write_parquet(b, p)
        b2 = cio.read_parquet([p], columns=["c", "a"])
        assert set(b2.schema.names) == {"a", "c"}

    def test_multi_file_read(self, tmp_path):
        cio.write_parquet(ColumnBatch.from_pydict({"a": [1, 2]}), str(tmp_path / "1.parquet"))
        cio.write_parquet(ColumnBatch.from_pydict({"a": [3]}), str(tmp_path / "2.parquet"))
        b = cio.read_parquet([str(tmp_path / "1.parquet"), str(tmp_path / "2.parquet")])
        assert b.to_pydict()["a"] == [1, 2, 3]

    def test_csv(self, tmp_path):
        p = tmp_path / "d.csv"
        p.write_text("a,b\n1,x\n2,y\n")
        b = cio.read_csv([str(p)])
        assert b.to_pydict() == {"a": [1, 2], "b": ["x", "y"]}

    def test_json(self, tmp_path):
        p = tmp_path / "d.json"
        p.write_text('{"a": 1}\n{"a": 2}\n')
        b = cio.read_json([str(p)])
        assert b.to_pydict() == {"a": [1, 2]}

    def test_date32_roundtrip(self, tmp_path):
        import pyarrow as pa
        import pyarrow.parquet as pq
        import datetime

        t = pa.table({"d": pa.array([datetime.date(1994, 1, 1), datetime.date(1995, 6, 2)])})
        p = str(tmp_path / "d.parquet")
        pq.write_table(t, p)
        b = cio.read_parquet([p])
        assert b.schema.field("d").dtype == "date32"
        # days since epoch
        assert b.column("d").data[0] == (datetime.date(1994, 1, 1) - datetime.date(1970, 1, 1)).days


class TestIndexChunkCache:
    def test_cache_hits_and_invalidates(self, tmp_path):
        import numpy as np

        from hyperspace_tpu.columnar import io as cio
        from hyperspace_tpu.columnar.table import ColumnBatch

        p = str(tmp_path / "f.parquet")
        cio.write_parquet(ColumnBatch.from_pydict({"x": [1, 2, 3]}), p)
        cio._INDEX_CHUNK_CACHE.clear()
        b1 = cio.read_parquet([p], cache=True)
        b2 = cio.read_parquet([p], cache=True)
        # served from cache: a fresh ColumnBatch (callers may rebind columns)
        # sharing the immutable decoded Column objects
        assert b2 is not b1
        assert b2.column("x") is b1.column("x")
        # uncached read never populates or hits
        b3 = cio.read_parquet([p])
        assert b3.column("x") is not b1.column("x")
        # rewrite invalidates. A permuted same-values rewrite produces an
        # identical file size, so this passes ONLY if the key also carries
        # st_mtime_ns/st_ino — the coarse (mtime, size) key this replaced
        # would serve the stale [1, 2, 3].
        import os

        size_before = os.path.getsize(p)
        cio.write_parquet(ColumnBatch.from_pydict({"x": [3, 2, 1]}), p)
        assert os.path.getsize(p) == size_before  # same-size rewrite for real
        b4 = cio.read_parquet([p], cache=True)
        assert b4.to_pydict()["x"] == [3, 2, 1]

    def test_cache_byte_bound_evicts(self, tmp_path):
        from hyperspace_tpu.columnar import io as cio
        from hyperspace_tpu.columnar.table import ColumnBatch

        small = cio._BytesBoundedLRU(1000)
        b = ColumnBatch.from_pydict({"x": list(range(50))})
        nb = cio._batch_nbytes(b)
        small.set("a", b, nb)
        small.set("b", b, nb)
        small.set("c", b, nb)  # 3*400 > 1000: oldest evicted
        assert small.get("a") is None
        assert small.get("c") is b
        # oversized value is refused outright
        small.set("huge", b, 10_000)
        assert small.get("huge") is None
