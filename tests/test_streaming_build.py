"""Out-of-core (streaming) covering-index build tests: bounded-memory file
groups, multi-run buckets, query correctness, Optimize compaction."""

import numpy as np
import pytest

from hyperspace_tpu import CoveringIndexConfig, Hyperspace
from hyperspace_tpu import constants as C
from hyperspace_tpu.columnar import io as cio
from hyperspace_tpu.columnar.table import ColumnBatch
from hyperspace_tpu.models.covering import _file_groups, bucket_id_from_filename
from hyperspace_tpu.meta.entry import FileInfo
from hyperspace_tpu.plan import col, lit, Count, Sum


@pytest.fixture()
def env(tmp_session, tmp_path):
    rng = np.random.default_rng(17)
    src = tmp_path / "src"
    for i in range(6):
        n = 2000
        cio.write_parquet(
            ColumnBatch.from_pydict(
                {
                    "k": rng.integers(0, 500, n).tolist(),
                    "v": rng.uniform(size=n).tolist(),
                }
            ),
            str(src / f"f{i}.parquet"),
        )
    hs = Hyperspace(tmp_session)
    return tmp_session, hs, src


class TestFileGroups:
    def test_grouping_respects_budget(self):
        files = [FileInfo(f"/f{i}", 100, 0) for i in range(10)]
        groups = _file_groups(files, 250)
        assert all(sum(f.size for f in g) <= 250 for g in groups)
        assert sum(len(g) for g in groups) == 10

    def test_oversized_single_file_gets_own_group(self):
        files = [FileInfo("/big", 1000, 0), FileInfo("/small", 10, 0)]
        groups = _file_groups(files, 100)
        assert [len(g) for g in groups] == [1, 1]


class TestStreamingBuild:
    def test_streaming_build_matches_in_memory(self, env, tmp_path):
        session, hs, src = env
        session.set_conf(C.INDEX_NUM_BUCKETS, 4)
        # force streaming: budget below total source size
        session.set_conf(C.BUILD_MAX_BYTES_IN_MEMORY, 40_000)
        df = session.read.parquet(str(src))
        hs.create_index(df, CoveringIndexConfig("sidx", ["k"], ["v"]))
        entry = hs.get_index("sidx")
        files = entry.content.files()
        # multiple sorted runs per bucket (seq-suffixed filenames)
        buckets = [bucket_id_from_filename(f) for f in files]
        assert len(files) > 4 and max(buckets) < 4
        batch = cio.read_parquet(files)
        assert batch.num_rows == 12000
        # per-file: correct bucket, sorted within
        from hyperspace_tpu.ops.bucketize import bucket_ids_for_batch

        for f in files:
            b = cio.read_parquet([f])
            assert (bucket_ids_for_batch(b, ["k"], 4) == bucket_id_from_filename(f)).all()
            assert (np.diff(b.column("k").data) >= 0).all()

    def test_streamed_index_serves_queries(self, env):
        session, hs, src = env
        session.set_conf(C.BUILD_MAX_BYTES_IN_MEMORY, 40_000)
        df = session.read.parquet(str(src))
        hs.create_index(df, CoveringIndexConfig("sidx", ["k"], ["v"]))
        q = lambda d: (
            d.filter(col("k") == 77)
            .select("k", "v")
            .agg(Sum(col("v")).alias("s"), Count(lit(1)).alias("n"))
        )
        expected = q(df).to_pydict()
        session.enable_hyperspace()
        df2 = session.read.parquet(str(src))
        got = q(df2).to_pydict()
        assert got["n"] == expected["n"]
        assert abs(got["s"][0] - expected["s"][0]) < 1e-9

    def test_streamed_join_correct(self, env, tmp_path):
        session, hs, src = env
        session.set_conf(C.BUILD_MAX_BYTES_IN_MEMORY, 40_000)
        cio.write_parquet(
            ColumnBatch.from_pydict(
                {"rk": list(range(500)), "b": [float(i) for i in range(500)]}
            ),
            str(tmp_path / "r" / "r.parquet"),
        )
        ldf = session.read.parquet(str(src))
        rdf = session.read.parquet(str(tmp_path / "r"))
        hs.create_index(ldf, CoveringIndexConfig("sidx", ["k"], ["v"]))
        hs.create_index(rdf, CoveringIndexConfig("ridx", ["rk"], ["b"]))
        q = lambda l, r: l.select("k", "v").join(
            r.select("rk", "b"), col("k") == col("rk")
        )
        expected = q(ldf, rdf).count()
        session.enable_hyperspace()
        got = q(
            session.read.parquet(str(src)),
            session.read.parquet(str(tmp_path / "r")),
        ).count()
        assert got == expected  # multi-run buckets must re-sort, not merge raw

    def test_optimize_compacts_runs(self, env):
        session, hs, src = env
        session.set_conf(C.INDEX_NUM_BUCKETS, 4)
        session.set_conf(C.BUILD_MAX_BYTES_IN_MEMORY, 40_000)
        df = session.read.parquet(str(src))
        hs.create_index(df, CoveringIndexConfig("sidx", ["k"], ["v"]))
        n_before = len(hs.get_index("sidx").content.files())
        hs.optimize_index("sidx", "quick")
        files_after = hs.get_index("sidx").content.files()
        assert len(files_after) == 4 < n_before  # one file per bucket
        assert cio.read_parquet(files_after).num_rows == 12000


class TestStreamingFullRefresh:
    def test_full_refresh_streams_above_budget(self, env, tmp_path):
        """A full refresh of a large source must stream through the bucketed
        writer in file groups (regression: refresh materialized everything
        in memory even when create had streamed)."""
        from hyperspace_tpu import constants as C
        from hyperspace_tpu.models.covering import bucket_id_from_filename

        session, hs, src = env
        df = session.read.parquet(str(src))
        hs.create_index(df, CoveringIndexConfig("sfr", ["k"], ["v"]))
        # append two more files, then force the streaming threshold down
        rng = np.random.default_rng(23)
        for i in range(6, 8):
            cio.write_parquet(
                ColumnBatch.from_pydict(
                    {
                        "k": rng.integers(0, 500, 2000).tolist(),
                        "v": rng.uniform(size=2000).tolist(),
                    }
                ),
                str(src / f"f{i}.parquet"),
            )
        session.set_conf(C.BUILD_MAX_BYTES_IN_MEMORY, 20_000)  # << source size
        hs.refresh_index("sfr", "full")
        entry = hs.get_index("sfr")
        files = entry.content.files()
        # streaming runs carry seq suffixes; multiple runs per bucket expected
        names = [f.rsplit("/", 1)[-1] for f in files]
        assert len({bucket_id_from_filename(n) for n in names} - {None}) > 0
        assert len(names) > session.conf.num_buckets  # more runs than buckets
        # correctness: index-backed query equals raw after the refresh
        q = lambda d: d.filter(col("k") == 7).select("k", "v")
        expected = q(session.read.parquet(str(src))).to_pydict()
        session.enable_hyperspace()
        got = q(session.read.parquet(str(src))).to_pydict()
        session.disable_hyperspace()
        assert sorted(got["v"]) == sorted(expected["v"])
        session.set_conf(C.BUILD_MAX_BYTES_IN_MEMORY, C.BUILD_MAX_BYTES_IN_MEMORY_DEFAULT)


class TestStreamingIncrementalDelete:
    def test_delete_refresh_streams_above_budget(self, tmp_session, tmp_path):
        """Incremental refresh handling deletes must not materialize the
        whole old index above the memory budget: old bucket files rewrite
        one at a time as runs."""
        import os

        from hyperspace_tpu import constants as C

        src = tmp_path / "src"
        rng = np.random.default_rng(31)
        for i in range(4):
            cio.write_parquet(
                ColumnBatch.from_pydict(
                    {
                        "k": rng.integers(0, 100, 1500).tolist(),
                        "v": rng.uniform(size=1500).tolist(),
                    }
                ),
                str(src / f"f{i}.parquet"),
            )
        hs = Hyperspace(tmp_session)
        tmp_session.set_conf(C.INDEX_LINEAGE_ENABLED, True)
        df = tmp_session.read.parquet(str(src))
        hs.create_index(df, CoveringIndexConfig("sdel", ["k"], ["v"]))
        # delete one source file, then force the streaming threshold down
        os.unlink(str(src / "f1.parquet"))
        tmp_session.set_conf(C.BUILD_MAX_BYTES_IN_MEMORY, 10_000)
        hs.refresh_index("sdel", "incremental")
        tmp_session.set_conf(
            C.BUILD_MAX_BYTES_IN_MEMORY, C.BUILD_MAX_BYTES_IN_MEMORY_DEFAULT
        )
        q = lambda d: d.filter(col("k") == 5).select("k", "v")
        expected = q(tmp_session.read.parquet(str(src))).to_pydict()
        tmp_session.enable_hyperspace()
        got = q(tmp_session.read.parquet(str(src))).to_pydict()
        tmp_session.disable_hyperspace()
        assert sorted(got["v"]) == sorted(expected["v"])


class TestStreamingZOrderBuild:
    def test_zorder_create_streams_above_budget(self, tmp_session, tmp_path):
        """A z-order build above the memory budget streams in two passes
        (sampled stats + range-cut runs) and still prunes/answers
        identically to raw."""
        from hyperspace_tpu import ZOrderCoveringIndexConfig
        from hyperspace_tpu import constants as C

        src = tmp_path / "zsrc"
        rng = np.random.default_rng(41)
        for i in range(6):
            n = 3000
            cio.write_parquet(
                ColumnBatch.from_pydict(
                    {
                        "d": rng.integers(0, 10_000, n).tolist(),
                        "v": rng.uniform(size=n).tolist(),
                    }
                ),
                str(src / f"f{i}.parquet"),
            )
        hs = Hyperspace(tmp_session)
        tmp_session.set_conf(C.BUILD_MAX_BYTES_IN_MEMORY, 50_000)
        tmp_session.set_conf(C.ZORDER_TARGET_SOURCE_BYTES_PER_PARTITION, 40_000)
        df = tmp_session.read.parquet(str(src))
        hs.create_index(df, ZOrderCoveringIndexConfig("zs", ["d"], ["v"]))
        tmp_session.set_conf(
            C.BUILD_MAX_BYTES_IN_MEMORY, C.BUILD_MAX_BYTES_IN_MEMORY_DEFAULT
        )
        entry = hs.get_index("zs")
        files = entry.content.files()
        assert len(files) > 3  # multiple range runs
        # every z range-run file holds a narrow slice of the domain: at
        # least, total row count must match the source
        total = sum(cio.read_parquet([f]).num_rows for f in files)
        assert total == 18_000
        q = lambda d: d.filter((col("d") >= 2000) & (col("d") < 2300)).select("d", "v")
        expected = q(tmp_session.read.parquet(str(src))).to_pydict()
        tmp_session.enable_hyperspace()
        got = q(tmp_session.read.parquet(str(src))).to_pydict()
        tmp_session.disable_hyperspace()
        assert sorted(got["v"]) == sorted(expected["v"])

    def test_zorder_streaming_multi_column(self, tmp_session, tmp_path):
        from hyperspace_tpu import ZOrderCoveringIndexConfig
        from hyperspace_tpu import constants as C

        src = tmp_path / "zsrc2"
        rng = np.random.default_rng(43)
        for i in range(4):
            cio.write_parquet(
                ColumnBatch.from_pydict(
                    {
                        "a": rng.integers(0, 1000, 2000).tolist(),
                        "b": rng.uniform(0, 1000, 2000).tolist(),
                        "v": rng.uniform(size=2000).tolist(),
                    }
                ),
                str(src / f"f{i}.parquet"),
            )
        hs = Hyperspace(tmp_session)
        tmp_session.set_conf(C.BUILD_MAX_BYTES_IN_MEMORY, 50_000)
        df = tmp_session.read.parquet(str(src))
        hs.create_index(df, ZOrderCoveringIndexConfig("zs2", ["a", "b"], ["v"]))
        tmp_session.set_conf(
            C.BUILD_MAX_BYTES_IN_MEMORY, C.BUILD_MAX_BYTES_IN_MEMORY_DEFAULT
        )
        q = lambda d: d.filter(col("a") == 7).select("a", "b", "v")
        expected = q(tmp_session.read.parquet(str(src))).to_pydict()
        tmp_session.enable_hyperspace()
        got = q(tmp_session.read.parquet(str(src))).to_pydict()
        tmp_session.disable_hyperspace()
        assert sorted(got["v"]) == sorted(expected["v"])


class TestStreamingZOrderWithNulls:
    def test_nulls_in_one_indexed_column(self, tmp_session, tmp_path):
        """Multi-column streaming z-order over data with nulls must not
        produce ragged sample columns (regression: per-column null dropping
        in pass 1 crashed the build)."""
        import pyarrow as pa
        import pyarrow.parquet as pq

        from hyperspace_tpu import ZOrderCoveringIndexConfig
        from hyperspace_tpu import constants as C

        src = tmp_path / "znull"
        src.mkdir()
        rng = np.random.default_rng(47)
        for i in range(4):
            n = 2000
            b = rng.uniform(0, 100, n)
            bmask = rng.uniform(size=n) < 0.1
            pq.write_table(
                pa.table(
                    {
                        "a": pa.array(rng.integers(0, 1000, n)),
                        "b": pa.array(
                            [None if m else float(v) for v, m in zip(b, bmask)],
                            type=pa.float64(),
                        ),
                        "v": pa.array(rng.uniform(size=n)),
                    }
                ),
                str(src / f"f{i}.parquet"),
            )
        hs = Hyperspace(tmp_session)
        tmp_session.set_conf(C.BUILD_MAX_BYTES_IN_MEMORY, 40_000)
        df = tmp_session.read.parquet(str(src))
        hs.create_index(df, ZOrderCoveringIndexConfig("znul", ["a", "b"], ["v"]))
        tmp_session.set_conf(
            C.BUILD_MAX_BYTES_IN_MEMORY, C.BUILD_MAX_BYTES_IN_MEMORY_DEFAULT
        )
        q = lambda d: d.filter(col("a") < 100).select("a", "b", "v")
        expected = q(tmp_session.read.parquet(str(src))).to_pydict()
        tmp_session.enable_hyperspace()
        got = q(tmp_session.read.parquet(str(src))).to_pydict()
        tmp_session.disable_hyperspace()
        assert sorted(x for x in got["v"]) == sorted(x for x in expected["v"])


class TestPerBucketOptimize:
    def test_optimize_compacts_streamed_runs_per_bucket(self, env, tmp_path):
        """Optimize after a streamed (multi-run) build compacts each bucket
        independently to one file, preserving sort and query results."""
        from hyperspace_tpu import constants as C
        from hyperspace_tpu.models.covering import bucket_id_from_filename

        session, hs, src = env
        session.set_conf(C.BUILD_MAX_BYTES_IN_MEMORY, 20_000)
        df = session.read.parquet(str(src))
        hs.create_index(df, CoveringIndexConfig("opb", ["k"], ["v"]))
        session.set_conf(
            C.BUILD_MAX_BYTES_IN_MEMORY, C.BUILD_MAX_BYTES_IN_MEMORY_DEFAULT
        )
        before = hs.get_index("opb").content.files()
        assert len(before) > session.conf.num_buckets  # multiple runs exist
        hs.optimize_index("opb", "full")
        after = hs.get_index("opb").content.files()
        names = [f.rsplit("/", 1)[-1] for f in after]
        buckets = [bucket_id_from_filename(n) for n in names]
        assert len(names) == len(set(buckets))  # exactly one file per bucket
        q = lambda d: d.filter(col("k") == 11).select("k", "v")
        expected = q(session.read.parquet(str(src))).to_pydict()
        session.enable_hyperspace()
        got = q(session.read.parquet(str(src))).to_pydict()
        session.disable_hyperspace()
        assert sorted(got["v"]) == sorted(expected["v"])
