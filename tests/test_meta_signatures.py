"""Signature provider tests (ref: *SignatureProviderTest suites)."""

from hyperspace_tpu.meta.entry import FileInfo
from hyperspace_tpu.meta.signatures import (
    DEFAULT_PROVIDER_NAME,
    FileBasedSignatureProvider,
    IndexSignatureProvider,
    PlanSignatureProvider,
    get_provider,
)


class FakePlan:
    def __init__(self, kinds, leaves):
        self._kinds = kinds
        self._leaves = leaves

    def preorder_kinds(self):
        return self._kinds

    def leaf_file_infos(self):
        return self._leaves


def files(*specs):
    return [FileInfo(n, s, m) for (n, s, m) in specs]


PLAN = FakePlan(["Filter", "Scan"], [files(("/a", 1, 10), ("/b", 2, 20))])


class TestProviders:
    def test_file_signature_stable_under_order(self):
        p1 = FakePlan(["Scan"], [files(("/a", 1, 10), ("/b", 2, 20))])
        p2 = FakePlan(["Scan"], [files(("/b", 2, 20), ("/a", 1, 10))])
        fp = FileBasedSignatureProvider()
        assert fp.sign(p1) == fp.sign(p2)

    def test_file_signature_changes_on_mtime(self):
        p1 = FakePlan(["Scan"], [files(("/a", 1, 10))])
        p2 = FakePlan(["Scan"], [files(("/a", 1, 11))])
        fp = FileBasedSignatureProvider()
        assert fp.sign(p1) != fp.sign(p2)

    def test_plan_signature_tracks_shape(self):
        pp = PlanSignatureProvider()
        assert pp.sign(FakePlan(["Filter", "Scan"], [])) != pp.sign(
            FakePlan(["Project", "Scan"], [])
        )

    def test_index_signature_combines(self):
        ip = IndexSignatureProvider()
        s1 = ip.sign(PLAN)
        assert s1 is not None
        # data change flips it
        assert s1 != ip.sign(FakePlan(["Filter", "Scan"], [files(("/a", 1, 99))]))
        # shape change flips it
        assert s1 != ip.sign(
            FakePlan(["Project", "Scan"], [files(("/a", 1, 10), ("/b", 2, 20))])
        )

    def test_empty_leaves_gives_none(self):
        assert FileBasedSignatureProvider().sign(FakePlan(["Scan"], [])) is None
        assert IndexSignatureProvider().sign(FakePlan(["Scan"], [])) is None

    def test_factory(self):
        assert isinstance(get_provider(DEFAULT_PROVIDER_NAME), IndexSignatureProvider)
        assert isinstance(
            get_provider(FileBasedSignatureProvider.NAME), FileBasedSignatureProvider
        )
