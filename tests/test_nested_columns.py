"""Nested-column support: struct leaves flatten to __hs_nested.-prefixed
columns (ref: util/ResolverUtils.scala normalization; create-path nested
validation CreateAction.scala:50-81), bare dotted references resolve to
them, and indexes build/rewrite over nested fields."""

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from hyperspace_tpu import CoveringIndexConfig, Hyperspace
from hyperspace_tpu import constants as C
from hyperspace_tpu.plan import col, Sum
from hyperspace_tpu.plan.nodes import FileScan


def write_nested(path, n=2000, seed=0):
    rng = np.random.default_rng(seed)
    t = pa.table(
        {
            "id": pa.array(np.arange(n)),
            "nested": pa.StructArray.from_arrays(
                [
                    pa.array(rng.integers(0, 100, n)),
                    pa.StructArray.from_arrays(
                        [pa.array(rng.uniform(0, 1, n))], names=["score"]
                    ),
                ],
                names=["cnt", "leaf"],
            ),
        }
    )
    path.mkdir(parents=True, exist_ok=True)
    pq.write_table(t, str(path / "p.parquet"))
    return t


class TestNestedFlattening:
    def test_schema_flattens_with_prefix(self, tmp_session, tmp_path):
        write_nested(tmp_path / "src")
        df = tmp_session.read.parquet(str(tmp_path / "src"))
        names = df.schema.names
        assert "id" in names
        assert C.NESTED_FIELD_PREFIX + "nested.cnt" in names
        assert C.NESTED_FIELD_PREFIX + "nested.leaf.score" in names

    def test_dotted_reference_resolves(self, tmp_session, tmp_path):
        t = write_nested(tmp_path / "src")
        df = tmp_session.read.parquet(str(tmp_path / "src"))
        out = df.filter(col("nested.cnt") < 10).select("id", "nested.cnt").to_pydict()
        cnt = t.column("nested").combine_chunks().field("cnt").to_pylist()
        expected_ids = [i for i, c in zip(range(len(cnt)), cnt) if c < 10]
        assert out["id"] == expected_ids
        assert "nested.cnt" in out  # select keeps the user's dotted name

    def test_struct_null_propagates(self, tmp_session, tmp_path):
        t = pa.table(
            {
                "id": pa.array([0, 1, 2]),
                "nested": pa.array(
                    [{"cnt": 5}, None, {"cnt": None}],
                    type=pa.struct([("cnt", pa.int64())]),
                ),
            }
        )
        (tmp_path / "src").mkdir(parents=True)
        pq.write_table(t, str(tmp_path / "src" / "p.parquet"))
        df = tmp_session.read.parquet(str(tmp_path / "src"))
        out = df.filter(col("nested.cnt").is_not_null()).to_pydict()
        assert out["id"] == [0]


class TestNestedIndex:
    def test_covering_index_over_nested_field(self, tmp_session, tmp_path):
        write_nested(tmp_path / "src")
        hs = Hyperspace(tmp_session)
        df = tmp_session.read.parquet(str(tmp_path / "src"))
        hs.create_index(
            df, CoveringIndexConfig("nidx", ["nested.cnt"], ["id"])
        )
        entry = hs.get_index("nidx")
        assert entry.derived_dataset.indexed_columns() == [
            C.NESTED_FIELD_PREFIX + "nested.cnt"
        ]

        q = lambda d: d.filter(col("nested.cnt") == 7).select("id", "nested.cnt")
        expected = q(tmp_session.read.parquet(str(tmp_path / "src"))).to_pydict()
        tmp_session.enable_hyperspace()
        df2 = tmp_session.read.parquet(str(tmp_path / "src"))
        plan = q(df2).optimized_plan()
        scans = [n for n in plan.preorder() if isinstance(n, FileScan)]
        assert any("nidx" in (f.name or "") for s in scans for f in s.files)
        got = q(df2).to_pydict()
        tmp_session.disable_hyperspace()
        assert sorted(got["id"]) == sorted(expected["id"])

    def test_nested_grouped_aggregate(self, tmp_session, tmp_path):
        write_nested(tmp_path / "src")
        df = tmp_session.read.parquet(str(tmp_path / "src"))
        out = (
            df.group_by("nested.cnt")
            .agg(Sum(col("nested.leaf.score")).alias("s"))
            .to_pydict()
        )
        assert len(out[C.NESTED_FIELD_PREFIX + "nested.cnt"]) > 0
