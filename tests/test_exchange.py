"""Distributed bucket-exchange tests over the 8-virtual-device CPU mesh —
the analogue of the reference's shuffle-partitioning behavior exercised via
local-mode Spark."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from hyperspace_tpu.parallel.mesh import device_mesh, num_shards
from hyperspace_tpu.parallel.exchange import bucket_exchange, exchange_with_retry
from hyperspace_tpu.ops.hashing import bucket_ids_np


@pytest.fixture(scope="module")
def mesh():
    return device_mesh()


def test_eight_devices_available():
    assert len(jax.devices()) == 8


class TestBucketExchange:
    def test_rows_land_on_destination_shard(self, mesh):
        d = num_shards(mesh)
        n_total = d * 64
        rng = np.random.default_rng(0)
        keys = rng.integers(0, 10000, n_total).astype(np.int32)
        vals = np.arange(n_total, dtype=np.float32)
        dest = bucket_ids_np([keys], d)

        cols = {"k": jnp.asarray(keys), "v": jnp.asarray(vals)}
        out, valid, overflow = bucket_exchange(
            mesh, cols, jnp.asarray(dest), capacity=64
        )
        assert int(overflow) <= 64
        out_k = np.asarray(out["k"])
        out_v = np.asarray(out["v"])
        valid = np.asarray(valid)

        per_shard = out_k.shape[0] // d
        for shard in range(d):
            sl = slice(shard * per_shard, (shard + 1) * per_shard)
            got_keys = out_k[sl][valid[sl]]
            # every received key hashes to this shard
            assert (bucket_ids_np([got_keys], d) == shard).all()

        # multiset of (k, v) pairs preserved end to end
        got = sorted(zip(out_k[valid].tolist(), out_v[valid].tolist()))
        expect = sorted(zip(keys.tolist(), vals.tolist()))
        assert got == expect

    def test_overflow_detected(self, mesh):
        d = num_shards(mesh)
        # all rows to one bucket: per-(src,dst) count = rows per device
        n_total = d * 32
        keys = np.zeros(n_total, dtype=np.int32)
        dest = np.zeros(n_total, dtype=np.int32)
        cols = {"k": jnp.asarray(keys)}
        _, _, overflow = bucket_exchange(mesh, cols, jnp.asarray(dest), capacity=8)
        assert int(overflow) == 32  # caller must retry with capacity >= 32

    def test_retry_wrapper_handles_skew(self, mesh):
        d = num_shards(mesh)
        n_total = d * 32
        keys = np.zeros(n_total, dtype=np.int32)  # max skew
        vals = np.arange(n_total, dtype=np.float32)
        dest = np.zeros(n_total, dtype=np.int32)
        cols = {"k": jnp.asarray(keys), "v": jnp.asarray(vals)}
        out, valid = exchange_with_retry(mesh, cols, jnp.asarray(dest), n_total // d)
        valid = np.asarray(valid)
        assert valid.sum() == n_total
        assert sorted(np.asarray(out["v"])[valid].tolist()) == vals.tolist()

    def test_pytree_of_many_columns(self, mesh):
        d = num_shards(mesh)
        n = d * 16
        cols = {
            "a": jnp.arange(n, dtype=jnp.int32),
            "b": jnp.arange(n, dtype=jnp.float32) * 2,
            "c": jnp.ones(n, dtype=jnp.int32),
        }
        dest = jnp.asarray(np.arange(n, dtype=np.int32) % d)
        out, valid, overflow = bucket_exchange(mesh, cols, dest, capacity=16)
        valid = np.asarray(valid)
        assert valid.sum() == n
        a = np.asarray(out["a"])[valid]
        b = np.asarray(out["b"])[valid]
        assert np.allclose(b, a * 2.0)


class TestMeshPartitionParity:
    """partition_batch_mesh must reproduce the host partition exactly — the
    bucket layout is the on-disk contract shared by build and query."""

    def _batch(self, n=5000, seed=3):
        from hyperspace_tpu.columnar.table import ColumnBatch

        rng = np.random.default_rng(seed)
        return ColumnBatch.from_pydict(
            {
                "i32": rng.integers(-(2**31), 2**31 - 1, n).astype(np.int32).tolist(),
                "i64": rng.integers(-(2**62), 2**62, n).tolist(),
                "f64": rng.uniform(-1e9, 1e9, n).tolist(),
                "s": [f"v{int(x)}" for x in rng.integers(0, 100, n)],
            }
        )

    @pytest.mark.parametrize(
        "cols", [["i32"], ["i64"], ["f64"], ["s"], ["i32", "s"], ["i64", "i32"]]
    )
    def test_matches_host_partition(self, mesh, cols):
        from hyperspace_tpu.ops.bucketize import partition_batch
        from hyperspace_tpu.parallel.exchange import partition_batch_mesh

        batch = self._batch()
        host = partition_batch(batch, cols, 8)
        dev = partition_batch_mesh(batch, cols, 8, mesh)
        assert dev is not None
        assert len(host) == len(dev)
        for (hb, hrows), (db, drows) in zip(host, dev):
            assert hb == db
            np.testing.assert_array_equal(np.sort(hrows), np.sort(drows))
            # original row order within a bucket is part of the contract
            np.testing.assert_array_equal(hrows, drows)

    def test_tiny_batch_falls_back(self, mesh):
        from hyperspace_tpu.parallel.exchange import partition_batch_mesh

        batch = self._batch(n=4)
        assert partition_batch_mesh(batch, ["i32"], 8, mesh) is None

    def test_skewed_keys_retry_capacity(self, mesh):
        """All rows share one key: per-(src,dst) counts overflow the first
        capacity guess and the retry path must still return every row."""
        from hyperspace_tpu.columnar.table import ColumnBatch
        from hyperspace_tpu.ops.bucketize import partition_batch
        from hyperspace_tpu.parallel.exchange import partition_batch_mesh

        batch = ColumnBatch.from_pydict({"k": [7] * 4096})
        host = partition_batch(batch, ["k"], 8)
        dev = partition_batch_mesh(batch, ["k"], 8, mesh)
        assert dev is not None
        assert len(dev) == len(host) == 1
        np.testing.assert_array_equal(host[0][1], dev[0][1])


class TestMeshBuildEndToEnd:
    def test_index_files_identical_host_vs_mesh(self, tmp_path):
        """A covering index built through the mesh exchange must produce
        byte-identical bucket files to the host build."""
        import pathlib

        from hyperspace_tpu import CoveringIndexConfig, Hyperspace, HyperspaceSession
        from hyperspace_tpu import constants as C
        from hyperspace_tpu.columnar import io as cio
        from hyperspace_tpu.columnar.table import ColumnBatch

        rng = np.random.default_rng(9)
        n = 20000
        data = {
            "k": rng.integers(0, 500, n).tolist(),
            "v": rng.uniform(size=n).tolist(),
            "name": [f"n{int(i)}" for i in rng.integers(0, 50, n)],
        }
        src = tmp_path / "src"
        cio.write_parquet(ColumnBatch.from_pydict(data), str(src / "p.parquet"))

        def build(ws, mesh_devices):
            session = HyperspaceSession(warehouse_dir=str(ws))
            if mesh_devices:
                session.set_conf(C.EXEC_MESH_DEVICES, mesh_devices)
            hs = Hyperspace(session)
            df = session.read.parquet(str(src))
            hs.create_index(df, CoveringIndexConfig("pidx", ["k"], ["v", "name"]))
            entry = hs.get_index("pidx")
            return {
                pathlib.Path(f).name: pathlib.Path(f).read_bytes()
                for f in entry.content.files()
            }

        host_files = build(tmp_path / "w_host", 0)
        mesh_files = build(tmp_path / "w_mesh", 8)
        assert host_files.keys() == mesh_files.keys()
        for name in host_files:
            assert host_files[name] == mesh_files[name], name
