"""Distributed bucket-exchange tests over the 8-virtual-device CPU mesh —
the analogue of the reference's shuffle-partitioning behavior exercised via
local-mode Spark."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from hyperspace_tpu.parallel.mesh import device_mesh, num_shards
from hyperspace_tpu.parallel.exchange import bucket_exchange, exchange_with_retry
from hyperspace_tpu.ops.hashing import bucket_ids_np


@pytest.fixture(scope="module")
def mesh():
    return device_mesh()


def test_eight_devices_available():
    assert len(jax.devices()) == 8


class TestBucketExchange:
    def test_rows_land_on_destination_shard(self, mesh):
        d = num_shards(mesh)
        n_total = d * 64
        rng = np.random.default_rng(0)
        keys = rng.integers(0, 10000, n_total).astype(np.int32)
        vals = np.arange(n_total, dtype=np.float32)
        dest = bucket_ids_np([keys], d)

        cols = {"k": jnp.asarray(keys), "v": jnp.asarray(vals)}
        out, valid, overflow = bucket_exchange(
            mesh, cols, jnp.asarray(dest), capacity=64
        )
        assert int(overflow) <= 64
        out_k = np.asarray(out["k"])
        out_v = np.asarray(out["v"])
        valid = np.asarray(valid)

        per_shard = out_k.shape[0] // d
        for shard in range(d):
            sl = slice(shard * per_shard, (shard + 1) * per_shard)
            got_keys = out_k[sl][valid[sl]]
            # every received key hashes to this shard
            assert (bucket_ids_np([got_keys], d) == shard).all()

        # multiset of (k, v) pairs preserved end to end
        got = sorted(zip(out_k[valid].tolist(), out_v[valid].tolist()))
        expect = sorted(zip(keys.tolist(), vals.tolist()))
        assert got == expect

    def test_overflow_detected(self, mesh):
        d = num_shards(mesh)
        # all rows to one bucket: per-(src,dst) count = rows per device
        n_total = d * 32
        keys = np.zeros(n_total, dtype=np.int32)
        dest = np.zeros(n_total, dtype=np.int32)
        cols = {"k": jnp.asarray(keys)}
        _, _, overflow = bucket_exchange(mesh, cols, jnp.asarray(dest), capacity=8)
        assert int(overflow) == 32  # caller must retry with capacity >= 32

    def test_retry_wrapper_handles_skew(self, mesh):
        d = num_shards(mesh)
        n_total = d * 32
        keys = np.zeros(n_total, dtype=np.int32)  # max skew
        vals = np.arange(n_total, dtype=np.float32)
        dest = np.zeros(n_total, dtype=np.int32)
        cols = {"k": jnp.asarray(keys), "v": jnp.asarray(vals)}
        out, valid = exchange_with_retry(mesh, cols, jnp.asarray(dest), n_total // d)
        valid = np.asarray(valid)
        assert valid.sum() == n_total
        assert sorted(np.asarray(out["v"])[valid].tolist()) == vals.tolist()

    def test_pytree_of_many_columns(self, mesh):
        d = num_shards(mesh)
        n = d * 16
        cols = {
            "a": jnp.arange(n, dtype=jnp.int32),
            "b": jnp.arange(n, dtype=jnp.float32) * 2,
            "c": jnp.ones(n, dtype=jnp.int32),
        }
        dest = jnp.asarray(np.arange(n, dtype=np.int32) % d)
        out, valid, overflow = bucket_exchange(mesh, cols, dest, capacity=16)
        valid = np.asarray(valid)
        assert valid.sum() == n
        a = np.asarray(out["a"])[valid]
        b = np.asarray(out["b"])[valid]
        assert np.allclose(b, a * 2.0)
