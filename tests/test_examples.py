"""Smoke-run every example script: the examples double as end-to-end
lifecycle drives (the reference exercises its notebooks in CI via the
docs build; here the scripts run directly)."""

import os
import subprocess
import sys

import pytest

EXAMPLES_DIR = os.path.join(os.path.dirname(__file__), "..", "examples")
EXAMPLES = sorted(
    f for f in os.listdir(EXAMPLES_DIR) if f.endswith(".py")
)


@pytest.mark.parametrize("script", EXAMPLES)
def test_example_runs(script):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"  # examples must not wait on a TPU grant
    env.pop("PALLAS_AXON_POOL_IPS", None)
    out = subprocess.run(
        [sys.executable, os.path.join(EXAMPLES_DIR, script)],
        capture_output=True,
        text=True,
        timeout=300,
        env=env,
    )
    assert out.returncode == 0, f"{script} failed:\n{out.stderr[-2000:]}"
