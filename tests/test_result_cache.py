"""Snapshot-keyed result cache + incremental view maintenance.

Correctness edges pinned here: a hit is bit-identical to a cold run
(``.hex()`` precision), a fold-after-append equals a full recompute,
non-foldable fragments recompute, a stale-version entry is never served
after vacuum retires its bytes, verify-mode divergence raises, the
8-thread stampede computes once (single-flight), and a CANCELLED build
(``QueryCancelledError`` is a BaseException) never leaves the in-flight
marker latched — the ``BoundedLRU.get_or_put`` regression the cache
population reuses.
"""

import os
import threading
import time

import numpy as np
import pytest

from hyperspace_tpu import CoveringIndexConfig, Hyperspace, HyperspaceSession
from hyperspace_tpu import constants as C
from hyperspace_tpu import ingest
from hyperspace_tpu.cache import result_cache as rc
from hyperspace_tpu.cache import view_maintenance as vm
from hyperspace_tpu.cache.result_cache import RESULT_CACHE
from hyperspace_tpu.columnar import io as cio
from hyperspace_tpu.columnar.table import ColumnBatch
from hyperspace_tpu.exceptions import HyperspaceError
from hyperspace_tpu.plan import Avg, Count, Max, Min, Sum, col, lit
from hyperspace_tpu.plan.kernel_cache import (
    plan_files_fingerprint,
    plan_structure_fingerprint,
)
from hyperspace_tpu.serve.context import QueryCancelledError
from hyperspace_tpu.telemetry import trace
from hyperspace_tpu.telemetry.metrics import REGISTRY


@pytest.fixture()
def cache_on(monkeypatch):
    """Enable the result cache for one test, starting from an empty store."""
    monkeypatch.setenv("HYPERSPACE_RESULT_CACHE", "1")
    RESULT_CACHE.clear()
    yield RESULT_CACHE
    RESULT_CACHE.clear()


@pytest.fixture()
def no_refresh(monkeypatch):
    """Make version-advance refresh a no-op so foreground fold accounting
    is deterministic (refresh has its own test)."""
    monkeypatch.setattr(vm, "maybe_refresh", lambda *a, **k: 0)


def _batch(seed: int, n: int = 1500) -> dict:
    r = np.random.default_rng(seed)
    return {
        "k": r.integers(0, 40, n).tolist(),
        "v": r.integers(0, 1000, n).tolist(),
        "w": r.random(n).tolist(),
    }


def _mk(tmp_path, name="ev", buckets=4):
    ws = str(tmp_path)
    src = os.path.join(ws, "events")
    os.makedirs(src, exist_ok=True)
    cio.write_parquet(
        ColumnBatch.from_pydict(_batch(0)), os.path.join(src, "part0.parquet")
    )
    session = HyperspaceSession(warehouse_dir=ws)
    session.set_conf(C.INDEX_NUM_BUCKETS, buckets)
    hs = Hyperspace(session)
    hs.create_index(
        session.read.parquet(src), CoveringIndexConfig(name, ["k"], ["v", "w"])
    )
    session.enable_hyperspace()
    return session, hs, src


def _agg_df(session, src):
    """Exactly-foldable fragment: count/min/max/int-sum, filter below."""
    df = session.read.parquet(src)
    return df.filter(df["k"] < 25).agg(
        Count(lit(1)).alias("n"),
        Sum(col("v")).alias("sv"),
        Min(col("v")).alias("mn"),
        Max(col("v")).alias("mx"),
    )


def _bits(d: dict) -> str:
    return repr(
        {
            k: [x.hex() if isinstance(x, float) else x for x in v]
            for k, v in d.items()
        }
    )


def _val(name: str) -> int:
    m = REGISTRY.get(name)
    return 0 if m is None else int(m.value)


def _cold(session, src, build):
    """Reference run that bypasses the cache entirely."""
    os.environ["HYPERSPACE_RESULT_CACHE"] = "0"
    try:
        return build(session, src).collect().to_pydict()
    finally:
        os.environ["HYPERSPACE_RESULT_CACHE"] = "1"


# ---------------------------------------------------------------------------
# keys and gating
# ---------------------------------------------------------------------------

def test_disabled_by_default(tmp_path, monkeypatch):
    monkeypatch.delenv("HYPERSPACE_RESULT_CACHE", raising=False)
    RESULT_CACHE.clear()
    session, _hs, src = _mk(tmp_path)
    m0 = _val("cache.result.misses")
    _agg_df(session, src).collect()
    _agg_df(session, src).collect()
    assert len(RESULT_CACHE) == 0
    assert _val("cache.result.misses") == m0


def test_unpinned_plans_not_cached(tmp_path, cache_on):
    """A raw query (no index rewrite, so no snapshot pins) never caches:
    there is no version authority to make invalidation exact."""
    session, _hs, src = _mk(tmp_path)
    session.disable_hyperspace()
    _agg_df(session, src).collect()
    _agg_df(session, src).collect()
    assert len(RESULT_CACHE) == 0


def test_structure_fingerprint_distinguishes_plans(tmp_path):
    session, _hs, src = _mk(tmp_path)
    df = session.read.parquet(src)
    a = df.filter(df["k"] < 25).agg(Sum(col("v")).alias("s")).optimized_plan()
    b = df.filter(df["k"] < 26).agg(Sum(col("v")).alias("s")).optimized_plan()
    c = df.filter(df["k"] < 25).agg(Sum(col("w")).alias("s")).optimized_plan()
    a2 = df.filter(df["k"] < 25).agg(Sum(col("v")).alias("s")).optimized_plan()
    assert plan_structure_fingerprint(a) == plan_structure_fingerprint(a2)
    assert plan_structure_fingerprint(a) != plan_structure_fingerprint(b)
    assert plan_structure_fingerprint(a) != plan_structure_fingerprint(c)


def test_files_fingerprint_tracks_append(tmp_path, cache_on, no_refresh):
    session, _hs, src = _mk(tmp_path)
    p0 = _agg_df(session, src).optimized_plan()
    ingest.append_batch(session, "ev", _batch(1))
    p1 = _agg_df(session, src).optimized_plan()
    assert plan_structure_fingerprint(p0) == plan_structure_fingerprint(p1)
    assert plan_files_fingerprint(p0) != plan_files_fingerprint(p1)


# ---------------------------------------------------------------------------
# hits
# ---------------------------------------------------------------------------

def test_hit_bit_identity_vs_cold_run(tmp_path, cache_on):
    session, _hs, src = _mk(tmp_path)
    h0, m0 = _val("cache.result.hits"), _val("cache.result.misses")
    first = _agg_df(session, src).collect().to_pydict()
    second = _agg_df(session, src).collect().to_pydict()
    assert _val("cache.result.misses") == m0 + 1
    assert _val("cache.result.hits") == h0 + 1
    cold = _cold(session, src, _agg_df)
    assert _bits(first) == _bits(second) == _bits(cold)


def test_hit_runs_zero_exec_and_kernel_spans(tmp_path, cache_on):
    """The zero scan/upload/dispatch contract: a hit's trace carries the
    probe span but no exec:/kernel:/compile:/pipeline: spans at all."""
    session, _hs, src = _mk(tmp_path)
    _agg_df(session, src).collect()  # populate
    with trace.capture() as cap:
        _agg_df(session, src).collect()
    names = [s.name for s in cap.sink.spans]
    assert "cache:probe" in names
    assert not [
        n for n in names
        if n.startswith(("exec:", "kernel:", "compile:", "pipeline:"))
    ]


def test_grouped_results_cache_but_do_not_fold(tmp_path, cache_on, no_refresh):
    """Grouped aggregates cache (exact key) but are classified
    non-foldable; after an append they recompute and re-cache."""
    session, _hs, src = _mk(tmp_path)

    def q(s, p):
        df = s.read.parquet(p)
        return (
            df.filter(df["k"] < 30)
            .group_by("k")
            .agg(Sum(col("v")).alias("sv"), Count(lit(1)).alias("n"))
            .sort("k")
        )

    f0 = _val("cache.result.folds")
    first = q(session, src).collect().to_pydict()
    again = q(session, src).collect().to_pydict()
    assert _bits(first) == _bits(again)
    ingest.append_batch(session, "ev", _batch(2))
    after = q(session, src).collect().to_pydict()
    assert _val("cache.result.folds") == f0
    assert _bits(after) == _bits(_cold(session, src, q))


# ---------------------------------------------------------------------------
# folds
# ---------------------------------------------------------------------------

def test_fold_after_append_equals_full_recompute(tmp_path, cache_on, no_refresh):
    session, _hs, src = _mk(tmp_path)
    f0 = _val("cache.result.folds")
    _agg_df(session, src).collect()  # populate at v0
    ingest.append_batch(session, "ev", _batch(3))
    folded = _agg_df(session, src).collect().to_pydict()
    assert _val("cache.result.folds") == f0 + 1
    assert _val("cache.result.fold_rows") > 0
    RESULT_CACHE.clear()
    recomputed = _agg_df(session, src).collect().to_pydict()
    assert _bits(folded) == _bits(recomputed)
    assert _bits(folded) == _bits(_cold(session, src, _agg_df))


def test_fold_chain_over_multiple_appends(tmp_path, cache_on, no_refresh):
    session, _hs, src = _mk(tmp_path)
    _agg_df(session, src).collect()
    f0 = _val("cache.result.folds")
    for i in range(3):
        ingest.append_batch(session, "ev", _batch(10 + i))
        got = _agg_df(session, src).collect().to_pydict()
        assert _bits(got) == _bits(_cold(session, src, _agg_df))
    assert _val("cache.result.folds") == f0 + 3


def test_fold_depth_cap_reanchors(tmp_path, cache_on, no_refresh, monkeypatch):
    """At the depth cap a candidate is skipped; shallower anchors may still
    fold (a larger delta, same bounded chain), and with every candidate at
    the cap the miss recomputes from scratch — re-anchoring at depth 0."""
    monkeypatch.setenv("HYPERSPACE_RESULT_CACHE_FOLD_DEPTH", "1")
    session, _hs, src = _mk(tmp_path)
    _agg_df(session, src).collect()
    f0 = _val("cache.result.folds")
    ingest.append_batch(session, "ev", _batch(21))
    _agg_df(session, src).collect()  # depth 0 -> 1: folds
    assert _val("cache.result.folds") == f0 + 1
    # drop the depth-0 anchor (as eviction would): only the at-cap entry
    # remains, so the next advance must recompute, not fold
    with RESULT_CACHE._lock:
        anchor = [e for e in RESULT_CACHE._d.values() if e.fold_depth == 0]
        for e in anchor:
            RESULT_CACHE._unlink(e)
    ingest.append_batch(session, "ev", _batch(22))
    got = _agg_df(session, src).collect().to_pydict()
    assert _val("cache.result.folds") == f0 + 1  # no further fold
    new_anchor = [e for e in RESULT_CACHE._d.values() if e.fold_depth == 0]
    assert new_anchor  # the recompute re-anchored at depth 0
    assert _bits(got) == _bits(_cold(session, src, _agg_df))


def test_non_foldable_float_sum_recomputes(tmp_path, cache_on, no_refresh):
    """Float sums are not decomposition-invariant: the fragment caches but
    never folds — post-append queries recompute from scratch."""
    session, _hs, src = _mk(tmp_path)

    def q(s, p):
        df = s.read.parquet(p)
        return df.filter(df["k"] < 25).agg(
            Sum(col("w")).alias("sw"), Avg(col("w")).alias("aw")
        )

    f0 = _val("cache.result.folds")
    q(session, src).collect()
    ingest.append_batch(session, "ev", _batch(4))
    after = q(session, src).collect().to_pydict()
    assert _val("cache.result.folds") == f0
    assert _bits(after) == _bits(_cold(session, src, q))


def test_classify_plan_fold_eligibility(tmp_path):
    session, _hs, src = _mk(tmp_path)
    df = session.read.parquet(src)
    good = df.filter(df["k"] < 25).agg(
        Count(lit(1)).alias("n"), Sum(col("v")).alias("s"),
        Min(col("v")).alias("mn"), Max(col("v")).alias("mx"),
    )
    spec = vm.classify_plan(good.optimized_plan())
    assert spec is not None
    assert spec.kinds == ("count", "sum", "min", "max")
    floaty = df.agg(Sum(col("w")).alias("s"))
    assert vm.classify_plan(floaty.optimized_plan()) is None
    avg = df.agg(Avg(col("v")).alias("a"))
    assert vm.classify_plan(avg.optimized_plan()) is None
    grouped = df.group_by("k").agg(Count(lit(1)).alias("n"))
    assert vm.classify_plan(grouped.optimized_plan()) is None


def test_fold_results_null_identity():
    """SQL NULL (zero qualifying rows) is the fold identity on either side."""
    from hyperspace_tpu.columnar.table import Column

    spec = vm.FoldSpec(("n", "s"), ("count", "sum"))
    null_s = ColumnBatch({
        "n": Column(np.array([0], np.int64), "int64"),
        "s": Column(np.array([0.0]), "float64", np.array([False])),
    })
    val_s = ColumnBatch({
        "n": Column(np.array([3], np.int64), "int64"),
        "s": Column(np.array([42], np.int64), "int64"),
    })
    both = vm.fold_results(null_s, val_s, spec)
    assert both.column("n").data[0] == 3
    assert both.column("s").data[0] == 42 and both.column("s").validity is None
    none = vm.fold_results(null_s, null_s, spec)
    assert none.column("n").data[0] == 0
    assert not none.column("s").validity[0]


# ---------------------------------------------------------------------------
# staleness / vacuum
# ---------------------------------------------------------------------------

def test_stale_version_entry_never_served_after_vacuum(
    tmp_path, cache_on, no_refresh
):
    """Compaction + vacuum retire the entry's pinned version: the exact key
    can never hit again AND the entry leaves the store, so no fold can
    source from vacuumed bytes either."""
    session, hs, src = _mk(tmp_path)
    _agg_df(session, src).collect()  # cached at v0
    assert len(RESULT_CACHE) == 1
    for i in range(3):
        ingest.append_batch(session, "ev", _batch(30 + i))
    hs.compact_index("ev", min_runs=2)
    hs.vacuum_outdated_index("ev")
    # the pre-compaction versions are gone; every cache entry pinned to
    # them (the v0 entry, and any folded descendant) left the store
    assert len(RESULT_CACHE) == 0
    h0 = _val("cache.result.hits")
    got = _agg_df(session, src).collect().to_pydict()
    assert _val("cache.result.hits") == h0  # no stale hit
    assert _bits(got) == _bits(_cold(session, src, _agg_df))


# ---------------------------------------------------------------------------
# verify mode
# ---------------------------------------------------------------------------

def test_verify_mode_passes_clean(tmp_path, cache_on, monkeypatch):
    session, _hs, src = _mk(tmp_path)
    _agg_df(session, src).collect()
    monkeypatch.setenv("HYPERSPACE_RESULT_CACHE", "verify")
    v0 = _val("cache.result.verified")
    _agg_df(session, src).collect()
    assert _val("cache.result.verified") == v0 + 1


def test_verify_mode_divergence_raises(tmp_path, cache_on, monkeypatch):
    session, _hs, src = _mk(tmp_path)
    _agg_df(session, src).collect()
    # tamper the stored result: verify must catch the divergence
    entry = next(iter(RESULT_CACHE._d.values()))
    entry.result.column("sv").data[0] += 1
    monkeypatch.setenv("HYPERSPACE_RESULT_CACHE", "verify")
    with pytest.raises(HyperspaceError, match="verify divergence"):
        _agg_df(session, src).collect()


# ---------------------------------------------------------------------------
# single-flight population (the BoundedLRU.get_or_put semantics)
# ---------------------------------------------------------------------------

def test_single_flight_stampede_computes_once():
    cache = rc.ResultCache("result_test_stampede")
    calls = {"n": 0}
    barrier = threading.Barrier(8)

    def build():
        calls["n"] += 1
        time.sleep(0.2)  # hold the in-flight window open for the stampede
        batch = ColumnBatch({})
        return rc.CachedResult(
            "k", "s", batch, (), (), None, 0, None, None
        )

    results = []

    def worker():
        barrier.wait()
        entry, _hit = cache.get_or_compute("k", build)
        results.append(entry)

    threads = [threading.Thread(target=worker) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert calls["n"] == 1
    assert len({id(e) for e in results}) == 1
    assert cache.check_consistency()


def test_cancelled_build_never_latches_inflight():
    """Regression: a build that dies with QueryCancelledError (a
    BaseException) clears the in-flight marker and wakes waiters, one of
    which takes over — the key is never latched."""
    cache = rc.ResultCache("result_test_cancel")
    started = threading.Event()
    release = threading.Event()
    outcome = {}

    def cancelled_build():
        started.set()
        release.wait(5)
        raise QueryCancelledError("query 1 (stampede) cancelled")

    def victim():
        try:
            cache.get_or_compute("k", cancelled_build)
        except QueryCancelledError:
            outcome["cancelled"] = True

    def successor():
        started.wait(5)
        entry, hit = cache.get_or_compute(
            "k",
            lambda: rc.CachedResult(
                "k", "s", ColumnBatch({}), (), (), None, 0, None, None
            ),
        )
        outcome["successor"] = (entry is not None, hit)

    t1 = threading.Thread(target=victim)
    t2 = threading.Thread(target=successor)
    t1.start()
    t2.start()
    time.sleep(0.05)
    release.set()  # the in-flight build now dies cancelled
    t1.join(5)
    t2.join(5)
    assert outcome.get("cancelled") is True
    built, _ = outcome["successor"]
    assert built
    assert not cache._inflight  # nothing latched
    assert cache.check_consistency()


def test_cancelled_served_query_leaves_cache_clean(tmp_path, cache_on):
    """Integration: a scheduler-cancelled query unwinds through the cache
    build without latching; the same query then computes normally."""
    from hyperspace_tpu import serve

    session, _hs, src = _mk(tmp_path)
    sched = serve.QueryScheduler(max_concurrent=1, queue_depth=8)
    try:
        blocker = threading.Event()

        def slow():
            blocker.wait(5)
            return _agg_df(session, src).collect()

        h1 = sched.submit(slow, label="victim")
        h2 = sched.submit(
            lambda: _agg_df(session, src).collect(), label="follower"
        )
        h1.cancel()
        blocker.set()
        try:
            h1.result(timeout=30)
        except serve.QueryCancelledError:
            pass
        got = h2.result(timeout=30).to_pydict()
        assert _bits(got) == _bits(_cold(session, src, _agg_df))
        assert not RESULT_CACHE._inflight
        assert RESULT_CACHE.check_consistency()
    finally:
        sched.shutdown(wait=True)


# ---------------------------------------------------------------------------
# store accounting / refresh / surfaces
# ---------------------------------------------------------------------------

def test_eviction_byte_accounting(tmp_path, cache_on, monkeypatch):
    # ~1.5 entries worth of budget: the third store must evict
    session, _hs, src = _mk(tmp_path)
    df = session.read.parquet(src)
    probe = df.filter(df["k"] < 1).agg(Count(lit(1)).alias("n"))
    probe.collect()
    per_entry = next(iter(RESULT_CACHE._d.values())).nbytes
    RESULT_CACHE.clear()
    monkeypatch.setenv(
        "HYPERSPACE_RESULT_CACHE_MB", str(2.5 * per_entry / (1024 * 1024))
    )
    e0 = _val("cache.result.evictions")
    for lim in (1, 2, 3, 4):
        df.filter(df["k"] < lim).agg(Count(lit(1)).alias("n")).collect()
    assert _val("cache.result.evictions") > e0
    assert len(RESULT_CACHE) == 2
    assert RESULT_CACHE.check_consistency()


def test_background_refresh_on_append(tmp_path, cache_on):
    session, _hs, src = _mk(tmp_path)
    _agg_df(session, src).collect()
    r0, f0 = _val("cache.result.refreshes"), _val("cache.result.folds")
    ingest.append_batch(session, "ev", _batch(40))
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline and not vm.refresh_idle():
        time.sleep(0.02)
    assert vm.refresh_idle()
    assert _val("cache.result.refreshes") == r0 + 1
    assert _val("cache.result.folds") == f0 + 1
    # the re-issued query hits the refreshed entry: zero execution
    h0 = _val("cache.result.hits")
    got = _agg_df(session, src).collect().to_pydict()
    assert _val("cache.result.hits") == h0 + 1
    assert _bits(got) == _bits(_cold(session, src, _agg_df))


def test_state_surfaces(tmp_path, cache_on):
    session, _hs, src = _mk(tmp_path)
    _agg_df(session, src).collect()
    s = RESULT_CACHE.state()
    assert s["entries"] == 1 and s["bytes"] > 0
    block = rc.result_cache_state_string()
    assert "Result cache" in block and "hit_ratio" in block
    from hyperspace_tpu.telemetry.exporter import snapshot_dict

    snap = snapshot_dict()
    assert snap["result_cache"]["entries"] == 1
