"""SQL three-valued-logic and NULL-handling regression tests.

These pin the semantics the index rewrites rely on: a rewritten plan
(e.g. via to_nnf in data-skipping translation) must return identical rows
to the original, including around NULLs.
"""

import numpy as np
import pytest

from hyperspace_tpu.columnar.table import Column, ColumnBatch, Field, Schema
from hyperspace_tpu.plan import col, lit, Count, Max, Min, Sum
from hyperspace_tpu.plan.expr import Not, to_nnf
from hyperspace_tpu.exceptions import HyperspaceError


def nullable_int(values):
    data = np.array([0 if v is None else v for v in values], dtype=np.int64)
    validity = np.array([v is not None for v in values], dtype=bool)
    return Column(data, "int64", validity)


@pytest.fixture()
def nb():
    return ColumnBatch(
        {
            "a": nullable_int([5, None, 7]),
            "k": nullable_int([1, None, 0]),
        }
    )


class TestThreeValuedLogic:
    def test_not_of_null_comparison_excludes_row(self, nb):
        # a = [5, NULL, 7]; NOT(a == 5) must keep only 7 (NULL is unknown)
        pred = Not(col("a") == 5)
        out = pred.eval(nb)
        assert list(out.data) == [False, False, True]

    def test_nnf_rewrite_is_equivalent(self, nb):
        pred = Not(col("a") == 5)
        direct = pred.eval(nb).data
        rewritten = to_nnf(pred).eval(nb).data
        assert list(direct) == list(rewritten)

    def test_kleene_or_with_known_true(self, nb):
        # NULL OR TRUE is TRUE
        pred = (col("a") == 999) | (col("k").is_null())
        out = pred.eval(nb)
        assert list(out.data) == [False, True, False]

    def test_kleene_and_with_known_false(self, nb):
        # NULL AND FALSE is FALSE (known), row excluded either way
        pred = (col("a") == 5) & (col("k") == 1)
        out = pred.eval(nb)
        assert list(out.data) == [True, False, False]

    def test_in_with_null(self, nb):
        out = col("a").isin([5, 7]).eval(nb)
        assert list(out.data) == [True, False, True]
        out2 = Not(col("a").isin([5])).eval(nb)
        assert list(out2.data) == [False, False, True]


class TestNullJoins:
    def test_null_keys_never_match(self, tmp_session):
        from hyperspace_tpu.plan.nodes import InMemoryScan
        from hyperspace_tpu.plan.dataframe import DataFrame

        left = DataFrame(
            tmp_session,
            InMemoryScan(ColumnBatch({"k": nullable_int([1, None, 0]), "lv": Column.from_values([10, 20, 30])})),
        )
        right = DataFrame(
            tmp_session,
            InMemoryScan(ColumnBatch({"rk": nullable_int([0, None]), "rv": Column.from_values([100, 200])})),
        )
        out = left.join(right, left["k"] == right["rk"]).to_pydict()
        # only k=0 matches rk=0; the two NULLs must not match each other or 0
        assert out["k"] == [0]
        assert out["rv"] == [100]


class TestNullAggregation:
    def test_null_group_key_is_distinct_group(self, tmp_session):
        from hyperspace_tpu.plan.nodes import InMemoryScan
        from hyperspace_tpu.plan.dataframe import DataFrame

        df = DataFrame(
            tmp_session,
            InMemoryScan(
                ColumnBatch(
                    {
                        "g": nullable_int([0, None, 0, None]),
                        "x": Column.from_values([1, 2, 3, 4]),
                    }
                )
            ),
        )
        out = df.group_by("g").agg(Sum(col("x")).alias("s")).to_pydict()
        got = {g: s for g, s in zip(out["g"], out["s"])}
        assert got == {0: 4, None: 6}

    def test_all_null_group_aggregates_to_null(self, tmp_session):
        from hyperspace_tpu.plan.nodes import InMemoryScan
        from hyperspace_tpu.plan.dataframe import DataFrame

        df = DataFrame(
            tmp_session,
            InMemoryScan(
                ColumnBatch(
                    {
                        "g": Column.from_values([1, 1, 2]),
                        "x": nullable_int([None, None, 9]),
                    }
                )
            ),
        )
        out = (
            df.group_by("g")
            .agg(Min(col("x")).alias("mn"), Sum(col("x")).alias("s"), Count(col("x")).alias("n"))
            .sort("g")
            .to_pydict()
        )
        assert out["mn"] == [None, 9]
        assert out["s"] == [None, 9]
        assert out["n"] == [0, 1]

    def test_string_min_max(self, tmp_session):
        df = tmp_session.create_dataframe({"g": [1, 1, 2], "s": ["banana", "apple", "cherry"]})
        out = (
            df.group_by("g")
            .agg(Min(col("s")).alias("mn"), Max(col("s")).alias("mx"))
            .sort("g")
            .to_pydict()
        )
        assert out["mn"] == ["apple", "cherry"]
        assert out["mx"] == ["banana", "cherry"]

    def test_global_string_min(self, tmp_session):
        df = tmp_session.create_dataframe({"s": ["zebra", "apple", "mango"]})
        out = df.agg(Min(col("s")).alias("mn"), Max(col("s")).alias("mx")).to_pydict()
        assert out == {"mn": ["apple"], "mx": ["zebra"]}

    def test_sum_on_string_raises(self, tmp_session):
        df = tmp_session.create_dataframe({"s": ["a"]})
        with pytest.raises(HyperspaceError):
            df.agg(Sum(col("s"))).collect()


class TestDate32Pydict:
    def test_date32_with_none(self):
        import datetime

        schema = Schema([Field("d", "date32")])
        b = ColumnBatch.from_pydict(
            {"d": [datetime.date(1994, 1, 1), None, 19000]}, schema
        )
        assert b.schema.field("d").dtype == "date32"
        assert b.column("d").data[0] == 8766
        assert b.column("d").data[2] == 19000
        assert list(b.column("d").validity) == [True, False, True]


class TestDuplicateJoinColumns:
    def test_collect_raises_on_ambiguous(self, tmp_session):
        l = tmp_session.create_dataframe({"k": [1], "v": [2]})
        r = tmp_session.create_dataframe({"k2": [1], "v": [99]})
        with pytest.raises(HyperspaceError):
            l.join(r, l["k"] == r["k2"]).collect()
