"""Test harness: single-process 8-virtual-device CPU mesh.

Analogue of the reference's local-mode Spark `local[4]` harness
(ref: src/test/scala/com/microsoft/hyperspace/SparkInvolvedSuite.scala:26-56):
distribution is exercised through virtual devices on one host.
Env must be set before jax initializes its backends.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import pytest  # noqa: E402


@pytest.fixture()
def tmp_session(tmp_path):
    """Fresh session with its own warehouse/system path per test (analogue of
    HyperspaceSuite's per-suite `spark.hyperspace.system.path` temp dir)."""
    from hyperspace_tpu.session import HyperspaceSession

    return HyperspaceSession(warehouse_dir=str(tmp_path))
