"""Test harness: single-process 8-virtual-device CPU mesh.

Analogue of the reference's local-mode Spark `local[4]` harness
(ref: src/test/scala/com/microsoft/hyperspace/SparkInvolvedSuite.scala:26-56):
distribution is exercised through virtual devices on one host.
Env must be set before jax initializes its backends.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"
# device kernels must FAIL tests, not silently fall back to the host path
# (the fail-open circuit breaker is for production tunnels, not CI)
os.environ["HYPERSPACE_DEVICE_STRICT"] = "1"

# The environment may pre-register a remote TPU backend (axon sitecustomize)
# and pin jax_platforms to it at interpreter boot; the config update wins as
# long as no backend has been initialized yet, forcing tests onto the
# 8-virtual-device CPU mesh.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture()
def tmp_session(tmp_path):
    """Fresh session with its own warehouse/system path per test (analogue of
    HyperspaceSuite's per-suite `spark.hyperspace.system.path` temp dir)."""
    from hyperspace_tpu.session import HyperspaceSession

    return HyperspaceSession(warehouse_dir=str(tmp_path))
