"""Workload intelligence plane: durable journal, utility ledger, drift.

Pins the PR-16 tentpole guarantees:

- journal durability: size-bound rotation, bounded retention, torn-tail
  tolerance through the ``workload.journal`` fault point (a crash between
  payload and newline costs at most one record, never the journal), and
  first-append healing after a predecessor died mid-write;
- the disabled default is INERT: ``HYPERSPACE_WORKLOAD_DIR`` unset means
  zero writes, zero drift series, zero ledger charges, and the query-log
  record shape is identical whether the plane is on or off;
- one uniform record shape across outcomes: done / failed / cancelled
  (``record_unrun``) records carry the same keys, including the
  zero-filled ``phases_ms`` map over the full phase vocabulary;
- conservation: utility-ledger cross-index sums equal the global
  ``workload.index.*`` counter deltas (charged at the same site);
- the utility ledger ranks used indexes above never-applied ones, flags
  cold candidates, and survives persist/recover round-trips;
- drift fires on a planted regression (once, on the transition), stays
  silent on stable series, and the absolute-ms floor keeps
  microsecond-scale latency jitter from ratio-tripping;
- a result-cache hit emits the same ``HyperspaceIndexUsageEvent``
  chokepoint the rewrite rules use (rule=``ResultCacheHit``);
- /healthz degrades (503 + structured reason) while a drift regression
  stands.
"""

import json
import os

import numpy as np
import pytest

from hyperspace_tpu import CoveringIndexConfig, Hyperspace, HyperspaceSession
from hyperspace_tpu import constants as C
from hyperspace_tpu.columnar import io as cio
from hyperspace_tpu.columnar.table import ColumnBatch
from hyperspace_tpu.plan import Count, Max, Min, Sum, col, lit
from hyperspace_tpu.serve.context import QueryContext
from hyperspace_tpu.telemetry import attribution, workload
from hyperspace_tpu.telemetry.attribution import PHASES, QueryStatsLedger
from hyperspace_tpu.telemetry.index_ledger import INDEX_LEDGER, IndexUtilityLedger
from hyperspace_tpu.telemetry.metrics import REGISTRY
from hyperspace_tpu.telemetry.workload import DRIFT, JOURNAL, DriftDetector
from hyperspace_tpu.utils import faults
from hyperspace_tpu.utils.faults import InjectedCrash


@pytest.fixture(autouse=True)
def _pristine_workload(monkeypatch):
    monkeypatch.delenv("HYPERSPACE_WORKLOAD_DIR", raising=False)
    workload.reset_for_testing()
    yield
    faults.disarm()
    workload.reset_for_testing()


def _val(name: str) -> float:
    m = REGISTRY.get(name)
    return 0 if m is None else m.value


def _journal_on(monkeypatch, tmp_path) -> str:
    d = str(tmp_path / "journal")
    monkeypatch.setenv("HYPERSPACE_WORKLOAD_DIR", d)
    return d


# ---------------------------------------------------------------------------
# journal durability
# ---------------------------------------------------------------------------

class TestJournalDurability:
    def test_fault_point_registered(self):
        assert "workload.journal" in faults.POINTS

    def test_rotation_at_size_bound_and_retention(self, monkeypatch, tmp_path):
        _journal_on(monkeypatch, tmp_path)
        # ROTATE_MB clamps at 1024 bytes; ~420-byte records rotate every 3
        monkeypatch.setenv("HYPERSPACE_WORKLOAD_ROTATE_MB", "0")
        monkeypatch.setenv("HYPERSPACE_WORKLOAD_RETAIN", "2")
        for i in range(12):
            JOURNAL.append({"seq": i, "pad": "x" * 400})
        st = JOURNAL.state()
        assert st["rotations"] >= 3
        files = JOURNAL.files()
        rotated = [f for f in files if not f.endswith("workload.jsonl")]
        assert len(rotated) <= 2, "retention bound must delete oldest slots"
        records = JOURNAL.load()
        assert records, "retained files must still load"
        seqs = [r["seq"] for r in records]
        assert seqs == sorted(seqs) and seqs[-1] == 11
        assert len(records) < 12, "rotation + retention dropped oldest"

    def test_torn_tail_crash_heal_and_skip(self, monkeypatch, tmp_path):
        d = _journal_on(monkeypatch, tmp_path)
        JOURNAL.append({"seq": 1})
        # crash between payload and newline: the armed process dies
        faults.arm("workload.journal:crash_after:n=1")
        with pytest.raises(InjectedCrash):
            JOURNAL.append({"seq": 2})
        faults.disarm()
        path = os.path.join(d, "workload.jsonl")
        raw = open(path, "rb").read()
        assert not raw.endswith(b"\n"), "fault must land between payload and newline"
        # "restart": first append of the next process heals the torn tail
        # so the new record starts on its own line
        JOURNAL.reset_for_testing()
        JOURNAL.append({"seq": 3})
        assert [r["seq"] for r in JOURNAL.load()] == [1, 2, 3]
        # a genuinely truncated payload (crash mid-os-write) is skipped,
        # counted, and never corrupts neighbours
        with open(path, "a", encoding="utf-8") as f:
            f.write('{"seq": 4, "trunca')
        torn_before = _val("workload.journal.torn_skipped")
        JOURNAL.reset_for_testing()
        JOURNAL.append({"seq": 5})
        assert [r["seq"] for r in JOURNAL.load()] == [1, 2, 3, 5]
        assert _val("workload.journal.torn_skipped") == torn_before + 1

    def test_async_submit_lands_after_flush(self, monkeypatch, tmp_path):
        _journal_on(monkeypatch, tmp_path)
        errors = _val("workload.journal.errors")
        for i in range(4):
            JOURNAL.submit({"seq": i})
        JOURNAL.flush()
        assert len(JOURNAL.load()) == 4
        assert _val("workload.journal.errors") == errors


# ---------------------------------------------------------------------------
# the disabled default is inert
# ---------------------------------------------------------------------------

class TestDisabledInert:
    def test_no_writes_no_series_no_charges(self, tmp_path):
        assert not workload.enabled()
        rec_before = _val("workload.journal.records")
        led = QueryStatsLedger(window=4)
        s = led.begin(QueryContext(label="off"))
        with attribution.scope(s):
            workload.note_index_applied("idx", 1_000_000)
            workload.note_prune("idx", "bucket", "k:*", 500, 2)
            workload.note_candidate_reject(["idx"], "NO_APPLICABLE")
        rec = led.finish(s, "done")
        workload.observe_qerror("rows", 7.0)
        assert _val("workload.journal.records") == rec_before
        assert JOURNAL.state() == {
            "enabled": False, "dir": None, "writes": 0, "rotations": 0,
            "current_bytes": 0, "files": 0,
        }
        assert DRIFT.snapshot()["series"] == 0
        assert INDEX_LEDGER.totals()["queries"] == 0
        assert not list(tmp_path.iterdir())
        snap = workload.snapshot()
        assert snap["enabled"] is False and snap["indexes"] == []
        assert workload.healthz_reasons() == []
        # the query-log record is the same shape either way (no key the
        # enabled plane would add or remove from the base record)
        assert "workload" not in rec

    def test_report_strings_name_the_knob(self):
        assert "HYPERSPACE_WORKLOAD_DIR" in workload.workload_report_string()


# ---------------------------------------------------------------------------
# one record shape across outcomes (incl. record_unrun)
# ---------------------------------------------------------------------------

class TestRecordShape:
    def test_done_failed_cancelled_share_one_shape(self):
        led = QueryStatsLedger(window=8)
        done = led.finish(led.begin(QueryContext(label="a")), "done")
        failed = led.finish(led.begin(QueryContext(label="b")), "failed")
        cancelled = led.record_unrun(QueryContext(label="c"), queue_wait_s=0.1)
        assert set(done) == set(failed) == set(cancelled)
        for rec in (done, failed, cancelled):
            assert tuple(rec["phases_ms"]) == PHASES
        # a query that never ran charges nothing but still carries the map
        assert all(v == 0.0 for v in cancelled["phases_ms"].values())
        assert cancelled["counters"] == {}

    def test_enabled_journal_record_schema(self, monkeypatch, tmp_path):
        _journal_on(monkeypatch, tmp_path)
        led = QueryStatsLedger(window=8)
        s = led.begin(QueryContext(label="q"))
        with attribution.scope(s):
            workload.note_index_applied("idx_a", 1_000_000)
            workload.note_prune("idx_a", "bucket", "ev_k:*", 500, 0)
            workload.note_prune("idx_a", "sketch", "", 200, 3)
            workload.note_candidate_reject(["idx_b"], "NO_COMMON_KEYS")
        led.finish(s, "done")
        cancelled = led.record_unrun(QueryContext(label="c"))
        JOURNAL.flush()
        records = JOURNAL.load()
        assert len(records) == 2
        done_rec = next(r for r in records if r["outcome"] == "done")
        canc_rec = next(r for r in records if r["outcome"] == "cancelled")
        # journal rows are base record + v + workload block, uniformly
        assert set(done_rec) == set(canc_rec) == set(cancelled) | {"v", "workload"}
        wl = done_rec["workload"]
        assert [c["index"] for c in wl["chosen"]] == ["idx_a"]
        assert wl["chosen"][0]["prune_kind"] == "bucket+sketch"
        assert {"index": "idx_b", "code": "NO_COMMON_KEYS"} in wl["candidates"]
        assert tuple(done_rec["phases_ms"]) == PHASES


# ---------------------------------------------------------------------------
# conservation + benefit settlement
# ---------------------------------------------------------------------------

class TestConservation:
    def test_ledger_sums_equal_counter_deltas(self, monkeypatch, tmp_path):
        _journal_on(monkeypatch, tmp_path)
        before = {
            k: _val(k) for k in (
                "workload.index.applied", "workload.index.benefit_bytes",
                "workload.index.bytes_skipped",
                "workload.index.rowgroups_skipped",
                "workload.maintenance.actions",
            )
        }
        led = QueryStatsLedger(window=8)
        for i in range(3):
            s = led.begin(QueryContext(label=f"q{i}"))
            with attribution.scope(s):
                workload.note_index_applied("idx_a", 2_000_000)
                workload.note_prune("idx_a", "rowgroup", "", 10_000, 4)
            led.finish(s, "done")
        workload.charge_maintenance("/x/idx_a", "CreateAction", 0.25)
        totals = INDEX_LEDGER.totals()
        assert totals["queries"] == 3
        assert _val("workload.index.applied") - before["workload.index.applied"] == totals["queries"]
        assert (
            _val("workload.index.bytes_skipped")
            - before["workload.index.bytes_skipped"] == totals["bytes_skipped"]
        )
        assert (
            _val("workload.index.rowgroups_skipped")
            - before["workload.index.rowgroups_skipped"]
            == totals["rowgroups_skipped"]
        )
        assert (
            _val("workload.index.benefit_bytes")
            - before["workload.index.benefit_bytes"]
            == pytest.approx(totals["benefit_bytes"], abs=0.01)
        )
        assert (
            _val("workload.maintenance.actions")
            - before["workload.maintenance.actions"]
            == totals["maintenance_actions"] == 1
        )
        assert totals["maintenance_s"] == pytest.approx(0.25)

    def test_benefit_is_counterfactual_minus_actual_share(
        self, monkeypatch, tmp_path
    ):
        _journal_on(monkeypatch, tmp_path)
        led = QueryStatsLedger(window=8)
        s = led.begin(QueryContext(label="q"))
        with attribution.scope(s):
            workload.note_index_applied("idx_a", 1_000_000)
            REGISTRY.counter("io.bytes_decoded").inc(400_000)
        led.finish(s, "done")
        row = next(
            r for r in INDEX_LEDGER.report() if r["name"] == "idx_a"
        )
        assert row["benefit_bytes"] == pytest.approx(600_000, abs=1)
        assert row["queries"] == 1 and row["rules"] == {"rewrite": 1}


# ---------------------------------------------------------------------------
# utility ledger: ranking, cold candidates, persistence
# ---------------------------------------------------------------------------

class TestUtilityLedger:
    def test_used_ranks_above_never_applied(self):
        led = IndexUtilityLedger()
        led.charge_query("used", benefit_bytes=2e9, seq=5, when_s=100.0)
        led.charge_prune("used", bytes_skipped=1e6, rowgroups_skipped=3)
        led.charge_maintenance("used", "create", 0.01)
        led.charge_maintenance("unused", "create", 0.01)
        order = [r["name"] for r in led.report()]
        assert order == ["used", "unused"]
        assert led.cold_candidates() == ["unused"]
        used = led.report()[0]
        assert used["net_utility_s"] > 0
        assert used["last_used_seq"] == 5

    def test_persist_recover_round_trip(self, tmp_path):
        d = str(tmp_path)
        led = IndexUtilityLedger()
        led.charge_query("a", benefit_bytes=10.0, seq=1, when_s=1.0)
        led.charge_maintenance("b", "compact", 0.5)
        led.persist(d)
        fresh = IndexUtilityLedger()
        assert fresh.recover(d) == 2
        assert fresh.totals() == led.totals()
        # recovery is a floor: live numbers past the snapshot are kept
        fresh.charge_query("a", benefit_bytes=5.0, seq=2, when_s=2.0)
        fresh.recover(d)
        assert fresh.totals()["queries"] == 2

    def test_maybe_recover_runs_once(self, tmp_path):
        d = str(tmp_path)
        led = IndexUtilityLedger()
        led.charge_query("a", benefit_bytes=10.0, seq=1, when_s=1.0)
        led.persist(d)
        fresh = IndexUtilityLedger()
        fresh.maybe_recover(d)
        assert fresh.totals()["queries"] == 1
        led.charge_query("a", benefit_bytes=10.0, seq=2, when_s=2.0)
        led.persist(d)
        fresh.maybe_recover(d)  # once-flag: no re-read
        assert fresh.totals()["queries"] == 1


# ---------------------------------------------------------------------------
# drift detection
# ---------------------------------------------------------------------------

@pytest.fixture()
def drift_knobs(monkeypatch):
    monkeypatch.setenv("HYPERSPACE_WORKLOAD_BASELINE", "4")
    monkeypatch.setenv("HYPERSPACE_WORKLOAD_WINDOW", "4")
    monkeypatch.setenv("HYPERSPACE_WORKLOAD_DRIFT_MIN", "4")
    monkeypatch.setenv("HYPERSPACE_WORKLOAD_DRIFT_FACTOR", "2.0")
    monkeypatch.setenv("HYPERSPACE_WORKLOAD_DRIFT_ABS_MS", "1.0")


class TestDrift:
    def test_latency_regression_fires_on_transition_only(self, drift_knobs):
        det = DriftDetector()
        before = _val("workload.drift.latency")
        for _ in range(4):
            det.observe_latency("slowed", 10.0)
        assert det.regressions() == []
        for _ in range(4):
            det.observe_latency("slowed", 100.0)
        regs = det.regressions()
        assert [(r["kind"], r["key"]) for r in regs] == [("latency", "slowed")]
        assert regs[0]["ratio"] == pytest.approx(10.0)
        assert _val("workload.drift.latency") == before + 1
        for _ in range(4):  # sustained drift: one event, not one per query
            det.observe_latency("slowed", 100.0)
        assert _val("workload.drift.latency") == before + 1

    def test_stable_series_is_silent(self, drift_knobs):
        det = DriftDetector()
        for _ in range(10):
            det.observe_latency("stable", 10.0)
        assert det.regressions() == []

    def test_abs_floor_guards_microsecond_jitter(self, drift_knobs):
        det = DriftDetector()
        for _ in range(4):
            det.observe_latency("tiny", 0.01)
        for _ in range(4):
            det.observe_latency("tiny", 0.05)  # 5x ratio, 0.04 ms delta
        assert det.regressions() == []

    def test_qerror_geomean_drift(self, drift_knobs):
        det = DriftDetector()
        for _ in range(4):
            det.observe_qerror("rows", 1.5)
        for _ in range(4):
            det.observe_qerror("rows", 8.0)
        regs = det.regressions()
        assert [(r["kind"], r["key"]) for r in regs] == [("estimator", "rows")]

    def test_healthz_degrades_on_drift(self, monkeypatch, tmp_path, drift_knobs):
        from hyperspace_tpu.telemetry import exporter

        _journal_on(monkeypatch, tmp_path)
        for _ in range(4):
            DRIFT.observe_latency("served_q", 10.0)
        for _ in range(4):
            DRIFT.observe_latency("served_q", 100.0)
        payload, status = exporter.health_dict()
        assert status == 503 and payload["status"] == "degraded"
        assert "workload_drift:latency:served_q" in payload["reasons"]
        monkeypatch.delenv("HYPERSPACE_WORKLOAD_DIR")
        payload, status = exporter.health_dict()
        assert status == 200 and payload["reasons"] == []


# ---------------------------------------------------------------------------
# result-cache serves emit the usage-event chokepoint
# ---------------------------------------------------------------------------

class CacheCapturingLogger:
    events: list = []

    def log_event(self, event):
        type(self).events.append(event)


class TestCacheHitUsageEvent:
    def test_hit_and_workload_credit(self, monkeypatch, tmp_path):
        import importlib

        from hyperspace_tpu.cache.result_cache import RESULT_CACHE
        from hyperspace_tpu.telemetry.logger import clear_event_logger_cache

        # the logger resolves the class through the canonical import path;
        # under pytest this file is ALSO imported as a top-level module, so
        # assert against the canonical copy, not this one
        canonical = importlib.import_module(
            "tests.test_workload"
        ).CacheCapturingLogger

        _journal_on(monkeypatch, tmp_path / "wl")
        monkeypatch.setenv("HYPERSPACE_RESULT_CACHE", "1")
        RESULT_CACHE.clear()
        ws = str(tmp_path)
        src = os.path.join(ws, "events")
        rng = np.random.default_rng(3)
        cio.write_parquet(
            ColumnBatch.from_pydict({
                "k": rng.integers(0, 40, 1500).tolist(),
                "v": rng.integers(0, 1000, 1500).tolist(),
            }),
            os.path.join(src, "part0.parquet"),
        )
        session = HyperspaceSession(warehouse_dir=ws)
        session.set_conf(C.INDEX_NUM_BUCKETS, 4)
        hs = Hyperspace(session)
        hs.create_index(
            session.read.parquet(src),
            CoveringIndexConfig("evc_idx", ["k"], ["v"]),
        )
        clear_event_logger_cache(session)
        session.set_conf(
            C.EVENT_LOGGER_CLASS, "tests.test_workload.CacheCapturingLogger"
        )
        canonical.events.clear()
        session.enable_hyperspace()
        try:
            df = session.read.parquet(src)
            q = lambda: df.filter(col("k") == 7).select("k", "v")
            hits = _val("cache.result.hits")
            cold = q().collect().to_pydict()
            hot = q().collect().to_pydict()
            assert hot == cold
            assert _val("cache.result.hits") == hits + 1
        finally:
            session.disable_hyperspace()
            clear_event_logger_cache(session)
            session.unset_conf(C.EVENT_LOGGER_CLASS)
            RESULT_CACHE.clear()
        usage = [
            e for e in canonical.events
            if type(e).__name__ == "HyperspaceIndexUsageEvent"
            and e.rule == "ResultCacheHit"
        ]
        assert usage and any("evc_idx" in e.index_names for e in usage)
        # the avoided index scan is credited to the workload plane too
        row = next(
            (r for r in INDEX_LEDGER.report() if r["name"] == "evc_idx"), None
        )
        assert row is not None and row["rules"].get("ResultCacheHit", 0) >= 1
