"""TPC-H harness tests: every query must return identical rows with indexes
on vs off (the E2E acceptance gate for the BASELINE workloads)."""

import numpy as np
import pytest

from hyperspace_tpu import Hyperspace
from hyperspace_tpu.benchmark import TPCH_QUERIES, generate_tpch, tpch_indexes
from hyperspace_tpu.plan.nodes import FileScan


def rows_of(df):
    d = df.to_pydict()
    keys = list(d.keys())
    return [tuple(round(v, 6) if isinstance(v, float) else v for v in row)
            for row in zip(*[d[k] for k in keys])]


@pytest.fixture(scope="module")
def tpch_env(tmp_path_factory):
    import jax

    root = str(tmp_path_factory.mktemp("tpch"))
    from hyperspace_tpu.session import HyperspaceSession

    session = HyperspaceSession(warehouse_dir=root)
    generate_tpch(root, rows_lineitem=60_000, seed=1)
    hs = Hyperspace(session)
    tpch_indexes(session, hs, root)
    return session, hs, root


class TestTPCHQueries:
    @pytest.mark.parametrize("name", ["q1", "q3", "q6", "q10", "q17", "q18"])
    def test_indexed_equals_raw(self, tpch_env, name):
        session, hs, root = tpch_env
        q = TPCH_QUERIES[name]
        session.disable_hyperspace()
        expected = rows_of(q(session, root))
        session.enable_hyperspace()
        got = rows_of(q(session, root))
        session.disable_hyperspace()
        assert got == expected, f"{name} rows diverge with indexes enabled"

    def test_q6_uses_zorder(self, tpch_env):
        session, hs, root = tpch_env
        session.enable_hyperspace()
        plan = TPCH_QUERIES["q6"](session, root).optimized_plan()
        session.disable_hyperspace()
        used = [
            n.index_info.index_kind_abbr
            for n in plan.preorder()
            if isinstance(n, FileScan) and n.index_info
        ]
        assert "ZCI" in used

    def test_q3_uses_join_indexes(self, tpch_env):
        session, hs, root = tpch_env
        session.enable_hyperspace()
        plan = TPCH_QUERIES["q3"](session, root).optimized_plan()
        session.disable_hyperspace()
        used = {
            n.index_info.index_name
            for n in plan.preorder()
            if isinstance(n, FileScan) and n.index_info
        }
        assert {"li_orderkey", "od_orderkey"} <= used

    def test_q10_uses_join_indexes_and_produces_rows(self, tpch_env):
        """Q10's join output feeds BOTH a grouped aggregate and a top-k sort
        (the reference's JoinIndexRule covers it because the widened
        li_orderkey/od_orderkey indexes carry the filter + group columns)."""
        session, hs, root = tpch_env
        session.enable_hyperspace()
        plan = TPCH_QUERIES["q10"](session, root).optimized_plan()
        out = TPCH_QUERIES["q10"](session, root).to_pydict()
        session.disable_hyperspace()
        used = {
            n.index_info.index_name
            for n in plan.preorder()
            if isinstance(n, FileScan) and n.index_info
        }
        assert {"li_orderkey", "od_orderkey"} <= used
        assert 0 < len(out["revenue"]) <= 20
        assert out["revenue"] == sorted(out["revenue"], reverse=True)

    def test_q10_cross_check_pandas(self, tpch_env):
        from hyperspace_tpu.benchmark.external import pandas_q10

        session, hs, root = tpch_env
        session.enable_hyperspace()
        got = TPCH_QUERIES["q10"](session, root).to_pydict()
        session.disable_hyperspace()
        exp = pandas_q10(root)
        assert got["o_custkey"] == exp["o_custkey"].tolist()
        for a, b in zip(got["revenue"], exp["revenue"].tolist()):
            assert abs(a - b) <= 1e-6 * max(1.0, abs(b))

    def test_q18_cross_check_pandas(self, tpch_env):
        """HAVING-over-aggregate joined back to orders; ties on sum_qty are
        broken by l_orderkey so both engines agree on the exact row order."""
        from hyperspace_tpu.benchmark.external import pandas_q18

        session, hs, root = tpch_env
        session.enable_hyperspace()
        got = TPCH_QUERIES["q18"](session, root).to_pydict()
        session.disable_hyperspace()
        exp = pandas_q18(root)
        assert len(got["l_orderkey"]) > 0, "threshold leaves no rows: weak test"
        assert got["l_orderkey"] == exp["l_orderkey"].tolist()
        assert got["sum_qty"] == exp["sum_qty"].tolist()

    def test_q1_cross_check_pandas(self, tpch_env):
        """Independent engine check for the grouped-aggregate query."""
        import pandas as pd
        import pyarrow.parquet as pq
        import os

        session, hs, root = tpch_env
        t = pq.read_table(os.path.join(root, "lineitem")).to_pandas()
        t = t[t.l_shipdate <= 10470]
        g = (
            t.groupby(["l_returnflag", "l_linestatus"])
            .agg(
                sum_qty=("l_quantity", "sum"),
                count_order=("l_quantity", "size"),
            )
            .reset_index()
            .sort_values(["l_returnflag", "l_linestatus"])
        )
        out = TPCH_QUERIES["q1"](session, root).to_pydict()
        assert out["l_returnflag"] == list(g.l_returnflag)
        assert np.allclose(out["sum_qty"], g.sum_qty)
        assert list(out["count_order"]) == list(g.count_order)


    def test_q3_uses_fused_bucketed_join_aggregate(self, tpch_env, monkeypatch):
        """The Q3 shape must execute via the per-bucket join+aggregate (the
        join output must never materialize)."""
        import hyperspace_tpu.plan.bucket_join as bj

        session, hs, root = tpch_env
        fired = []
        orig = bj.try_bucketed_join_aggregate

        def spy(a, s):
            r = orig(a, s)
            fired.append(r is not None)
            return r

        monkeypatch.setattr(bj, "try_bucketed_join_aggregate", spy)
        session.enable_hyperspace()
        TPCH_QUERIES["q3"](session, root).collect()
        session.disable_hyperspace()
        assert True in fired


class TestTPCHDeviceJoin:
    def test_q3_device_join_matches_raw(self, tpch_env):
        """With TPU exec enabled, Q3's fused join+aggregate runs the stacked
        device kernel (f64 inputs accumulate in f32 under the relaxed
        default) and must agree with raw within f32 accumulation error;
        under exactF64Aggregates it declines to the exact host twin and
        matches bit for bit."""
        from hyperspace_tpu import constants as C
        from hyperspace_tpu.plan import device_join

        session, hs, root = tpch_env
        expected = TPCH_QUERIES["q3"](session, root).to_pydict()
        session.enable_hyperspace()
        session.set_conf(C.EXEC_TPU_ENABLED, True)
        device_join._CACHE.clear()
        device_join._STACK_CACHE.clear()
        try:
            got = TPCH_QUERIES["q3"](session, root).to_pydict()
            session.set_conf(C.EXEC_EXACT_F64_AGG, True)
            got_exact = TPCH_QUERIES["q3"](session, root).to_pydict()
        finally:
            session.set_conf(C.EXEC_TPU_ENABLED, False)
            session.set_conf(C.EXEC_EXACT_F64_AGG, False)
            session.disable_hyperspace()
        # relaxed default ran the stacked device kernel; exact conf declined
        assert len(device_join._STACK_CACHE) > 0
        assert list(got.keys()) == list(expected.keys())
        for k in got:
            assert len(got[k]) == len(expected[k])
            for a, b, c in zip(got[k], expected[k], got_exact[k]):
                if isinstance(a, float):
                    # device tier: f32 accumulation tolerance
                    assert abs(a - b) <= 1e-6 * max(1.0, abs(b))
                    # strict conf: exact host twin, bit-level agreement
                    assert abs(c - b) <= 1e-9 * max(1.0, abs(b))
                else:
                    assert a == b and c == b
