"""Snapshot-versioned (delta-style) source tests: scans, index builds over
snapshots, refresh reload, time-travel closest-index matching
(ref: DeltaLakeIntegrationTest + DeltaLakeRelation.closestIndex)."""

import numpy as np
import pytest

from hyperspace_tpu import CoveringIndexConfig, Hyperspace
from hyperspace_tpu.columnar.table import ColumnBatch
from hyperspace_tpu.plan import col
from hyperspace_tpu.plan.nodes import FileScan
from hyperspace_tpu.sources.delta import SnapshotTable, VERSION_HISTORY_PROPERTY, closest_index_version


def index_scans(plan):
    return [n for n in plan.preorder() if isinstance(n, FileScan) and n.index_info]


@pytest.fixture()
def table(tmp_path):
    t = SnapshotTable(str(tmp_path / "tbl"))
    t.commit(ColumnBatch.from_pydict({"k": [1, 2, 3], "v": [1.0, 2.0, 3.0]}))
    return t


class TestSnapshotTable:
    def test_commit_and_scan(self, tmp_session, table):
        assert table.latest_version() == 0
        df = table.scan(tmp_session)
        assert df.to_pydict()["k"] == [1, 2, 3]

    def test_append_creates_version(self, tmp_session, table):
        table.commit(ColumnBatch.from_pydict({"k": [4], "v": [4.0]}))
        assert table.latest_version() == 1
        assert table.scan(tmp_session).count() == 4
        # time travel to v0
        assert table.scan(tmp_session, version=0).count() == 3

    def test_delete_files(self, tmp_session, table):
        table.commit(ColumnBatch.from_pydict({"k": [4], "v": [4.0]}))
        files_v1 = table.snapshot_files(1)
        table.delete_files([files_v1[0]])
        assert table.scan(tmp_session).to_pydict()["k"] == [4]


class TestSnapshotIndexing:
    def test_create_index_records_history(self, tmp_session, table):
        hs = Hyperspace(tmp_session)
        df = table.scan(tmp_session)
        hs.create_index(df, CoveringIndexConfig("sidx", ["k"], ["v"]))
        entry = hs.get_index("sidx")
        assert entry.properties[VERSION_HISTORY_PROPERTY] == "1:0"
        assert entry.relation.file_format == "snapshot-parquet"

    def test_rewrite_on_snapshot_scan(self, tmp_session, table):
        hs = Hyperspace(tmp_session)
        hs.create_index(table.scan(tmp_session), CoveringIndexConfig("sidx", ["k"], ["v"]))
        tmp_session.enable_hyperspace()
        q = table.scan(tmp_session).filter(col("k") == 2).select("k", "v")
        assert index_scans(q.optimized_plan())
        assert q.to_pydict() == {"k": [2], "v": [2.0]}

    def test_refresh_after_append_updates_history(self, tmp_session, table):
        hs = Hyperspace(tmp_session)
        hs.create_index(table.scan(tmp_session), CoveringIndexConfig("sidx", ["k"], ["v"]))
        table.commit(ColumnBatch.from_pydict({"k": [9], "v": [9.0]}))
        hs.refresh_index("sidx", "full")
        entry = hs.get_index("sidx")
        assert entry.properties[VERSION_HISTORY_PROPERTY].endswith(":1")
        tmp_session.enable_hyperspace()
        q = table.scan(tmp_session).filter(col("k") == 9).select("k", "v")
        assert index_scans(q.optimized_plan())
        assert q.to_pydict()["k"] == [9]

    def test_time_travel_uses_older_index_version(self, tmp_session, table):
        """Query v0 after the index was refreshed for v1: the rules must pick
        the OLD index log version that matches snapshot v0."""
        hs = Hyperspace(tmp_session)
        hs.create_index(table.scan(tmp_session), CoveringIndexConfig("sidx", ["k"], ["v"]))
        v1_entry_version = hs.get_index("sidx").id
        table.commit(ColumnBatch.from_pydict({"k": [9], "v": [9.0]}))
        hs.refresh_index("sidx", "full")
        assert hs.get_index("sidx").id > v1_entry_version
        tmp_session.enable_hyperspace()
        q = table.scan(tmp_session, version=0).filter(col("k") == 2).select("k", "v")
        plan = q.optimized_plan()
        iscans = index_scans(plan)
        assert iscans, "older snapshot query should still use the index"
        assert iscans[0].index_info.log_version == v1_entry_version
        assert q.to_pydict() == {"k": [2], "v": [2.0]}

    def test_closest_index_version_logic(self):
        props = {VERSION_HISTORY_PROPERTY: "1:0,5:3,9:7"}
        assert closest_index_version(props, 0) == 1
        assert closest_index_version(props, 3) == 5
        assert closest_index_version(props, 5) == 5
        assert closest_index_version(props, 99) == 9
        assert closest_index_version({}, 1) is None
        # malformed/legacy entries are skipped, not crashed on
        assert closest_index_version({VERSION_HISTORY_PROPERTY: "0,3"}, 5) is None


    def test_time_travel_survives_delete_restore(self, tmp_session, table):
        """Extra ACTIVE log entries (delete/restore) must not break the
        log-version:table-version pairing (regression)."""
        hs = Hyperspace(tmp_session)
        hs.create_index(table.scan(tmp_session), CoveringIndexConfig("sidx", ["k"], ["v"]))
        v_created = hs.get_index("sidx").id
        hs.delete_index("sidx")
        hs.restore_index("sidx")
        table.commit(ColumnBatch.from_pydict({"k": [9], "v": [9.0]}))
        hs.refresh_index("sidx", "full")
        tmp_session.enable_hyperspace()
        q = table.scan(tmp_session, version=0).filter(col("k") == 2).select("k", "v")
        iscans = index_scans(q.optimized_plan())
        assert iscans, "v0 query must still use the original index version"
        assert iscans[0].index_info.log_version == v_created
        assert q.to_pydict() == {"k": [2], "v": [2.0]}
