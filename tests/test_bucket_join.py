"""Co-partitioned bucketed merge join execution tests — the physical half of
JoinIndexRule (ref: BucketUnionExec / Exchange-free SMJ behavior)."""

import numpy as np
import pytest

from hyperspace_tpu import CoveringIndexConfig, Hyperspace
from hyperspace_tpu import constants as C
from hyperspace_tpu.columnar import io as cio
from hyperspace_tpu.columnar.table import ColumnBatch
from hyperspace_tpu.plan import col
from hyperspace_tpu.plan.bucket_join import try_bucketed_merge_join, _decompose_side
from hyperspace_tpu.plan.nodes import Join


def sorted_rows(d):
    keys = list(d.keys())
    return sorted(zip(*[d[k] for k in keys]), key=repr)


@pytest.fixture()
def env(tmp_session, tmp_path):
    rng = np.random.default_rng(11)
    n = 3000
    left = {
        "k": rng.integers(0, 300, n).tolist(),
        "a": rng.uniform(size=n).tolist(),
    }
    right = {
        "rk": list(range(300)),
        "b": [i * 1.0 for i in range(300)],
    }
    cio.write_parquet(ColumnBatch.from_pydict(left), str(tmp_path / "l" / "l.parquet"))
    cio.write_parquet(ColumnBatch.from_pydict(right), str(tmp_path / "r" / "r.parquet"))
    hs = Hyperspace(tmp_session)
    ldf = tmp_session.read.parquet(str(tmp_path / "l"))
    rdf = tmp_session.read.parquet(str(tmp_path / "r"))
    hs.create_index(ldf, CoveringIndexConfig("lidx", ["k"], ["a"]))
    hs.create_index(rdf, CoveringIndexConfig("ridx", ["rk"], ["b"]))
    return tmp_session, hs, tmp_path


class TestBucketedJoin:
    def test_rewritten_join_uses_bucketed_path(self, env):
        session, hs, tmp = env
        q = lambda l, r: l.select("k", "a").join(
            r.select("rk", "b"), col("k") == col("rk")
        )
        ldf = session.read.parquet(str(tmp / "l"))
        rdf = session.read.parquet(str(tmp / "r"))
        expected = q(ldf, rdf).to_pydict()
        session.enable_hyperspace()
        l2 = session.read.parquet(str(tmp / "l"))
        r2 = session.read.parquet(str(tmp / "r"))
        plan = q(l2, r2).optimized_plan()
        # the optimized join must decompose into bucketed sides
        join_node = next(n for n in plan.preorder() if isinstance(n, Join))
        assert _decompose_side(join_node.left) is not None
        assert _decompose_side(join_node.right) is not None
        out = try_bucketed_merge_join(join_node, session)
        assert out is not None
        assert sorted_rows(out.to_pydict()) == sorted_rows(expected)

    def test_collect_equals_unindexed(self, env):
        session, hs, tmp = env
        q = lambda l, r: (
            l.select("k", "a")
            .join(r.select("rk", "b"), col("k") == col("rk"))
            .filter(col("b") < 100.0)
        )
        ldf = session.read.parquet(str(tmp / "l"))
        rdf = session.read.parquet(str(tmp / "r"))
        expected = q(ldf, rdf).to_pydict()
        session.enable_hyperspace()
        got = q(
            session.read.parquet(str(tmp / "l")),
            session.read.parquet(str(tmp / "r")),
        ).to_pydict()
        assert sorted_rows(got) == sorted_rows(expected)

    def test_hybrid_append_flows_through_bucket_union(self, env):
        session, hs, tmp = env
        # append new rows to the left source after the index build
        cio.write_parquet(
            ColumnBatch.from_pydict({"k": [7, 8], "a": [111.0, 222.0]}),
            str(tmp / "l" / "l2.parquet"),
        )
        session.set_conf(C.HYBRID_SCAN_ENABLED, True)
        session.enable_hyperspace()
        q = lambda l, r: l.select("k", "a").join(
            r.select("rk", "b"), col("k") == col("rk")
        )
        l2 = session.read.parquet(str(tmp / "l"))
        r2 = session.read.parquet(str(tmp / "r"))
        got = q(l2, r2).to_pydict()
        session.disable_hyperspace()
        expected = q(
            session.read.parquet(str(tmp / "l")),
            session.read.parquet(str(tmp / "r")),
        ).to_pydict()
        assert sorted_rows(got) == sorted_rows(expected)
        assert 111.0 in got["a"]

    def test_no_matches_in_some_buckets(self, tmp_session, tmp_path):
        # keys chosen so several buckets are empty on one side
        cio.write_parquet(
            ColumnBatch.from_pydict({"k": [1, 1, 2], "a": [1.0, 2.0, 3.0]}),
            str(tmp_path / "l" / "l.parquet"),
        )
        cio.write_parquet(
            ColumnBatch.from_pydict({"rk": [2, 99], "b": [10.0, 20.0]}),
            str(tmp_path / "r" / "r.parquet"),
        )
        hs = Hyperspace(tmp_session)
        ldf = tmp_session.read.parquet(str(tmp_path / "l"))
        rdf = tmp_session.read.parquet(str(tmp_path / "r"))
        hs.create_index(ldf, CoveringIndexConfig("li", ["k"], ["a"]))
        hs.create_index(rdf, CoveringIndexConfig("ri", ["rk"], ["b"]))
        tmp_session.enable_hyperspace()
        out = (
            tmp_session.read.parquet(str(tmp_path / "l"))
            .select("k", "a")
            .join(
                tmp_session.read.parquet(str(tmp_path / "r")).select("rk", "b"),
                col("k") == col("rk"),
            )
            .to_pydict()
        )
        assert out["k"] == [2] and out["b"] == [10.0]

    def test_empty_join_result(self, tmp_session, tmp_path):
        cio.write_parquet(
            ColumnBatch.from_pydict({"k": [1], "a": [1.0]}), str(tmp_path / "l" / "l.parquet")
        )
        cio.write_parquet(
            ColumnBatch.from_pydict({"rk": [999], "b": [2.0]}), str(tmp_path / "r" / "r.parquet")
        )
        hs = Hyperspace(tmp_session)
        ldf = tmp_session.read.parquet(str(tmp_path / "l"))
        rdf = tmp_session.read.parquet(str(tmp_path / "r"))
        hs.create_index(ldf, CoveringIndexConfig("li", ["k"], ["a"]))
        hs.create_index(rdf, CoveringIndexConfig("ri", ["rk"], ["b"]))
        tmp_session.enable_hyperspace()
        out = (
            tmp_session.read.parquet(str(tmp_path / "l"))
            .select("k", "a")
            .join(
                tmp_session.read.parquet(str(tmp_path / "r")).select("rk", "b"),
                col("k") == col("rk"),
            )
            .to_pydict()
        )
        assert out == {"k": [], "a": [], "rk": [], "b": []}


class TestBucketJoinAfterRefresh:
    """Multi-file buckets (incremental refresh MERGE) must not be treated as
    sorted (regression: searchsorted over unsorted concatenation)."""

    def test_join_after_incremental_refresh(self, env):
        session, hs, tmp = env
        q = lambda l, r: l.select("k", "a").join(
            r.select("rk", "b"), col("k") == col("rk")
        )
        # append to the RIGHT side source and refresh incrementally: each
        # right bucket now spans two files
        cio.write_parquet(
            ColumnBatch.from_pydict(
                {"rk": list(range(300, 350)), "b": [float(i) for i in range(50)]}
            ),
            str(tmp / "r" / "r2.parquet"),
        )
        hs.refresh_index("ridx", "incremental")
        ldf = session.read.parquet(str(tmp / "l"))
        rdf = session.read.parquet(str(tmp / "r"))
        expected = q(ldf, rdf).to_pydict()
        session.enable_hyperspace()
        got = q(
            session.read.parquet(str(tmp / "l")),
            session.read.parquet(str(tmp / "r")),
        ).to_pydict()
        assert sorted_rows(got) == sorted_rows(expected)


class TestLineagePruneInteraction:
    """Column pruning must not leak the lineage column into the logical
    schema (regression: Union alignment crash under hybrid delete)."""

    def test_hybrid_delete_with_unused_included_column(self, tmp_session, tmp_path):
        import os as _os

        from hyperspace_tpu import CoveringIndexConfig as CIC

        session = tmp_session
        session.set_conf(C.INDEX_LINEAGE_ENABLED, True)
        src = tmp_path / "hd"
        cio.write_parquet(
            ColumnBatch.from_pydict({"k": [1, 2], "a": [1.0, 2.0], "s": ["x", "y"]}),
            str(src / "p1.parquet"),
        )
        cio.write_parquet(
            ColumnBatch.from_pydict({"k": [3], "a": [3.0], "s": ["z"]}),
            str(src / "p2.parquet"),
        )
        hs = Hyperspace(session)
        df = session.read.parquet(str(src))
        # index includes BOTH a and s; the query will not use s
        hs.create_index(df, CIC("hidx", ["k"], ["a", "s"]))
        _os.unlink(src / "p2.parquet")
        cio.write_parquet(
            ColumnBatch.from_pydict({"k": [9], "a": [9.0], "s": ["w"]}),
            str(src / "p3.parquet"),
        )
        session.enable_hyperspace()
        session.set_conf(C.HYBRID_SCAN_ENABLED, True)
        df2 = session.read.parquet(str(src))
        q = df2.filter(col("k") >= 1).select("k", "a")
        got = q.to_pydict()
        session.disable_hyperspace()
        expected = q.to_pydict()
        assert sorted_rows(got) == sorted_rows(expected)
        assert 3.0 not in got["a"] and 9.0 in got["a"]


class TestAliasedKeyNotBucketJoined:
    """A projection that rebinds the bucket column name to another column
    must NOT take the bucketed path (regression: silently wrong results)."""

    def test_aliased_key_falls_back_to_generic_join(self, tmp_session, tmp_path):
        rng = np.random.default_rng(2)
        n = 3000
        cio.write_parquet(
            ColumnBatch.from_pydict(
                {
                    "k": rng.integers(0, 300, n).tolist(),
                    "x": rng.integers(0, 300, n).tolist(),
                    "a": rng.uniform(size=n).tolist(),
                }
            ),
            str(tmp_path / "l" / "l.parquet"),
        )
        cio.write_parquet(
            ColumnBatch.from_pydict(
                {"rk": list(range(300)), "b": [float(i) for i in range(300)]}
            ),
            str(tmp_path / "r" / "r.parquet"),
        )
        hs = Hyperspace(tmp_session)
        ldf = tmp_session.read.parquet(str(tmp_path / "l"))
        rdf = tmp_session.read.parquet(str(tmp_path / "r"))
        hs.create_index(ldf, CoveringIndexConfig("li", ["k"], ["a", "x"]))
        hs.create_index(rdf, CoveringIndexConfig("ri", ["rk"], ["b"]))
        q = lambda l, r: l.select(col("x").alias("k"), "a").join(
            r.select("rk", "b"), col("k") == col("rk")
        )
        expected = q(ldf, rdf).count()
        tmp_session.enable_hyperspace()
        got = q(
            tmp_session.read.parquet(str(tmp_path / "l")),
            tmp_session.read.parquet(str(tmp_path / "r")),
        ).count()
        assert got == expected == n  # every x matches some rk


class TestCompositeKeyGrouping:
    """Grouping by a strict subset of a multi-column join key must NOT take
    the fused per-bucket aggregate: buckets hash the full key tuple, so one
    group's rows span buckets and the per-bucket partials would concatenate
    unmerged (regression: 399 rows instead of 50, wrong sums)."""

    @pytest.fixture()
    def two_key_env(self, tmp_session, tmp_path):
        rng = np.random.default_rng(5)
        n = 4000
        left = {
            "k1": rng.integers(0, 50, n).tolist(),
            "k2": rng.integers(0, 8, n).tolist(),
            "a": rng.uniform(size=n).tolist(),
        }
        # right side: the full (k1, k2) cross product so every row joins
        right = {
            "r1": [i for i in range(50) for _ in range(8)],
            "r2": [j for _ in range(50) for j in range(8)],
            "b": [1.0] * 400,
        }
        cio.write_parquet(ColumnBatch.from_pydict(left), str(tmp_path / "l" / "l.parquet"))
        cio.write_parquet(ColumnBatch.from_pydict(right), str(tmp_path / "r" / "r.parquet"))
        hs = Hyperspace(tmp_session)
        ldf = tmp_session.read.parquet(str(tmp_path / "l"))
        rdf = tmp_session.read.parquet(str(tmp_path / "r"))
        hs.create_index(ldf, CoveringIndexConfig("l2i", ["k1", "k2"], ["a"]))
        hs.create_index(rdf, CoveringIndexConfig("r2i", ["r1", "r2"], ["b"]))
        return tmp_session, tmp_path

    def _query(self, session, tmp, group_cols):
        from hyperspace_tpu.plan import Sum

        l = session.read.parquet(str(tmp / "l")).select("k1", "k2", "a")
        r = session.read.parquet(str(tmp / "r")).select("r1", "r2", "b")
        j = l.join(r, (col("k1") == col("r1")) & (col("k2") == col("r2")))
        return j.group_by(*group_cols).agg(Sum(col("a")).alias("s"))

    def test_subset_grouping_not_fused_and_correct(self, two_key_env):
        from hyperspace_tpu.plan.bucket_join import try_bucketed_join_aggregate
        from hyperspace_tpu.plan.nodes import Aggregate

        session, tmp = two_key_env
        expected = self._query(session, tmp, ["k1"]).to_pydict()
        assert len(expected["k1"]) == 50
        session.enable_hyperspace()
        q = self._query(session, tmp, ["k1"])
        plan = q.optimized_plan()
        agg = next(n for n in plan.preorder() if isinstance(n, Aggregate))
        assert try_bucketed_join_aggregate(agg, session) is None
        got = q.to_pydict()
        assert_rows_close(got, expected)

    def test_full_key_grouping_still_fused(self, two_key_env):
        from hyperspace_tpu.plan.bucket_join import try_bucketed_join_aggregate
        from hyperspace_tpu.plan.nodes import Aggregate

        session, tmp = two_key_env
        expected = self._query(session, tmp, ["k1", "k2"]).to_pydict()
        session.enable_hyperspace()
        q = self._query(session, tmp, ["k1", "k2"])
        plan = q.optimized_plan()
        agg = next(n for n in plan.preorder() if isinstance(n, Aggregate))
        fused = try_bucketed_join_aggregate(agg, session)
        assert fused is not None
        got = q.to_pydict()
        assert_rows_close(got, expected)

    def test_mixed_side_grouping_fused(self, two_key_env):
        """Grouping by one key from each side still determines every pair."""
        from hyperspace_tpu.plan.bucket_join import try_bucketed_join_aggregate
        from hyperspace_tpu.plan.nodes import Aggregate

        session, tmp = two_key_env
        expected = self._query(session, tmp, ["k1", "r2"]).to_pydict()
        session.enable_hyperspace()
        q = self._query(session, tmp, ["k1", "r2"])
        plan = q.optimized_plan()
        agg = next(n for n in plan.preorder() if isinstance(n, Aggregate))
        assert try_bucketed_join_aggregate(agg, session) is not None
        got = q.to_pydict()
        assert_rows_close(got, expected)


def assert_rows_close(got, expected, tol=1e-6):
    gr, er = sorted_rows(got), sorted_rows(expected)
    assert len(gr) == len(er)
    for g, e in zip(gr, er):
        for gv, ev in zip(g, e):
            if isinstance(gv, float):
                assert abs(gv - ev) <= tol * max(1.0, abs(ev))
            else:
                assert gv == ev


class TestDeviceJoinAggregate:
    """The fused join+aggregate lowers to the device kernels when TPU exec
    is enabled (searchsorted probe + segment reductions; the join output
    never materializes). Results must match the host path."""

    @pytest.fixture()
    def env3(self, tmp_session, tmp_path):
        from hyperspace_tpu.columnar.table import Column

        rng = np.random.default_rng(13)
        n = 6000
        n_keys = 400
        # f32 value columns: f64 Sum/Avg inputs decline to the host twin by
        # design (accumulation would diverge between tiers)
        left = ColumnBatch(
            {
                "k": Column(rng.integers(0, n_keys, n), "int64"),
                "price": Column(
                    rng.uniform(900, 10000, n).astype(np.float32), "float32"
                ),
                "disc": Column(
                    np.round(rng.uniform(0, 0.1, n), 2).astype(np.float32),
                    "float32",
                ),
            }
        )
        right = {
            "rk": list(range(n_keys)),
            "rdate": rng.integers(8000, 10000, n_keys).astype(int).tolist(),
        }
        cio.write_parquet(left, str(tmp_path / "l" / "l.parquet"))
        cio.write_parquet(ColumnBatch.from_pydict(right), str(tmp_path / "r" / "r.parquet"))
        hs = Hyperspace(tmp_session)
        hs.create_index(
            tmp_session.read.parquet(str(tmp_path / "l")),
            CoveringIndexConfig("dl", ["k"], ["price", "disc"]),
        )
        hs.create_index(
            tmp_session.read.parquet(str(tmp_path / "r")),
            CoveringIndexConfig("dr", ["rk"], ["rdate"]),
        )
        return tmp_session, tmp_path

    def _q3_shape(self, session, tmp):
        from hyperspace_tpu.plan import Avg, Count, Sum, lit

        l = session.read.parquet(str(tmp / "l")).select("k", "price", "disc")
        r = session.read.parquet(str(tmp / "r")).select("rk", "rdate").filter(
            col("rdate") < 9500
        )
        return (
            l.join(r, col("k") == col("rk"))
            .group_by("k", "rdate")
            .agg(
                Sum(col("price") * (lit(1.0) - col("disc"))).alias("revenue"),
                Count(lit(1)).alias("n"),
                Avg(col("price")).alias("ap"),
            )
        )

    def test_device_fused_matches_host(self, env3):
        from hyperspace_tpu.plan import device_join

        session, tmp = env3
        expected = self._q3_shape(session, tmp).to_pydict()
        session.enable_hyperspace()
        device_join._CACHE.clear()
        device_join._STACK_CACHE.clear()
        session.set_conf(C.EXEC_TPU_ENABLED, True)
        got = self._q3_shape(session, tmp).to_pydict()
        # the device path actually ran: the stacked all-buckets kernel (one
        # dispatch per join) or, if it declined, the per-bucket kernel
        assert len(device_join._STACK_CACHE) + len(device_join._CACHE) > 0
        assert_rows_close(got, expected)

    def test_stacked_join_is_one_dispatch(self, env3):
        """The whole fused join+aggregate — every bucket — must cost ONE
        kernel dispatch and ONE fetch (VERDICT r3: per-bucket dispatches
        each paid a tunnel round trip)."""
        from hyperspace_tpu.plan import device_join
        from hyperspace_tpu.utils.rpc_meter import METER, RpcMeter

        session, tmp = env3
        session.enable_hyperspace()
        session.set_conf(C.EXEC_TPU_ENABLED, True)
        self._q3_shape(session, tmp).collect()  # warm compile + caches
        before = METER.snapshot()
        out = self._q3_shape(session, tmp).collect()
        delta = RpcMeter.delta(before, METER.snapshot())
        assert out.num_rows > 0
        assert len(device_join._STACK_CACHE) > 0, "stacked path must engage"
        assert delta["dispatches"] == 1, delta
        assert delta["fetches"] == 1, delta

    def test_stacked_right_side_uploads_cache(self, env3):
        """Steady-state repeats re-ship only the left (filtered) side: the
        stacked right-key/column uploads hit the device cache."""
        from hyperspace_tpu.utils.device_cache import DEVICE_CACHE
        from hyperspace_tpu.utils.rpc_meter import METER, RpcMeter

        session, tmp = env3
        session.enable_hyperspace()
        session.set_conf(C.EXEC_TPU_ENABLED, True)
        self._q3_shape(session, tmp).collect()
        h0 = DEVICE_CACHE.hits
        before = METER.snapshot()
        self._q3_shape(session, tmp).collect()
        delta = RpcMeter.delta(before, METER.snapshot())
        assert DEVICE_CACHE.hits > h0, "stacked right side must cache"
        # uploads: the left stack + per-query scalars only — strictly fewer
        # bytes than the cold query shipped
        first_bytes = delta["upload_bytes"]
        before2 = METER.snapshot()
        self._q3_shape(session, tmp).collect()
        delta2 = RpcMeter.delta(before2, METER.snapshot())
        assert delta2["upload_bytes"] <= first_bytes

    def test_stacked_dup_right_keys_left_only(self, tmp_session):
        """Duplicate right keys with left-only aggregates + key groups stay
        on the stacked device path (match-count weighting)."""
        from hyperspace_tpu.plan import Sum
        from hyperspace_tpu.plan.device_join import try_stacked_join_agg, try_host_join_agg
        from hyperspace_tpu.plan.expr import col as ecol
        from hyperspace_tpu.plan.nodes import Aggregate, InMemoryScan
        from hyperspace_tpu.columnar.table import Column

        rng = np.random.default_rng(7)
        loaded = []
        for b in range(3):
            n_l, n_r = 3000, 120
            lb = ColumnBatch(
                {
                    "k": Column(rng.integers(0, 40, n_l), "int64"),
                    "price": Column(
                        rng.uniform(0, 100, n_l).astype(np.float32), "float32"
                    ),
                }
            )
            # duplicate right keys: every key appears 3x
            rb = ColumnBatch.from_pydict(
                {"rk": sorted(list(range(40)) * 3)}
            )
            loaded.append((b, lb, rb, False, True))
        agg = Aggregate(
            [ecol("k")],
            [Sum(ecol("price")).alias("s")],
            InMemoryScan(ColumnBatch.from_pydict({"k": [], "price": []})),
        )
        tmp_session.set_conf(C.EXEC_TPU_ENABLED, True)
        try:
            out = try_stacked_join_agg(
                loaded, ["k"], ["rk"], [], tmp_session, agg
            )
        finally:
            tmp_session.set_conf(C.EXEC_TPU_ENABLED, False)
        assert out is not None
        # host twin declines dup right keys; build the expectation by
        # weighting each left row by its match count (3 per present key)
        got = out.to_pydict()
        expected_parts = []
        for _b, lb, rb, _ls, _rs in loaded:
            k = lb.column("k").data
            p = lb.column("price").data.astype(np.float64)
            sums = {}
            counts = {}
            for kk, pp in zip(k, p):
                sums[kk] = sums.get(kk, 0.0) + 3 * pp
                counts[kk] = counts.get(kk, 0) + 3
            expected_parts.append((sums, counts))
        exp_k, exp_s = [], []
        for sums, _counts in expected_parts:
            for kk in sorted(sums):
                exp_k.append(kk)
                exp_s.append(sums[kk])
        # compare as sorted multisets of (k, s) with f32 tolerance
        got_pairs = sorted(zip(got["k"], got["s"]))
        exp_pairs = sorted(zip(exp_k, exp_s))
        assert len(got_pairs) == len(exp_pairs)
        for (gk, gs), (ek, es) in zip(got_pairs, exp_pairs):
            assert gk == ek
            assert abs(gs - es) <= 1e-3 * max(1.0, abs(es))

    def test_residual_predicate_on_device_unit(self, tmp_session):
        """Residual (non-equi) conjuncts never reach the bucketed path via
        JoinIndexRule (pure equi-join only, as in the reference), but the
        device kernel supports them for direct callers: evaluate per left
        row over gathered right attributes."""
        from hyperspace_tpu.plan import Sum
        from hyperspace_tpu.plan.device_join import try_device_join_agg
        from hyperspace_tpu.plan.expr import col as ecol
        from hyperspace_tpu.plan.nodes import Aggregate, InMemoryScan

        from hyperspace_tpu.columnar.table import Column

        rng = np.random.default_rng(3)
        n = 2000
        lb = ColumnBatch(
            {
                "k": Column(rng.integers(0, 50, n), "int64"),
                # f32: f64 Sum inputs decline to the host twin by design
                "price": Column(
                    rng.uniform(0, 100, n).astype(np.float32), "float32"
                ),
            }
        )
        rb = ColumnBatch.from_pydict(
            {"rk": list(range(50)), "thr": rng.uniform(0, 100, 50).tolist()}
        )
        residual = [ecol("price") > ecol("thr")]
        agg = Aggregate(
            [ecol("k")],
            [Sum(ecol("price")).alias("s")],
            InMemoryScan(
                ColumnBatch.from_pydict({"k": [], "thr": [], "price": []})
            ),
        )
        tmp_session.set_conf(C.EXEC_TPU_ENABLED, True)
        out = try_device_join_agg(
            agg, lb, rb, ["k"], ["rk"], residual, tmp_session, r_sorted=True
        )
        assert out is not None
        got = out.to_pydict()
        # host reference
        import collections

        sums = collections.defaultdict(float)
        thr = {i: t for i, t in zip(rb.to_pydict()["rk"], rb.to_pydict()["thr"])}
        d = lb.to_pydict()
        for k, p in zip(d["k"], d["price"]):
            if p > thr[k]:
                sums[k] += p
        expected = {k: v for k, v in sums.items()}
        got_map = dict(zip(got["k"], got["s"]))
        assert set(got_map) == set(expected)
        for k in expected:
            assert got_map[k] == pytest.approx(expected[k], rel=1e-5)

    def test_f64_sum_declines_device_under_exact_conf(self, tmp_session):
        """Under hyperspace.tpu.exec.exactF64Aggregates, f64 Sum/Avg inputs
        must NOT run the device fused kernel (f32 accumulation would diverge
        from the host twin's exact f64); the host twin serves the bucket.
        With the default (relaxed) conf the device kernel accepts them and
        matches the host within f32 accumulation tolerance."""
        from hyperspace_tpu.plan import Sum
        from hyperspace_tpu.plan import device_join
        from hyperspace_tpu.plan.device_join import (
            try_device_join_agg,
            try_host_join_agg,
        )
        from hyperspace_tpu.plan.expr import col as ecol
        from hyperspace_tpu.plan.nodes import Aggregate, InMemoryScan

        rng = np.random.default_rng(5)
        n = 3000
        lb = ColumnBatch.from_pydict(
            {
                "k": rng.integers(0, 40, n).tolist(),
                "price": rng.uniform(0, 100, n).tolist(),  # float64
            }
        )
        rb = ColumnBatch.from_pydict({"rk": list(range(40))})

        def mkagg():
            return Aggregate(
                [ecol("k")],
                [Sum(ecol("price")).alias("s")],
                InMemoryScan(ColumnBatch.from_pydict({"k": [], "price": []})),
            )

        tmp_session.set_conf(C.EXEC_TPU_ENABLED, True)
        tmp_session.set_conf(C.EXEC_EXACT_F64_AGG, True)
        device_join._CACHE.clear()
        out = try_device_join_agg(
            mkagg(), lb, rb, ["k"], ["rk"], [], tmp_session, r_sorted=True
        )
        assert out is None  # declined: no kernel built, host twin takes over
        assert len(device_join._CACHE) == 0

        # relaxed default: device runs and agrees with the exact host twin
        # within f32 accumulation error
        tmp_session.set_conf(C.EXEC_EXACT_F64_AGG, False)
        dev = try_device_join_agg(
            mkagg(), lb, rb, ["k"], ["rk"], [], tmp_session, r_sorted=True
        )
        tmp_session.set_conf(C.EXEC_TPU_ENABLED, False)
        host = try_host_join_agg(
            mkagg(), lb, rb, ["k"], ["rk"], [], tmp_session, r_sorted=True
        )
        assert dev is not None and host is not None
        d, h = dev.to_pydict(), host.to_pydict()
        assert d["k"] == h["k"]
        for a, b in zip(d["s"], h["s"]):
            assert abs(a - b) <= 1e-5 * max(1.0, abs(b))

    def test_duplicate_right_keys_fall_back(self, tmp_session, tmp_path):
        """Right side with duplicate keys per bucket must use the host join
        (device gather keeps only the first match)."""
        from hyperspace_tpu.plan import Sum

        rng = np.random.default_rng(7)
        n = 3000
        cio.write_parquet(
            ColumnBatch.from_pydict(
                {
                    "k": rng.integers(0, 100, n).tolist(),
                    "a": rng.uniform(size=n).tolist(),
                }
            ),
            str(tmp_path / "l" / "l.parquet"),
        )
        # two rows per right key
        cio.write_parquet(
            ColumnBatch.from_pydict(
                {
                    "rk": [i for i in range(100) for _ in range(2)],
                    "b": [float(i) for i in range(200)],
                }
            ),
            str(tmp_path / "r" / "r.parquet"),
        )
        hs = Hyperspace(tmp_session)
        hs.create_index(
            tmp_session.read.parquet(str(tmp_path / "l")),
            CoveringIndexConfig("dupl", ["k"], ["a"]),
        )
        hs.create_index(
            tmp_session.read.parquet(str(tmp_path / "r")),
            CoveringIndexConfig("dupr", ["rk"], ["b"]),
        )

        def q(s):
            l = s.read.parquet(str(tmp_path / "l")).select("k", "a")
            r = s.read.parquet(str(tmp_path / "r")).select("rk", "b")
            return (
                l.join(r, col("k") == col("rk"))
                .group_by("k")
                .agg(Sum(col("a") * col("b")).alias("s"))
            )

        expected = q(tmp_session).to_pydict()
        tmp_session.enable_hyperspace()
        tmp_session.set_conf(C.EXEC_TPU_ENABLED, True)
        got = q(tmp_session).to_pydict()
        assert_rows_close(got, expected)


class TestDevicePlainJoin:
    """The plain (non-aggregated) co-partitioned merge join probes on
    device and gathers on host in original dtypes — output bit-identical to
    the host merge join, duplicate keys included."""

    def test_unit_matches_host_merge_join_exactly(self, tmp_session):
        from hyperspace_tpu.plan import device_join
        from hyperspace_tpu.plan.bucket_join import _merge_join_batches
        from hyperspace_tpu.plan.device_join import try_device_plain_join

        rng = np.random.default_rng(17)
        n_l, n_r = 9000, 600
        lb = ColumnBatch.from_pydict(
            {
                "k": rng.integers(0, 200, n_l).tolist(),
                "price": rng.uniform(0, 1e4, n_l).tolist(),  # f64 gathers fine
                "tag": rng.choice(["x", "y", "z"], n_l).tolist(),
            }
        )
        # duplicate right keys: three rows per key
        rb = ColumnBatch.from_pydict(
            {
                "rk": [k for k in range(200) for _ in range(3)],
                "w": rng.uniform(size=600).tolist(),
            }
        )
        tmp_session.set_conf(C.EXEC_TPU_ENABLED, True)
        device_join._PLAIN_CACHE.clear()
        dev = try_device_plain_join(
            lb, rb, ["k"], ["rk"], tmp_session, l_sorted=False, r_sorted=False
        )
        tmp_session.set_conf(C.EXEC_TPU_ENABLED, False)
        assert dev is not None and len(device_join._PLAIN_CACHE) == 1
        host = _merge_join_batches(lb, rb, ["k"], ["rk"], False, False)
        assert dev.to_pydict() == host.to_pydict()  # bit-identical, same order

    def test_e2e_join_without_aggregate_uses_device(self, tmp_session, tmp_path):
        """A Q3-shaped rewritten join whose output feeds a projection (no
        aggregate) must run the device probe per bucket in strict mode."""
        from hyperspace_tpu.plan import device_join

        rng = np.random.default_rng(23)
        n = 40000
        n_keys = 500
        cio.write_parquet(
            ColumnBatch.from_pydict(
                {
                    "k": rng.integers(0, n_keys, n).tolist(),
                    "price": rng.uniform(0, 100, n).tolist(),
                }
            ),
            str(tmp_path / "l" / "l.parquet"),
        )
        cio.write_parquet(
            ColumnBatch.from_pydict(
                {
                    "rk": list(range(n_keys)),
                    "rdate": rng.integers(8000, 10000, n_keys).astype(int).tolist(),
                }
            ),
            str(tmp_path / "r" / "r.parquet"),
        )
        tmp_session.set_conf(C.INDEX_NUM_BUCKETS, 2)  # >=4096 rows per bucket
        hs = Hyperspace(tmp_session)
        hs.create_index(
            tmp_session.read.parquet(str(tmp_path / "l")),
            CoveringIndexConfig("pjl", ["k"], ["price"]),
        )
        hs.create_index(
            tmp_session.read.parquet(str(tmp_path / "r")),
            CoveringIndexConfig("pjr", ["rk"], ["rdate"]),
        )

        def q(s):
            l = s.read.parquet(str(tmp_path / "l")).select("k", "price")
            r = s.read.parquet(str(tmp_path / "r")).select("rk", "rdate")
            return l.join(r, col("k") == col("rk")).select("k", "price", "rdate")

        expected = q(tmp_session).to_pydict()
        tmp_session.enable_hyperspace()
        device_join._PLAIN_CACHE.clear()
        tmp_session.set_conf(C.EXEC_TPU_ENABLED, True)
        got = q(tmp_session).to_pydict()
        tmp_session.set_conf(C.EXEC_TPU_ENABLED, False)
        assert len(device_join._PLAIN_CACHE) > 0  # the device probe ran
        assert sorted_rows(got) == sorted_rows(expected)


class TestDeviceJoinAggDuplicates:
    def test_duplicate_right_keys_left_only_aggs_on_device(self, tmp_session):
        """Duplicate right keys + left-only aggregates: the fused kernel
        weights each left row by its match count instead of falling back."""
        from hyperspace_tpu.plan import Avg, Count, Sum, lit
        from hyperspace_tpu.plan import device_join
        from hyperspace_tpu.plan.device_join import (
            try_device_join_agg,
            try_host_join_agg,
        )
        from hyperspace_tpu.plan.expr import col as ecol
        from hyperspace_tpu.plan.nodes import Aggregate, InMemoryScan
        from hyperspace_tpu.columnar.table import Column

        rng = np.random.default_rng(29)
        n = 6000
        lb = ColumnBatch(
            {
                "k": Column(rng.integers(0, 80, n), "int64"),
                "price": Column(
                    rng.uniform(0, 100, n).astype(np.float32), "float32"
                ),
            }
        )
        reps = rng.integers(1, 4, 80)  # 1-3 rows per right key
        rb = ColumnBatch.from_pydict(
            {"rk": [k for k in range(80) for _ in range(int(reps[k]))]}
        )
        agg = Aggregate(
            [ecol("k")],
            [
                Sum(ecol("price")).alias("s"),
                Count(lit(1)).alias("n"),
                Avg(ecol("price")).alias("m"),
            ],
            InMemoryScan(ColumnBatch.from_pydict({"k": [], "price": []})),
        )
        tmp_session.set_conf(C.EXEC_TPU_ENABLED, True)
        device_join._CACHE.clear()
        dev = try_device_join_agg(
            agg, lb, rb, ["k"], ["rk"], [], tmp_session, r_sorted=False
        )
        tmp_session.set_conf(C.EXEC_TPU_ENABLED, False)
        assert dev is not None and len(device_join._CACHE) == 1
        # host reference: per-pair expansion via the numpy merge join
        from hyperspace_tpu.plan.bucket_join import _merge_join_batches

        joined = _merge_join_batches(lb, rb, ["k"], ["rk"], False, False)
        jd = joined.to_pydict()
        import collections

        sums = collections.defaultdict(float)
        cnts = collections.defaultdict(int)
        for k, p in zip(jd["k"], jd["price"]):
            sums[k] += p
            cnts[k] += 1
        d = dev.to_pydict()
        got = {k: (s, c, m) for k, s, c, m in zip(d["k"], d["s"], d["n"], d["m"])}
        assert set(got) == set(sums)
        for k in sums:
            s, c, m = got[k]
            assert c == cnts[k]
            assert s == pytest.approx(sums[k], rel=2e-5)
            assert m == pytest.approx(sums[k] / cnts[k], rel=2e-5)


class TestFloat64JoinKeys:
    def test_f64_keys_near_f32_collapse_stay_exact(self, tmp_session, tmp_path):
        """Distinct f64 join keys that collapse in f32 (16777216.0 vs
        16777217.0) must not spuriously match: the device fused path
        declines f64 keys; the host fused path compares them exactly."""
        from hyperspace_tpu.plan import Sum

        left = {
            "k": [16777216.0, 16777217.0, 16777218.0] * 400,
            "a": [1.0] * 1200,
        }
        right = {"rk": [16777216.0, 16777218.0], "b": [10.0, 20.0]}
        cio.write_parquet(ColumnBatch.from_pydict(left), str(tmp_path / "l" / "l.parquet"))
        cio.write_parquet(ColumnBatch.from_pydict(right), str(tmp_path / "r" / "r.parquet"))
        hs = Hyperspace(tmp_session)
        hs.create_index(
            tmp_session.read.parquet(str(tmp_path / "l")),
            CoveringIndexConfig("f64l", ["k"], ["a"]),
        )
        hs.create_index(
            tmp_session.read.parquet(str(tmp_path / "r")),
            CoveringIndexConfig("f64r", ["rk"], ["b"]),
        )

        def q(s):
            l = s.read.parquet(str(tmp_path / "l")).select("k", "a")
            r = s.read.parquet(str(tmp_path / "r")).select("rk", "b")
            return (
                l.join(r, col("k") == col("rk"))
                .group_by("k")
                .agg(Sum(col("a") * col("b")).alias("s"))
            )

        expected = q(tmp_session).to_pydict()
        assert len(expected["k"]) == 2  # 16777217.0 must NOT match
        tmp_session.enable_hyperspace()
        tmp_session.set_conf(C.EXEC_TPU_ENABLED, True)
        got = q(tmp_session).to_pydict()
        assert_rows_close(got, expected)


class TestMeshMergeJoin:
    """The co-partitioned plain join probes every bucket pair across the
    8-device mesh (parallel.dist_join, shard-local under shard_map — zero
    collectives by co-partitioning); output is bit-identical to the
    per-bucket host merge join including bucket order."""

    def test_e2e_mesh_join_matches_host(self, tmp_session, tmp_path):
        from hyperspace_tpu.parallel import dist_join

        rng = np.random.default_rng(31)
        n = 40000
        n_keys = 400
        cio.write_parquet(
            ColumnBatch.from_pydict(
                {
                    "k": rng.integers(0, n_keys, n).tolist(),
                    "price": rng.uniform(0, 100, n).tolist(),
                }
            ),
            str(tmp_path / "ml" / "l.parquet"),
        )
        cio.write_parquet(
            ColumnBatch.from_pydict(
                {
                    # duplicate right keys exercise run expansion
                    "rk": [k for k in range(n_keys) for _ in range(2)],
                    "rdate": rng.integers(8000, 10000, 2 * n_keys).astype(int).tolist(),
                }
            ),
            str(tmp_path / "mr" / "r.parquet"),
        )
        tmp_session.set_conf(C.INDEX_NUM_BUCKETS, 4)
        hs = Hyperspace(tmp_session)
        hs.create_index(
            tmp_session.read.parquet(str(tmp_path / "ml")),
            CoveringIndexConfig("mjl", ["k"], ["price"]),
        )
        hs.create_index(
            tmp_session.read.parquet(str(tmp_path / "mr")),
            CoveringIndexConfig("mjr", ["rk"], ["rdate"]),
        )

        def q(s):
            l = s.read.parquet(str(tmp_path / "ml")).select("k", "price")
            r = s.read.parquet(str(tmp_path / "mr")).select("rk", "rdate")
            return l.join(r, col("k") == col("rk")).select("k", "price", "rdate")

        expected_raw = q(tmp_session).to_pydict()
        tmp_session.enable_hyperspace()
        host_tier = q(tmp_session).to_pydict()  # indexed, host tier

        dist_join._PROBE_CACHE.clear()
        tmp_session.set_conf(C.EXEC_TPU_ENABLED, True)
        tmp_session.set_conf("hyperspace.tpu.exec.meshDevices", 8)
        mesh_tier = q(tmp_session).to_pydict()
        tmp_session.set_conf(C.EXEC_TPU_ENABLED, False)
        tmp_session.set_conf("hyperspace.tpu.exec.meshDevices", 0)
        tmp_session.disable_hyperspace()

        assert len(dist_join._PROBE_CACHE) > 0, "mesh probe must have run"
        # bit-identical to the indexed host tier (same bucket order), and
        # row-set-equal to the raw join
        assert mesh_tier == host_tier
        assert sorted_rows(mesh_tier) == sorted_rows(expected_raw)


class TestBatchedDeviceJoin:
    """The single-device batched plain join (probe + run expansion on
    device, two fetches total) is bit-identical to the host merge join."""

    def test_unit_matches_host_exactly(self, tmp_session):
        from hyperspace_tpu.plan import device_join
        from hyperspace_tpu.plan.bucket_join import _merge_join_batches
        from hyperspace_tpu.plan.device_join import try_batched_plain_join
        from hyperspace_tpu.ops.join import exact_key32

        rng = np.random.default_rng(43)
        work = []
        expected = {}
        for b, (n_l, n_r) in enumerate([(9000, 900), (5000, 0), (7000, 300)]):
            lb = ColumnBatch.from_pydict(
                {
                    "k": rng.integers(0, 300, n_l).tolist(),
                    "p": rng.uniform(0, 100, n_l).tolist(),
                }
            )
            rb = ColumnBatch.from_pydict(
                {
                    "rk": sorted(rng.integers(0, 300, n_r).tolist()),
                    "w": rng.uniform(0, 1, n_r).tolist(),
                }
            )
            if n_r == 0:
                continue
            lk32 = exact_key32(lb.column("k").data)
            rk32 = exact_key32(rb.column("rk").data)
            lorder = np.argsort(lk32, kind="stable")
            work.append(
                (b, lb, rb, lk32[lorder], rk32, lorder, None,
                 lb.column("k").data, rb.column("rk").data)
            )
            expected[b] = _merge_join_batches(lb, rb, ["k"], ["rk"], False, True)
        tmp_session.set_conf(C.EXEC_TPU_ENABLED, True)
        try:
            parts = try_batched_plain_join(work, [], tmp_session)
        finally:
            tmp_session.set_conf(C.EXEC_TPU_ENABLED, False)
        assert parts is not None
        assert set(parts) == set(expected)
        for b in parts:
            assert parts[b].to_pydict() == expected[b].to_pydict()
