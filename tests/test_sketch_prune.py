"""Per-row-group sketch pruning: the sidecar store (bloom / value-list /
z-region on non-sort columns), the sketch-stage prune path, its lifecycle
under ingest (build → append → compact), and its guard rails.

The soundness bar is the same as PR-4 pruning: a sketch may only vote
*definite miss*, so a false positive keeps an extra group (slow) and the
only way to lose a row is a broken sketch — which `HYPERSPACE_PRUNE=verify`
must catch (the tamper test) and which honest sketches must never do (the
exhaustive no-false-drop sweep).
"""

import dataclasses
import glob
import json
import os

import numpy as np
import pytest

from hyperspace_tpu import CoveringIndexConfig, Hyperspace
from hyperspace_tpu import constants as C
from hyperspace_tpu.columnar import io as cio
from hyperspace_tpu.columnar.table import ColumnBatch
from hyperspace_tpu.exceptions import HyperspaceError
from hyperspace_tpu.models import covering
from hyperspace_tpu.models.dataskipping import sketch_store
from hyperspace_tpu.models.dataskipping.sketches import (
    BloomFilterSketch,
    ValueListSketch,
    ZRegionSketch,
    sketch_from_dict,
)
from hyperspace_tpu.plan import col
from hyperspace_tpu.plan import expr as X
from hyperspace_tpu.plan.nodes import FileScan
from hyperspace_tpu.telemetry.metrics import REGISTRY

N = 12_000
N_FILES = 4
BUCKETS = 2
RGS = 512  # patched row-group floor: many groups per bucket at test scale


def _events(i: int, n_per: int, base: int) -> dict:
    rng = np.random.default_rng(100 + i)
    k = np.arange(n_per, dtype=np.int64) + base
    return {
        "ev_k": k.tolist(),
        # high-NDV, clustered with the sort key (monotone id): bloom territory
        "ev_id": (k + 10_000_000).tolist(),
        # low-NDV, clustered (time-bucket shape): value-list territory
        "ev_cat": (k // (N // 8)).tolist(),
        # low-NDV strings, clustered
        "ev_s": [chr(ord("a") + int(v)) for v in (k // (N // 4))],
        # value column (z-region box material)
        "ev_v": rng.uniform(0, 100, n_per).tolist(),
    }


@pytest.fixture()
def sketch_env(tmp_session, tmp_path, monkeypatch):
    """Covering index on ev_k with sketch sidecars enabled, sized so every
    bucket holds several row groups (patched row-group floor)."""
    monkeypatch.setenv("HYPERSPACE_SKETCHES", "1")
    monkeypatch.setattr(covering, "INDEX_ROW_GROUP_SIZE", RGS)
    src = str(tmp_path / "events")
    per = N // N_FILES
    for i in range(N_FILES):
        cio.write_parquet(
            ColumnBatch.from_pydict(_events(i, per, i * per)),
            os.path.join(src, f"part-{i:02d}.parquet"),
        )
    tmp_session.set_conf(C.INDEX_NUM_BUCKETS, BUCKETS)
    hs = Hyperspace(tmp_session)
    hs.create_index(
        tmp_session.read.parquet(src),
        CoveringIndexConfig("ev_idx", ["ev_k"], ["ev_id", "ev_cat", "ev_s", "ev_v"]),
    )
    tmp_session.enable_hyperspace()
    return tmp_session, hs, src


def _bits(d: dict) -> dict:
    return {
        k: [x.hex() if isinstance(x, float) else x for x in v]
        for k, v in d.items()
    }


def _identical(q, monkeypatch):
    got = q().to_pydict()
    monkeypatch.setenv("HYPERSPACE_PRUNE", "0")
    expected = q().to_pydict()
    monkeypatch.delenv("HYPERSPACE_PRUNE")
    assert _bits(got) == _bits(expected)
    return got


def _prune_delta(fn):
    def snap():
        return {
            k: v
            for k, v in REGISTRY.snapshot().items()
            if k.startswith("pruning.") and isinstance(v, (int, float))
        }

    before = snap()
    out = fn()
    after = snap()
    return out, {k: after[k] - before.get(k, 0) for k in after}


def _sidecars(session, name="ev_idx"):
    root = os.path.join(session.warehouse_dir, "indexes", name)
    return sorted(glob.glob(os.path.join(root, "**", "_sketch.*.json"),
                            recursive=True))


# ---------------------------------------------------------------------------
# units: serialization, config parsing, the z-region sketch
# ---------------------------------------------------------------------------

class TestUnits:
    def test_enabled_kinds_parsing(self, monkeypatch):
        monkeypatch.delenv("HYPERSPACE_SKETCHES", raising=False)
        assert sketch_store.enabled_kinds() == frozenset()
        for raw in ("0", "off", "false", ""):
            monkeypatch.setenv("HYPERSPACE_SKETCHES", raw)
            assert not sketch_store.sketches_enabled()
        for raw in ("1", "all", "on"):
            monkeypatch.setenv("HYPERSPACE_SKETCHES", raw)
            assert sketch_store.enabled_kinds() == {
                "bloom", "valuelist", "zregion"
            }
        monkeypatch.setenv("HYPERSPACE_SKETCHES", "bloom, zregion")
        assert sketch_store.enabled_kinds() == {"bloom", "zregion"}
        monkeypatch.setenv("HYPERSPACE_SKETCHES", "bloom,typo")
        assert sketch_store.enabled_kinds() == {"bloom"}

    def test_sidecar_roundtrip(self, tmp_path, monkeypatch):
        monkeypatch.setenv("HYPERSPACE_SKETCHES", "1")
        batch = ColumnBatch.from_pydict(_events(0, 4096, 0))
        path = str(tmp_path / "part-0-b00001.parquet")
        cio.write_index_file(batch, path, row_group_size=512)
        assert sketch_store.maybe_write_sidecar(batch, path, 512, ["ev_k"])
        sc = sketch_store.load_sidecar(path)
        assert sc is not None and sc.num_row_groups == 8
        kinds = sorted(type(s).__name__ for s in sc.sketches)
        assert "ZRegionSketch" in kinds
        assert "BloomFilterSketch" in kinds  # ev_id: high NDV
        assert "ValueListSketch" in kinds  # ev_cat / ev_s: low NDV
        # NDV/dictionary stats recorded per eligible (non-key) column
        assert sc.ndv["ev_id"] == 4096 and sc.ndv["ev_s"] <= 4
        assert "ev_k" not in sc.ndv
        # masks vote per group: ev_id is monotone, one group holds 10017
        mask = sc.keep_mask([X.Eq(X.Col("ev_id"), X.Lit(10_000_000 + 17))])
        assert mask is not None and mask.sum() == 1 and bool(mask[0])

    def test_zregion_sketch(self):
        z = ZRegionSketch(["a", "b"])
        assert sketch_from_dict(z.to_dict()) == z
        batch = ColumnBatch.from_pydict(
            {"a": [1, 2, 10, 20], "b": [5.0, 6.0, 50.0, 60.0]}
        )
        aggs = z.aggregate_batch(batch, np.array([0, 0, 1, 1]), 2)
        table = ColumnBatch(aggs)
        # Eq/range/In conversions intersect the query box per column
        assert z.convert_predicate(X.Eq(X.Col("a"), X.Lit(2)))(table).tolist() \
            == [True, False]
        assert z.convert_predicate(X.Ge(X.Col("b"), X.Lit(49.0)))(table).tolist() \
            == [False, True]
        assert z.convert_predicate(X.In(X.Col("a"), [0, 15]))(table).tolist() \
            == [False, True]
        # strings cannot be bounded by a numeric box
        assert z.convert_predicate(X.Eq(X.Col("a"), X.Lit("x"))) is None
        # single-column aggregate entry point is a DS-index contract it
        # deliberately does not implement
        with pytest.raises(HyperspaceError):
            z.aggregate(batch.column("a"), np.array([0, 0, 1, 1]), 2)

    def test_stale_data_size_ignored(self, tmp_path, monkeypatch):
        monkeypatch.setenv("HYPERSPACE_SKETCHES", "1")
        batch = ColumnBatch.from_pydict(_events(0, 1024, 0))
        path = str(tmp_path / "part-0-b00001.parquet")
        cio.write_index_file(batch, path, row_group_size=512)
        assert sketch_store.maybe_write_sidecar(batch, path, 512, ["ev_k"])
        side = sketch_store.sidecar_path(path)
        raw = json.load(open(side))
        raw["data_size"] = raw["data_size"] + 1  # simulate a bypassed rewrite
        json.dump(raw, open(side, "w"))
        assert sketch_store.load_sidecar(path) is None

    def test_malformed_sidecar_ignored(self, tmp_path, monkeypatch):
        monkeypatch.setenv("HYPERSPACE_SKETCHES", "1")
        batch = ColumnBatch.from_pydict(_events(0, 1024, 0))
        path = str(tmp_path / "part-0-b00001.parquet")
        cio.write_index_file(batch, path, row_group_size=512)
        assert sketch_store.maybe_write_sidecar(batch, path, 512, ["ev_k"])
        with open(sketch_store.sidecar_path(path), "w") as f:
            f.write("{not json")
        assert sketch_store.load_sidecar(path) is None

    def test_disabled_writes_nothing(self, tmp_path, monkeypatch):
        monkeypatch.delenv("HYPERSPACE_SKETCHES", raising=False)
        batch = ColumnBatch.from_pydict(_events(0, 1024, 0))
        path = str(tmp_path / "part-0-b00001.parquet")
        cio.write_index_file(batch, path, row_group_size=512)
        assert not sketch_store.maybe_write_sidecar(batch, path, 512, ["ev_k"])
        assert not os.path.exists(sketch_store.sidecar_path(path))

    def test_cache_consistency(self):
        assert sketch_store._SIDECAR_CACHE.check_consistency()


# ---------------------------------------------------------------------------
# end to end: non-sort-column skipping, bit-identity, lifecycle
# ---------------------------------------------------------------------------

class TestEndToEnd:
    def test_eq_on_nonsort_column_skips(self, sketch_env, monkeypatch):
        session, _hs, src = sketch_env
        assert len(_sidecars(session)) > 0
        key = 10_000_000 + N // 2 + 17
        q = lambda: (
            session.read.parquet(src)
            .filter(col("ev_id") == key)
            .select("ev_k", "ev_id", "ev_cat")
        )
        # the relaxed FilterColumnFilter admits the index although ev_k is
        # unconstrained, and apply_pruning routes the conjunct to sketches
        plan = q().optimized_plan()
        scan = [n for n in plan.preorder() if isinstance(n, FileScan)][0]
        assert scan.index_info is not None
        assert scan.prune_spec is not None and scan.prune_spec.sketch_conjuncts
        (_, delta) = _prune_delta(lambda: _identical(q, monkeypatch))
        assert delta.get("pruning.sketch.rowgroups_skipped", 0) > 0
        assert delta["pruning.rowgroups_kept"] < delta["pruning.rowgroups_total"]
        assert delta["pruning.bytes_skipped"] > 0

    def test_in_on_low_ndv_column_skips(self, sketch_env, monkeypatch):
        session, _hs, src = sketch_env
        q = lambda: (
            session.read.parquet(src)
            .filter(col("ev_cat").isin([1, 6]))
            .select("ev_k", "ev_cat")
        )
        got, delta = _prune_delta(lambda: _identical(q, monkeypatch))
        assert set(got["ev_cat"]) == {1, 6}
        assert delta.get("pruning.sketch.rowgroups_skipped", 0) > 0

    def test_string_eq_skips(self, sketch_env, monkeypatch):
        session, _hs, src = sketch_env
        q = lambda: (
            session.read.parquet(src)
            .filter(col("ev_s") == "c")
            .select("ev_k", "ev_s")
        )
        got, delta = _prune_delta(lambda: _identical(q, monkeypatch))
        assert set(got["ev_s"]) == {"c"}
        assert delta.get("pruning.sketch.rowgroups_skipped", 0) > 0

    def test_zregion_range_on_nonsort_column(self, sketch_env, monkeypatch):
        session, _hs, src = sketch_env
        lo = 10_000_000 + N // 4
        q = lambda: (
            session.read.parquet(src)
            .filter((col("ev_id") >= lo) & (col("ev_id") < lo + 500))
            .select("ev_k", "ev_id")
        )
        got, delta = _prune_delta(lambda: _identical(q, monkeypatch))
        assert len(got["ev_id"]) == 500
        assert delta.get("pruning.sketch.rowgroups_skipped", 0) > 0

    def test_combined_with_minmax_stage(self, sketch_env, monkeypatch):
        """Sort-column range (footer stats) AND non-sort Eq (sketches)
        intersect; streamed-vs-monolithic identity rides _identical."""
        session, _hs, src = sketch_env
        q = lambda: (
            session.read.parquet(src)
            .filter((col("ev_k") >= N // 4) & (col("ev_k") < N // 2)
                    & (col("ev_cat") == 2))
            .select("ev_k", "ev_cat")
        )
        _, delta = _prune_delta(lambda: _identical(q, monkeypatch))
        assert delta["pruning.rowgroups_kept"] < delta["pruning.rowgroups_total"]

    def test_no_false_drop_sweep(self, sketch_env, monkeypatch):
        """Bloom may only skip on a definite miss: every present key must
        come back (vs PRUNE=0), absent keys must return empty — swept over
        a sample of present and absent ev_id values."""
        session, _hs, src = sketch_env
        rng = np.random.default_rng(7)
        present = (10_000_000 + rng.integers(0, N, 12)).tolist()
        absent = (20_000_000 + rng.integers(0, N, 4)).tolist()
        for key in present + absent:
            q = lambda: (
                session.read.parquet(src)
                .filter(col("ev_id") == int(key))
                .select("ev_k", "ev_id")
            )
            got = _identical(q, monkeypatch)
            if key in present:
                assert got["ev_id"] == [key], key
            else:
                assert got["ev_id"] == [], key

    def test_lifecycle_append_append_compact(self, sketch_env, monkeypatch):
        """Skipping keeps working on a live index: two hs.append batches
        publish delta runs WITH sidecars, compaction merges them into
        re-sketched output — every stage bit-identical to PRUNE=0."""
        session, hs, src = sketch_env
        per = N // N_FILES
        q = lambda: (
            session.read.parquet(src)
            .filter(col("ev_cat").isin([3]))
            .select("ev_k", "ev_cat")
        )
        baseline_sidecars = len(_sidecars(session))
        for j in range(2):
            base = N + j * per
            cio.write_parquet(
                ColumnBatch.from_pydict(_events(10 + j, per, base)),
                os.path.join(src, f"part-a{j}.parquet"),
            )
            hs.append("ev_idx", session.read.parquet(src))
            got, delta = _prune_delta(lambda: _identical(q, monkeypatch))
            assert set(got["ev_cat"]) == {3}
            assert delta.get("pruning.sketch.rowgroups_skipped", 0) > 0, j
        # delta runs carry their own sidecars
        assert len(_sidecars(session)) > baseline_sidecars
        hs.compact_index("ev_idx", min_runs=2)
        got, delta = _prune_delta(lambda: _identical(q, monkeypatch))
        assert set(got["ev_cat"]) == {3}
        assert delta.get("pruning.sketch.rowgroups_skipped", 0) > 0
        # compacted output was re-sketched (fresh sidecars in the new version)
        latest = sorted(_sidecars(session))[-1]
        assert "_sketch." in latest

    def test_tampered_sketch_raises_under_verify(self, sketch_env, monkeypatch):
        """A corrupted bloom that votes 'definitely absent' for a present
        key is a false DROP — exactly what HYPERSPACE_PRUNE=verify exists
        to catch."""
        import base64

        session, _hs, src = sketch_env
        key = 10_000_000 + N // 2 + 17
        q = lambda: (
            session.read.parquet(src)
            .filter(col("ev_id") == key)
            .select("ev_k", "ev_id")
        )
        assert _identical(q, monkeypatch)["ev_id"] == [key]
        # zero every bloom bitset in every sidecar: all probes miss
        for side in _sidecars(session):
            raw = json.load(open(side))
            changed = False
            for name, cold in raw["columns"].items():
                if not name.endswith("__bloom"):
                    continue
                vals = []
                for blob in cold["values"]:
                    bf = json.loads(blob)
                    n_bytes = len(base64.b64decode(bf["bitset"]))
                    bf["bitset"] = base64.b64encode(b"\x00" * n_bytes).decode()
                    vals.append(json.dumps(bf))
                cold["values"] = vals
                changed = True
            if changed:
                json.dump(raw, open(side, "w"))
        sketch_store._SIDECAR_CACHE.clear()
        monkeypatch.setenv("HYPERSPACE_PRUNE", "verify")
        with pytest.raises(HyperspaceError, match="verify mismatch"):
            q().collect()

    def test_verify_mode_clean(self, sketch_env, monkeypatch):
        session, _hs, src = sketch_env
        monkeypatch.setenv("HYPERSPACE_PRUNE", "verify")
        _, delta = _prune_delta(
            lambda: session.read.parquet(src)
            .filter(col("ev_id") == 10_000_000 + 33)
            .select("ev_k", "ev_id")
            .collect()
        )
        assert delta.get("pruning.verified", 0) >= 1

    def test_disabled_by_default(self, tmp_session, tmp_path, monkeypatch):
        monkeypatch.delenv("HYPERSPACE_SKETCHES", raising=False)
        src = str(tmp_path / "events")
        cio.write_parquet(
            ColumnBatch.from_pydict(_events(0, 2048, 0)),
            os.path.join(src, "part-00.parquet"),
        )
        tmp_session.set_conf(C.INDEX_NUM_BUCKETS, BUCKETS)
        hs = Hyperspace(tmp_session)
        hs.create_index(
            tmp_session.read.parquet(src),
            CoveringIndexConfig("ev_idx", ["ev_k"], ["ev_id", "ev_cat"]),
        )
        tmp_session.enable_hyperspace()
        assert _sidecars(tmp_session) == []
        # without sketches the leading-column rule stands: a non-sort Eq
        # stays on the raw scan, and no spec carries sketch conjuncts
        plan = (
            tmp_session.read.parquet(src)
            .filter(col("ev_id") == 10_000_010)
            .select("ev_k")
            .optimized_plan()
        )
        scans = [n for n in plan.preorder() if isinstance(n, FileScan)]
        assert all(s.index_info is None for s in scans)
        assert all(
            s.prune_spec is None or not s.prune_spec.sketch_conjuncts
            for s in scans
        )


# ---------------------------------------------------------------------------
# planner/verifier/estimator integration
# ---------------------------------------------------------------------------

class TestIntegration:
    def _entry(self, session, name="ev_idx"):
        from hyperspace_tpu.index_manager import index_manager_for

        entry = index_manager_for(session).get_index(name)
        assert entry is not None
        return entry

    def test_ranker_ndv_feed(self, sketch_env, monkeypatch):
        from hyperspace_tpu.plan.pruning import estimate_scan_fraction

        session, _hs, _src = sketch_env
        entry = self._entry(session)
        cond = X.Eq(X.Col("ev_id"), X.Lit(10_000_033))
        frac_on = estimate_scan_fraction(cond, entry)
        assert frac_on < 1.0  # NDV stats price the sketch stage
        monkeypatch.delenv("HYPERSPACE_SKETCHES")
        assert estimate_scan_fraction(cond, entry) == 1.0

    def test_estimator_observes_sketch_rowgroups(self, sketch_env, monkeypatch):
        from hyperspace_tpu.telemetry.plan_stats import ACCURACY

        session, _hs, src = sketch_env
        (
            session.read.parquet(src)
            .filter(col("ev_id") == 10_000_042)
            .select("ev_k")
            .collect()
        )
        snap = ACCURACY.snapshot()
        assert snap["by_estimator"].get("sketch_rowgroups", 0) >= 1

    def test_feedback_corrects_sketch_fraction(self, sketch_env, monkeypatch):
        from hyperspace_tpu.plan.pruning import estimate_scan_fraction
        from hyperspace_tpu.telemetry.plan_stats import ACCURACY

        session, _hs, src = sketch_env
        entry = self._entry(session)
        cond = X.Eq(X.Col("ev_id"), X.Lit(10_000_033))
        ACCURACY.reset_for_testing()  # process-wide; isolate the window
        base = estimate_scan_fraction(cond, entry)
        # plant a consistent 4x under-estimate for this (index, shape)
        shape = "ev_id:eq"
        for _ in range(8):
            ACCURACY.observe("sketch_rowgroups", 1, 4,
                             index=entry.name, shape=shape)
        monkeypatch.setenv("HYPERSPACE_ESTIMATOR_FEEDBACK", "1")
        corrected = estimate_scan_fraction(cond, entry)
        assert corrected > base  # the ledger pushed the estimate up
        monkeypatch.delenv("HYPERSPACE_ESTIMATOR_FEEDBACK")
        assert estimate_scan_fraction(cond, entry) == base  # off = identical

    def test_verifier_rejects_undeclared_sketch_conjunct(self, sketch_env):
        from hyperspace_tpu.staticcheck.plan_verifier import (
            PRUNE_SKETCH_NOT_DECLARED,
            PlanInvariantError,
            verify_plan,
        )

        session, _hs, src = sketch_env
        plan = (
            session.read.parquet(src)
            .filter(col("ev_id") == 10_000_033)
            .select("ev_k", "ev_id")
            .optimized_plan()
        )
        scan = [n for n in plan.preorder() if isinstance(n, FileScan)][0]
        assert scan.prune_spec.sketch_conjuncts
        # the honest plan verifies clean
        assert verify_plan(plan, session) == []
        # strip the declared capability: the same sketch conjuncts are now
        # a prune decision with no evidence source — must be rejected
        bad_spec = dataclasses.replace(scan.prune_spec, sketch_capability=())
        bad = plan.transform_up(
            lambda n: n.copy(prune_spec=bad_spec)
            if isinstance(n, FileScan) and n.prune_spec is not None
            else n
        )
        with pytest.raises(PlanInvariantError) as ei:
            verify_plan(bad, session)
        assert ei.value.code == PRUNE_SKETCH_NOT_DECLARED

    def test_verify_plan_env_clean_on_sketch_queries(self, sketch_env, monkeypatch):
        session, _hs, src = sketch_env
        monkeypatch.setenv("HYPERSPACE_VERIFY_PLAN", "1")
        q = lambda: (
            session.read.parquet(src)
            .filter(col("ev_cat") == 5)
            .select("ev_k", "ev_cat")
        )
        got = _identical(q, monkeypatch)
        assert set(got["ev_cat"]) == {5}
