"""Query-level tracing, metrics registry, and profile artifacts.

Covers the observability acceptance contract: a fixed TPC-H query traced
twice (warm) yields the same deterministic span tree — rule spans → exec
spans → kernel spans with RpcMeter deltas; non-applied rules carry
structured reject reasons; with tracing disabled the instrumented paths add
no spans and results are bit-identical; the JSONL sink round-trips; and the
metrics registry is thread-safe under concurrent queries.
"""

import json
import os
import threading

import pytest

from hyperspace_tpu import Hyperspace
from hyperspace_tpu import constants as C
from hyperspace_tpu.benchmark import TPCH_QUERIES, generate_tpch, tpch_indexes
from hyperspace_tpu.telemetry import trace
from hyperspace_tpu.telemetry.metrics import MetricsRegistry, REGISTRY
from hyperspace_tpu.telemetry.trace import (
    JsonlTraceSink,
    read_jsonl_trace,
    profile_string,
)


@pytest.fixture(scope="module")
def tpch_env(tmp_path_factory):
    root = str(tmp_path_factory.mktemp("tpch_trace"))
    from hyperspace_tpu.session import HyperspaceSession

    session = HyperspaceSession(warehouse_dir=root)
    generate_tpch(root, rows_lineitem=6_000, seed=3)
    hs = Hyperspace(session)
    tpch_indexes(session, hs, root)
    return session, hs, root


@pytest.fixture(autouse=True)
def _tracing_off_between_tests():
    yield
    trace.disable()
    trace.drain_roots()


def _names(span):
    return (span.name, tuple(_names(c) for c in span.children))


def _walk(span):
    yield span
    for c in span.children:
        yield from _walk(c)


def _run_q6(session, root):
    return TPCH_QUERIES["q6"](session, root).to_pydict()


class TestSpanTree:
    def test_deterministic_tree_rule_exec_kernel(self, tpch_env):
        session, hs, root = tpch_env
        session.enable_hyperspace()
        session.set_conf(C.EXEC_TPU_ENABLED, True)
        try:
            _run_q6(session, root)  # warm: compiles + populates caches
            with trace.capture() as cap1:
                _run_q6(session, root)
            with trace.capture() as cap2:
                _run_q6(session, root)
        finally:
            session.set_conf(C.EXEC_TPU_ENABLED, False)
            session.disable_hyperspace()

        (q1,) = cap1.roots
        (q2,) = cap2.roots
        assert q1.name == "query"
        # warm runs produce the SAME tree, run to run
        assert _names(q1) == _names(q2)

        spans = list(_walk(q1))
        rule_spans = [s for s in spans if s.name.startswith("rule:")]
        exec_spans = [s for s in spans if s.name.startswith("exec:")]
        kernel_spans = [s for s in spans if s.name.startswith("kernel:")]
        assert rule_spans and exec_spans and kernel_spans

        # at least one rule applied (q6 rides an index), with an index_usage
        # event carrying the chosen index name
        applied = [s for s in rule_spans if s.attrs.get("applied")]
        assert applied
        assert any(
            ev.get("event") == "index_usage" and ev.get("index")
            for s in applied
            for ev in s.attrs.get("events", [])
        )

        # every NON-applied rule span carries a structured reject reason
        for s in rule_spans:
            if s.name == "rule:ApplyHyperspace" or s.attrs.get("applied"):
                continue
            rejects = [
                ev for ev in s.attrs.get("events", []) if ev.get("event") == "reject"
            ]
            assert rejects, f"{s.name} not applied but carries no reject reason"
            assert all(r.get("code") for r in rejects)

        # kernel spans carry RpcMeter deltas: the dispatch itself at minimum
        assert any(s.rpc["dispatches"] >= 1 for s in kernel_spans)
        assert all(set(s.rpc) == {
            "dispatches", "fetches", "uploads", "upload_bytes", "fetch_bytes"
        } for s in kernel_spans)

    def test_disabled_emits_nothing_and_results_identical(self, tpch_env):
        session, hs, root = tpch_env
        session.enable_hyperspace()
        try:
            assert not trace.enabled()
            trace.drain_roots()
            plain = _run_q6(session, root)
            assert trace.drain_roots() == []
            assert trace.current_span() is None
            with trace.capture():
                traced = _run_q6(session, root)
            trace.drain_roots()  # clear the traced run's root
            after = _run_q6(session, root)
            assert trace.drain_roots() == []
        finally:
            session.disable_hyperspace()
        # bit-identical results with tracing on, off before, and off after
        assert plain == traced == after

    def test_span_noop_is_shared_singleton(self):
        assert not trace.enabled()
        s1 = trace.span("anything", a=1)
        s2 = trace.span("else")
        assert s1 is s2 is trace.NOOP_SPAN

    def test_profile_string_renders(self, tpch_env):
        session, hs, root = tpch_env
        session.enable_hyperspace()
        try:
            out = hs.profile(TPCH_QUERIES["q6"](session, root))
        finally:
            session.disable_hyperspace()
        assert "query" in out and "rule:" in out and "exec:" in out
        assert "metrics:" in out


class TestJsonlRoundTrip:
    def test_round_trip(self, tpch_env, tmp_path):
        session, hs, root = tpch_env
        path = str(tmp_path / "trace.jsonl")
        session.enable_hyperspace()
        sink = JsonlTraceSink(path)
        trace.enable(sink)
        try:
            _run_q6(session, root)
        finally:
            trace.disable()
            session.disable_hyperspace()

        mem_roots = trace.drain_roots()
        file_roots = read_jsonl_trace(path)
        assert len(file_roots) == len(mem_roots) == 1

        def names_mem(s):
            return (s.name, tuple(names_mem(c) for c in s.children))

        def names_file(d):
            return (d["name"], tuple(names_file(c) for c in d["children"]))

        assert names_file(file_roots[0]) == names_mem(mem_roots[0])
        # attrs and rpc deltas survive the round trip
        assert file_roots[0]["attrs"]["rows_out"] == mem_roots[0].attrs["rows_out"]
        assert file_roots[0]["rpc"] == mem_roots[0].rpc
        # every line is standalone-parseable JSON
        with open(path, encoding="utf-8") as f:
            for line in f:
                json.loads(line)
        # the renderer accepts file dicts too
        assert "query" in profile_string(file_roots, include_metrics=False)


class TestMetricsRegistry:
    def test_counter_gauge_histogram(self):
        reg = MetricsRegistry()
        reg.counter("c").inc()
        reg.counter("c").inc(4)
        assert reg.counter("c").value == 5
        reg.gauge("g").set(2.5)
        assert reg.gauge("g").value == 2.5
        h = reg.histogram("h")
        for v in (0.2, 3.0, 700.0):
            h.observe(v)
        s = h.summary()
        assert s["count"] == 3 and s["min"] == 0.2 and s["max"] == 700.0
        snap = reg.snapshot()
        assert snap["c"] == 5 and snap["h"]["count"] == 3
        reg.reset()
        assert reg.counter("c").value == 0

    def test_type_conflict_raises(self):
        reg = MetricsRegistry()
        reg.counter("x")
        with pytest.raises(TypeError):
            reg.gauge("x")

    def test_thread_safety(self):
        reg = MetricsRegistry()
        n_threads, per = 8, 5_000
        barrier = threading.Barrier(n_threads)

        def work():
            barrier.wait()
            for i in range(per):
                reg.counter("hits").inc()
                reg.histogram("lat").observe(i % 7)

        threads = [threading.Thread(target=work) for _ in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert reg.counter("hits").value == n_threads * per
        assert reg.histogram("lat").summary()["count"] == n_threads * per

    def test_concurrent_traced_queries(self, tpch_env):
        """Tracing + registry under concurrent query threads: spans land on
        per-thread stacks (no cross-thread nesting) and nothing crashes."""
        session, hs, root = tpch_env
        session.enable_hyperspace()
        errors = []
        with trace.capture() as cap:
            def work():
                try:
                    _run_q6(session, root)
                except Exception as e:  # pragma: no cover
                    errors.append(e)

            threads = [threading.Thread(target=work) for _ in range(4)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        session.disable_hyperspace()
        assert not errors
        roots = cap.roots
        assert len(roots) == 4
        assert all(r.name == "query" for r in roots)


class TestRpcMeter:
    def test_measure_context_manager(self):
        from hyperspace_tpu.utils.rpc_meter import METER

        with METER.measure() as m:
            METER.record_dispatch()
            METER.record_upload(123)
        assert m.delta["dispatches"] == 1
        assert m.delta["uploads"] == 1
        assert m.delta["upload_bytes"] == 123

    def test_delta_since(self):
        from hyperspace_tpu.utils.rpc_meter import METER

        before = METER.snapshot()
        METER.record_fetch(50, n=2)
        d = METER.delta_since(before)
        assert d["fetches"] == 2 and d["fetch_bytes"] == 50


class TestUsageEvents:
    def test_uniform_usage_event_on_rewrite(self, tpch_env):
        """Every successful rewrite emits HyperspaceIndexUsageEvent with the
        chosen index name (uniform across all rules)."""
        import importlib

        from hyperspace_tpu.telemetry.logger import clear_event_logger_cache

        session, hs, root = tpch_env
        clear_event_logger_cache(session)
        session.set_conf(
            C.EVENT_LOGGER_CLASS, "tests.test_telemetry_trace.CapturingLogger"
        )
        canonical = importlib.import_module(
            "tests.test_telemetry_trace"
        ).CapturingLogger
        canonical.events.clear()
        session.enable_hyperspace()
        try:
            for name in ("q3", "q6"):
                TPCH_QUERIES[name](session, root).collect()
        finally:
            session.disable_hyperspace()
            clear_event_logger_cache(session)
            session.unset_conf(C.EVENT_LOGGER_CLASS)
        usage = [
            e for e in canonical.events
            if type(e).__name__ == "HyperspaceIndexUsageEvent"
        ]
        assert usage, "rewrites must emit usage events"
        rules_seen = {e.rule for e in usage}
        assert "JoinIndexRule" in rules_seen or "FilterIndexRule" in rules_seen
        for e in usage:
            assert e.index_names and all(e.index_names), e
            assert e.rule, e


class CapturingLogger:
    events: list = []

    def log_event(self, event):
        CapturingLogger.events.append(event)


class TestEnvForceEnable:
    def test_env_flag_enables_tracing(self, tmp_path):
        """HYPERSPACE_TRACE=1 (the verify-flow switch) must enable tracing at
        import in a fresh interpreter and write spans to the file sink."""
        import subprocess
        import sys

        out_file = str(tmp_path / "t.jsonl")
        env = dict(os.environ)
        env.update({
            "HYPERSPACE_TRACE": "1",
            "HYPERSPACE_TRACE_FILE": out_file,
            "JAX_PLATFORMS": "cpu",
        })
        code = (
            "from hyperspace_tpu.telemetry import trace\n"
            "assert trace.enabled()\n"
            "with trace.span('probe'):\n"
            "    pass\n"
        )
        r = subprocess.run(
            [sys.executable, "-c", code], env=env, capture_output=True, text=True,
            timeout=120,
        )
        assert r.returncode == 0, r.stderr
        roots = read_jsonl_trace(out_file)
        assert [s["name"] for s in roots] == ["probe"]
