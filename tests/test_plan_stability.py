"""Golden-file plan-stability tests.

Reference parity: goldstandard/PlanStabilitySuite.scala:83-289 — render a
normalized plan string for fixed queries and string-compare against approved
files; regenerate with GENERATE_GOLDEN_FILES=1.

Normalization strips run-dependent details (absolute paths, file counts per
se stay — the fixture is deterministic — and log versions are stable).
"""

import os
import re

import pytest

from hyperspace_tpu import CoveringIndexConfig, DataSkippingIndexConfig, Hyperspace, MinMaxSketch
from hyperspace_tpu.columnar import io as cio
from hyperspace_tpu.columnar.table import ColumnBatch
from hyperspace_tpu.plan import col, lit, Count, Sum

GOLDEN_DIR = os.path.join(os.path.dirname(__file__), "approved_plans")
GENERATE = os.environ.get("GENERATE_GOLDEN_FILES") == "1"


def normalize(plan_str: str, tmp: str) -> str:
    s = plan_str.replace(tmp, "<ROOT>")
    s = re.sub(r"/tmp/[^/ ]+", "<TMP>", s)
    return s + "\n"


def check(name: str, plan_str: str, tmp: str) -> None:
    os.makedirs(GOLDEN_DIR, exist_ok=True)
    path = os.path.join(GOLDEN_DIR, f"{name}.txt")
    rendered = normalize(plan_str, tmp)
    if GENERATE:
        with open(path, "w") as f:
            f.write(rendered)
        return
    assert os.path.exists(path), (
        f"No approved plan for {name!r}; generate it deliberately with "
        f"GENERATE_GOLDEN_FILES=1 after reviewing the plan:\n{rendered}"
    )
    with open(path) as f:
        approved = f.read()
    assert rendered == approved, (
        f"Plan for {name!r} changed; regenerate with GENERATE_GOLDEN_FILES=1 "
        f"if intended.\n--- approved ---\n{approved}\n--- actual ---\n{rendered}"
    )


@pytest.fixture()
def env(tmp_session, tmp_path):
    # deterministic fixture (fixed sizes, no randomness)
    n = 100
    left = {
        "k": [i % 10 for i in range(n)],
        "a": [float(i) for i in range(n)],
        "b": [i * 2 for i in range(n)],
    }
    right = {"rk": list(range(10)), "c": [float(i) for i in range(10)]}
    cio.write_parquet(ColumnBatch.from_pydict(left), str(tmp_path / "L" / "l.parquet"))
    cio.write_parquet(ColumnBatch.from_pydict(right), str(tmp_path / "R" / "r.parquet"))
    hs = Hyperspace(tmp_session)
    ldf = tmp_session.read.parquet(str(tmp_path / "L"))
    rdf = tmp_session.read.parquet(str(tmp_path / "R"))
    hs.create_index(ldf, CoveringIndexConfig("ci_k", ["k"], ["a"]))
    hs.create_index(rdf, CoveringIndexConfig("ci_rk", ["rk"], ["c"]))
    tmp_session.enable_hyperspace()
    return tmp_session, tmp_path


class TestPlanStability:
    def test_q_filter(self, env):
        session, tmp = env
        df = session.read.parquet(str(tmp / "L"))
        q = df.filter(col("k") == 3).select("k", "a")
        check("filter_index_scan", q.optimized_plan().pretty(), str(tmp))

    def test_q_join(self, env):
        session, tmp = env
        l = session.read.parquet(str(tmp / "L"))
        r = session.read.parquet(str(tmp / "R"))
        q = l.select("k", "a").join(r.select("rk", "c"), col("k") == col("rk"))
        check("join_index_scan", q.optimized_plan().pretty(), str(tmp))

    def test_q_agg(self, env):
        session, tmp = env
        df = session.read.parquet(str(tmp / "L"))
        q = (
            df.filter(col("k") == 3)
            .select("k", "a")
            .agg(Sum(col("a")).alias("s"), Count(lit(1)).alias("n"))
        )
        check("filter_agg", q.optimized_plan().pretty(), str(tmp))

    def test_q_no_index(self, env):
        session, tmp = env
        df = session.read.parquet(str(tmp / "L"))
        # needs column b: no index covers it -> plan unchanged
        q = df.filter(col("k") == 3).select("k", "b")
        check("filter_no_index", q.optimized_plan().pretty(), str(tmp))


@pytest.fixture(scope="module")
def tpch_golden_env(tmp_path_factory):
    """Deterministic tiny TPC-H with the full BASELINE index set (covering,
    z-order, data-skipping) — the golden corpus analogue of the reference's
    TPC-DS approved plans (goldstandard/PlanStabilitySuite.scala:83-289,
    src/test/resources/tpcds/)."""
    from hyperspace_tpu.benchmark import generate_tpch, tpch_indexes
    from hyperspace_tpu.session import HyperspaceSession

    root = str(tmp_path_factory.mktemp("tpch_golden"))
    session = HyperspaceSession(warehouse_dir=root)
    generate_tpch(root, rows_lineitem=2000, seed=7)
    hs = Hyperspace(session)
    tpch_indexes(session, hs, root)
    hs.create_index(
        session.read.parquet(os.path.join(root, "lineitem")),
        DataSkippingIndexConfig("li_ds_minmax", [MinMaxSketch("l_shipdate")]),
    )
    session.enable_hyperspace()
    return session, hs, root


class TestTPCHPlanStability:
    """Approved optimized plans for the TPC-H query set, one per index kind
    in play: Q6 (z-order covering), Q3 (join indexes + fused aggregate
    shape), Q17 (join index + per-part aggregate), Q1 (no covering index
    applies; DS sketch candidacy shows in whyNot)."""

    @pytest.mark.parametrize("name", ["q1", "q3", "q6", "q10", "q17", "q18"])
    def test_query_plan(self, tpch_golden_env, name):
        from hyperspace_tpu.benchmark import TPCH_QUERIES

        session, hs, root = tpch_golden_env
        q = TPCH_QUERIES[name](session, root)
        check(f"tpch_{name}", q.optimized_plan().pretty(), root)

    def test_q6_explain(self, tpch_golden_env):
        from hyperspace_tpu.benchmark import TPCH_QUERIES
        from hyperspace_tpu import constants as C

        session, hs, root = tpch_golden_env
        session.set_conf(C.DISPLAY_MODE, "plaintext")
        q = TPCH_QUERIES["q6"](session, root)
        check("tpch_q6_explain", hs.explain(q, verbose=True), root)

    def test_q3_why_not(self, tpch_golden_env):
        from hyperspace_tpu.benchmark import TPCH_QUERIES

        session, hs, root = tpch_golden_env
        q = TPCH_QUERIES["q3"](session, root)
        check("tpch_q3_whynot", hs.why_not(q, extended=True), root)

    def test_q10_explain(self, tpch_golden_env):
        """Verbose explain over the join+topk shape: both rewritten sides
        highlight, and the applicable-index table lists the join rule."""
        from hyperspace_tpu.benchmark import TPCH_QUERIES
        from hyperspace_tpu import constants as C

        session, hs, root = tpch_golden_env
        session.set_conf(C.DISPLAY_MODE, "plaintext")
        q = TPCH_QUERIES["q10"](session, root)
        check("tpch_q10_explain", hs.explain(q, verbose=True), root)

    def test_q18_why_not(self, tpch_golden_env):
        """Non-extended whyNot over the HAVING-over-aggregate join: the
        COL_SCHEMA_MISMATCH noise rows stay hidden with a count."""
        from hyperspace_tpu.benchmark import TPCH_QUERIES

        session, hs, root = tpch_golden_env
        q = TPCH_QUERIES["q18"](session, root)
        check("tpch_q18_whynot", hs.why_not(q), root)


class TestKernelJaxprStability:
    """Golden over the REWRITTEN COMPUTE IR, not just the logical plan
    (SURVEY §4's implication (b): golden-file tests over the jaxpr/HLO of
    the lowered kernels): the flagship Q6 fused kernel's jaxpr must not
    drift unnoticed — fusion regressions show up as structural changes
    here before they show up as latency."""

    def test_q6_fused_kernel_jaxpr(self, tmp_path):
        import jax
        import numpy as np

        from __graft_entry__ import entry

        kernel, (cols, mask) = entry()
        jaxpr = jax.make_jaxpr(kernel)(cols, mask)
        rendered = str(jaxpr)
        # normalize: drop memory-space/layout annotations that vary by
        # backend; keep the op structure
        rendered = re.sub(r"memory_kind=[a-z_]+", "memory_kind=<mk>", rendered)
        check("q6_fused_kernel_jaxpr", rendered, str(tmp_path))


@pytest.fixture(scope="module")
def tpcds_golden_env(tmp_path_factory):
    """The reference's goldstandard corpus is TPC-DS with exactly q1 enabled
    (goldstandard/TPCDSBase.scala:41); mirror it: q1-relevant tables, the
    q1 index set, approved plans for the q1 core shapes."""
    from hyperspace_tpu.benchmark.tpcds import generate_tpcds, tpcds_indexes
    from hyperspace_tpu.session import HyperspaceSession

    root = str(tmp_path_factory.mktemp("tpcds_golden"))
    session = HyperspaceSession(warehouse_dir=root)
    generate_tpcds(root, rows_store_returns=5_000, seed=3)
    hs = Hyperspace(session)
    tpcds_indexes(session, hs, root)
    session.enable_hyperspace()
    return session, hs, root


class TestTPCDSPlanStability:
    def test_q1_ctr_plan(self, tpcds_golden_env):
        from hyperspace_tpu.benchmark.tpcds import q1_customer_total_return

        session, hs, root = tpcds_golden_env
        q = q1_customer_total_return(session, root)
        check("tpcds_q1_ctr", q.optimized_plan().pretty(), root)

    def test_q1_store_avg_plan(self, tpcds_golden_env):
        from hyperspace_tpu.benchmark.tpcds import q1_store_avg

        session, hs, root = tpcds_golden_env
        q = q1_store_avg(session, root)
        check("tpcds_q1_store_avg", q.optimized_plan().pretty(), root)

    def test_q1_results_match_raw(self, tpcds_golden_env):
        from hyperspace_tpu.benchmark.tpcds import q1_customer_total_return

        session, hs, root = tpcds_golden_env
        session.disable_hyperspace()
        expected = q1_customer_total_return(session, root).to_pydict()
        session.enable_hyperspace()
        got = q1_customer_total_return(session, root).to_pydict()
        key = lambda d: sorted(
            zip(d["sr_customer_sk"], d["sr_store_sk"], [round(v, 6) for v in d["ctr_total_return"]])
        )
        assert key(got) == key(expected)

    def test_bloom_point_lookup_skips(self, tpcds_golden_env):
        """The config-5 bloom index prunes store_returns point-lookup files
        BEFORE any IO: the rewritten scan lists fewer files than the raw
        scan (customer keys are file-local, so most blooms reject)."""
        from hyperspace_tpu.plan.nodes import FileScan

        session, hs, root = tpcds_golden_env
        q = (
            session.read.parquet(root + "/store_returns")
            .filter(col("sr_customer_sk") == 17)
            .select("sr_customer_sk", "sr_return_amt")
        )
        plan = q.optimized_plan()
        scans = [n for n in plan.preorder() if isinstance(n, FileScan)]
        assert len(scans) == 1
        assert len(scans[0].files) < 8  # bloom rejected most of the 8 files
        session.disable_hyperspace()
        expected = q.to_pydict()
        session.enable_hyperspace()
        assert q.to_pydict() == expected
