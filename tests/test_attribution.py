"""Per-query attribution, live metrics export, and the serving health plane.

Covers the PR-9 tentpole guarantees:

- the metrics registry's attributed write path: counter/histogram deltas
  charged to the installed QueryStats in addition to the global value,
  propagated onto IO-pool tasks via ``attribution.bound``;
- conservation: for served queries, per-query ledger sums equal the global
  counter deltas over the serving window (no increment escapes, none is
  double-charged);
- ``MetricsRegistry`` snapshot/export consistency under a concurrent
  write hammer (no torn histogram bucket/count pairs);
- exporter lifecycle: disabled by default (no thread, no socket),
  ephemeral-port bind/release, Prometheus text parses and is internally
  consistent under concurrent scrapes, /healthz flips on an open breaker;
- the query log: rolling window, slow-query JSONL, zero-charge records
  for queries cancelled while queued, phase percentiles for bench;
- tools/trace_report.py --query extracts one serving query's span tree.
"""

import json
import os
import socket
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from hyperspace_tpu import HyperspaceSession, serve
from hyperspace_tpu import constants as C
from hyperspace_tpu.columnar import io as cio
from hyperspace_tpu.columnar.table import ColumnBatch
from hyperspace_tpu.plan import Count, Sum, col, lit
from hyperspace_tpu.serve.context import QueryContext
from hyperspace_tpu.telemetry import attribution, exporter
from hyperspace_tpu.telemetry.attribution import (
    LEDGER,
    QueryStats,
    QueryStatsLedger,
    phase_percentiles,
)
from hyperspace_tpu.telemetry.metrics import (
    REGISTRY,
    Histogram,
    MetricsRegistry,
)
from hyperspace_tpu.utils import backend, faults

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _pristine_observability_state():
    yield
    exporter.stop_exporter()
    exporter.stop_snapshot_sink()
    faults.disarm()
    backend._reset_for_testing()
    serve.reset_global_budget()


def _stats(qid=1, label="t", **kw) -> QueryStats:
    return QueryStats(qid, label=label, **kw)


# ---------------------------------------------------------------------------
# attributed write path
# ---------------------------------------------------------------------------

class TestAttributedWrites:
    def test_counter_inc_charges_scope_and_global(self):
        s = _stats()
        c = REGISTRY.counter("test.attr.counter")
        before = c.value
        with attribution.scope(s):
            c.inc(3)
            c.inc()
        c.inc(10)  # outside the scope: global only
        assert c.value == before + 14
        assert s.counters() == {"test.attr.counter": 4}

    def test_histogram_observe_charges_count_and_sum(self):
        s = _stats()
        h = REGISTRY.histogram("test.attr.hist")
        with attribution.scope(s):
            h.observe(2.0)
            h.observe(3.0)
        h.observe(100.0)
        rec = s.record()
        assert rec["histograms"]["test.attr.hist"] == {"count": 2, "sum": 5.0}

    def test_no_scope_no_charge(self):
        assert attribution.current_stats() is None
        REGISTRY.counter("test.attr.untracked").inc()
        # nothing to assert beyond "no crash": the contextvar read is the
        # entire disabled-path cost

    def test_nested_scope_restores_outer(self):
        outer, inner = _stats(1), _stats(2)
        c = REGISTRY.counter("test.attr.nested")
        with attribution.scope(outer):
            with attribution.scope(inner):
                assert attribution.current_stats() is inner
                c.inc()
            assert attribution.current_stats() is outer
            c.inc()
        assert attribution.current_stats() is None
        assert inner.counters() == {"test.attr.nested": 1}
        assert outer.counters() == {"test.attr.nested": 1}

    def test_bound_propagates_target_to_pool_thread(self):
        from hyperspace_tpu.utils.workers import io_pool

        s = _stats()
        c = REGISTRY.counter("test.attr.pool")

        def task(n):
            c.inc(n)
            return attribution.current_stats()

        with attribution.scope(s):
            with io_pool(2, "hs-test-attr") as pool:
                seen = list(pool.map(attribution.bound(task), [1, 2, 3]))
        assert all(x is s for x in seen)
        assert s.counters()["test.attr.pool"] == 6

    def test_bound_is_identity_without_target(self):
        def fn():
            pass

        assert attribution.bound(fn) is fn

    def test_phase_context_and_charge_phase(self):
        s = _stats()
        with attribution.scope(s):
            with attribution.phase("io"):
                pass
            attribution.charge_phase("dispatch", 0.25)
        attribution.charge_phase("fetch", 9.0)  # no scope: dropped
        phases = s.phases_s()
        assert phases["io"] >= 0.0
        assert phases["dispatch"] == pytest.approx(0.25)
        assert "fetch" not in phases
        assert set(phases) <= set(attribution.PHASES)


# ---------------------------------------------------------------------------
# query records + ledger lifecycle
# ---------------------------------------------------------------------------

class TestQueryLedger:
    def test_record_fields_and_cache_ratio(self):
        s = _stats(7, label="q7")
        s.charge_counter("io.bytes_decoded", 1024)
        s.charge_counter("io.rows_decoded", 10)
        s.charge_counter("cache.index_chunk.hits", 3)
        s.charge_counter("cache.kernel.misses", 1)
        s.charge_phase("io", 0.01)
        rec = s.record()
        assert rec["query_id"] == 7 and rec["label"] == "q7"
        assert rec["outcome"] == "running"
        assert rec["bytes_read"] == 1024 and rec["rows_decoded"] == 10
        assert rec["cache_hits"] == 3 and rec["cache_misses"] == 1
        assert rec["cache_hit_ratio"] == pytest.approx(0.75)
        assert rec["phases_ms"]["io"] == pytest.approx(10.0)

    def test_cache_ratio_none_without_lookups(self):
        assert _stats().record()["cache_hit_ratio"] is None

    def test_begin_finish_moves_to_recent_and_emits_rollups(self):
        led = QueryStatsLedger(window=8)
        ctx = QueryContext(label="unit")
        records = REGISTRY.counter("serve.query.records").value
        done = REGISTRY.counter("serve.query.outcome.done").value
        s = led.begin(ctx, queue_wait_s=0.5)
        assert led.active_records()[0]["query_id"] == ctx.query_id
        rec = led.finish(s, "done")
        assert rec["outcome"] == "done"
        assert rec["queue_wait_ms"] == pytest.approx(500.0)
        assert not led.active_records()
        assert led.recent_records()[0]["query_id"] == ctx.query_id
        assert REGISTRY.counter("serve.query.records").value == records + 1
        assert REGISTRY.counter("serve.query.outcome.done").value == done + 1

    def test_rollup_not_charged_back_to_query(self):
        """finish() runs after the scope exits: the serve.query.* rollups
        must not appear in the query's own counters."""
        led = QueryStatsLedger(window=8)
        s = led.begin(QueryContext(label="meta"))
        led.finish(s, "done")
        assert not any(k.startswith("serve.query.") for k in s.counters())

    def test_record_unrun_zero_charge_cancelled(self):
        led = QueryStatsLedger(window=8)
        rec = led.record_unrun(QueryContext(label="never-ran"))
        assert rec["outcome"] == "cancelled"
        assert rec["bytes_read"] == 0 and rec["counters"] == {}

    def test_window_eviction(self):
        led = QueryStatsLedger(window=2)
        for i in range(5):
            led.finish(led.begin(QueryContext(label=f"q{i}")), "done")
        recent = led.recent_records()
        assert len(recent) == 2
        assert [r["label"] for r in recent] == ["q3", "q4"]
        assert led.snapshot()["totals"]["recorded"] == 5

    def test_aggregate_counters_sums_entries(self):
        led = QueryStatsLedger(window=8)
        a = led.begin(QueryContext())
        b = led.begin(QueryContext())
        a.charge_counter("io.chunks", 2)
        b.charge_counter("io.chunks", 3)
        b.charge_counter("cache.kernel.hits", 1)
        led.finish(a, "done")
        agg = led.aggregate_counters()  # one active + one recent
        assert agg == {"io.chunks": 5, "cache.kernel.hits": 1}

    def test_health_window_rates(self):
        led = QueryStatsLedger(window=16)
        for outcome in ("done", "done", "failed", "cancelled"):
            led.finish(led.begin(QueryContext()), outcome)
        s = led.begin(QueryContext())
        s.charge_counter("device.degrades", 1)
        led.finish(s, "done")
        w = led.health_window()
        assert w["window_records"] == 5
        assert w["failed"] == 1 and w["cancelled"] == 1 and w["degraded"] == 1
        assert w["error_rate"] == pytest.approx(0.2)
        assert w["degrade_rate"] == pytest.approx(0.2)

    def test_slow_query_log_threshold(self, tmp_path, monkeypatch):
        path = str(tmp_path / "slow.jsonl")
        monkeypatch.setenv("HYPERSPACE_SLOW_QUERY_FILE", path)
        monkeypatch.setenv("HYPERSPACE_SLOW_QUERY_MS", "50")
        led = QueryStatsLedger(window=8)
        fast = led.begin(QueryContext(label="fast"))
        led.finish(fast, "done")  # ~0 ms: below threshold
        slow = led.begin(QueryContext(label="slow"))
        slow.started_s -= 1.0  # pretend it ran for a second
        led.finish(slow, "done")
        lines = [
            json.loads(ln)
            for ln in open(path, encoding="utf-8").read().splitlines()
        ]
        assert [r["label"] for r in lines] == ["slow"]
        assert lines[0]["total_ms"] >= 50
        assert led.snapshot()["totals"]["slow"] == 1

    def test_phase_percentiles(self):
        recs = [
            {"total_ms": 10.0, "queue_wait_ms": 1.0,
             "phases_ms": {"io": 4.0, "dispatch": 2.0}},
            {"total_ms": 20.0, "queue_wait_ms": 3.0,
             "phases_ms": {"io": 8.0}},
        ]
        out = phase_percentiles(recs)
        assert out["total"] == {"count": 2, "mean_ms": 15.0, "p99_ms": 20.0}
        assert out["io"]["mean_ms"] == pytest.approx(6.0)
        assert out["dispatch"]["count"] == 1
        assert out["queue"]["count"] == 2
        assert phase_percentiles([]) == {}


# ---------------------------------------------------------------------------
# registry snapshot consistency (concurrent hammer)
# ---------------------------------------------------------------------------

class TestSnapshotConsistency:
    def test_histogram_full_is_one_consistent_cut(self):
        h = Histogram("hammer.hist")
        stop = threading.Event()

        def writer():
            i = 0
            while not stop.is_set():
                h.observe(float(i % 1000))
                i += 1

        threads = [threading.Thread(target=writer) for _ in range(4)]
        for t in threads:
            t.start()
        try:
            for _ in range(300):
                full = h.full()
                # the torn pair snapshot() could historically produce:
                # bucket counts from one instant, count/sum from another
                assert sum(full["buckets"]) == full["count"]
                assert len(full["buckets"]) == len(full["bounds"]) + 1
        finally:
            stop.set()
            for t in threads:
                t.join()

    def test_registry_export_consistent_mid_update(self):
        reg = MetricsRegistry()
        stop = threading.Event()

        def writer(seed):
            i = seed
            while not stop.is_set():
                reg.counter("hammer.c%d" % (i % 3)).inc()
                reg.histogram("hammer.h%d" % (i % 2)).observe(i % 500)
                reg.gauge("hammer.g").set(i)
                i += 1

        threads = [threading.Thread(target=writer, args=(s,)) for s in range(4)]
        for t in threads:
            t.start()
        try:
            for _ in range(200):
                for name, kind, value in reg.export():
                    if kind == "histogram":
                        assert sum(value["buckets"]) == value["count"], name
                snap = reg.snapshot()  # single pass, no torn summaries
                for name, v in snap.items():
                    if isinstance(v, dict) and "count" in v:
                        assert v["count"] >= 0
        finally:
            stop.set()
            for t in threads:
                t.join()


# ---------------------------------------------------------------------------
# exporter lifecycle + health plane
# ---------------------------------------------------------------------------

def _get(url: str):
    """(status, body) following http.server semantics; 4xx/5xx bodies
    still read."""
    try:
        with urllib.request.urlopen(url, timeout=10) as r:
            return r.status, r.read().decode("utf-8")
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode("utf-8")


def _prom_violations(text: str) -> list:
    """Histogram consistency of a /metrics body: cumulative buckets and
    +Inf == _count for every histogram family."""
    buckets, counts = {}, {}
    out = []
    for ln in text.splitlines():
        if not ln or ln.startswith("#"):
            continue
        series, raw = ln.rsplit(" ", 1)
        float(raw)  # every sample line must end in a number
        if '{le="' in series:
            name = series.split("{", 1)[0]
            buckets.setdefault(name, []).append(
                (series.split('le="', 1)[1].split('"', 1)[0], float(raw))
            )
        elif series.endswith("_count"):
            counts[series[: -len("_count")]] = float(raw)
    for name, bs in buckets.items():
        cum = [v for _le, v in bs]
        if any(b < a for a, b in zip(cum, cum[1:])):
            out.append(f"{name}: not cumulative")
        base = name[: -len("_bucket")]
        if not bs or bs[-1][0] != "+Inf" or counts.get(base) != bs[-1][1]:
            out.append(f"{name}: +Inf != _count")
    return out


class TestExporter:
    def test_disabled_by_default_no_thread_no_socket(self, monkeypatch):
        monkeypatch.delenv("HYPERSPACE_METRICS_PORT", raising=False)
        monkeypatch.delenv("HYPERSPACE_SNAPSHOT_FILE", raising=False)
        exporter.maybe_start_from_env()
        assert exporter.get_exporter() is None
        assert not [
            t for t in threading.enumerate()
            if t.name.startswith("hs-metrics")
        ]
        assert exporter.start_exporter() is None  # knob unset: stays off

    def test_bind_serve_stop_release(self):
        exp = exporter.start_exporter(port=0)
        assert exp is not None and exp.port > 0
        assert REGISTRY.gauge("exporter.up").value == 1
        code, body = _get(exp.url + "/metrics")
        assert code == 200
        assert "hyperspace_" in body
        assert _prom_violations(body) == []
        port = exp.port
        exporter.stop_exporter()
        assert REGISTRY.gauge("exporter.up").value == 0
        # the port is actually released: we can bind it again
        s = socket.socket()
        try:
            s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            s.bind(("127.0.0.1", port))
        finally:
            s.close()
        exporter.stop_exporter()  # idempotent

    def test_start_is_singleton(self):
        a = exporter.start_exporter(port=0)
        b = exporter.start_exporter(port=0)
        assert a is b

    def test_snapshot_endpoint_shape(self):
        exp = exporter.start_exporter(port=0)
        code, body = _get(exp.url + "/snapshot")
        assert code == 200
        snap = json.loads(body)
        assert set(snap) >= {"ts", "metrics", "serving", "breaker", "queries"}
        assert set(snap["queries"]) >= {"window", "totals", "active", "recent"}
        code, _404 = _get(exp.url + "/nope")
        assert code == 404
        assert REGISTRY.counter("exporter.scrapes").value > 0

    def test_healthz_ok_then_flips_on_open_breaker(self, monkeypatch):
        monkeypatch.setenv("HYPERSPACE_DEVICE_STRICT", "0")
        backend._reset_for_testing()
        exp = exporter.start_exporter(port=0)
        code, body = _get(exp.url + "/healthz")
        assert code == 200 and json.loads(body)["status"] == "ok"
        # a transient device failure (the PR 7 injected flavor) opens the
        # breaker: the health plane must flip to degraded/503
        backend.record_device_failure(
            faults.InjectedIOError("injected: tunnel dropped")
        )
        assert backend.breaker_state() == "open"
        code, body = _get(exp.url + "/healthz")
        payload = json.loads(body)
        assert code == 503
        assert payload["status"] == "degraded"
        assert payload["breaker"] == "open"

    def test_concurrent_scrapes_stay_consistent(self):
        exp = exporter.start_exporter(port=0)
        stop = threading.Event()

        def writer():
            h = REGISTRY.histogram("scrape.hammer_ms")
            i = 0
            while not stop.is_set():
                h.observe(i % 750)
                REGISTRY.counter("scrape.hammer").inc()
                i += 1

        t = threading.Thread(target=writer)
        t.start()
        try:
            for _ in range(25):
                code, body = _get(exp.url + "/metrics")
                assert code == 200
                assert _prom_violations(body) == []
        finally:
            stop.set()
            t.join()

    def test_snapshot_sink_writes_and_final_flush(self, tmp_path):
        path = str(tmp_path / "snaps.jsonl")
        sink = exporter.start_snapshot_sink(path, interval_s=0.05)
        assert sink is not None
        time.sleep(0.2)
        exporter.stop_snapshot_sink()  # also writes one final snapshot
        lines = [
            json.loads(ln)
            for ln in open(path, encoding="utf-8").read().splitlines()
        ]
        assert len(lines) >= 2
        assert all(
            set(s) >= {"ts", "metrics", "serving", "breaker", "queries"}
            for s in lines
        )

    def test_sink_disabled_without_knob(self, monkeypatch):
        monkeypatch.delenv("HYPERSPACE_SNAPSHOT_FILE", raising=False)
        assert exporter.start_snapshot_sink() is None


# ---------------------------------------------------------------------------
# served-query integration: conservation + query log + scheduler wiring
# ---------------------------------------------------------------------------

def _write_multifile(root, n_files=6, rows=2500, seed=3):
    rng = np.random.default_rng(seed)
    for i in range(n_files):
        n = rows + i * 97
        data = {
            "k": rng.integers(0, 40, n).tolist(),
            "x": rng.uniform(0, 100, n).tolist(),
            "q": rng.integers(1, 50, n).tolist(),
        }
        cio.write_parquet(
            ColumnBatch.from_pydict(data),
            os.path.join(root, "t", f"part-{i}.parquet"),
        )


CONSERVED = ("io.", "cache.", "rpc.", "pipeline.", "serve.budget.")


def _conserved_globals() -> dict:
    return {
        name: value
        for name, kind, value in REGISTRY.export()
        if kind == "counter" and name.startswith(CONSERVED)
    }


class TestServedAttribution:
    def _session_query(self, tmp_path, monkeypatch):
        _write_multifile(str(tmp_path))
        monkeypatch.setenv("HYPERSPACE_IO_THREADS", "4")
        monkeypatch.setenv("HYPERSPACE_STREAM_CHUNK_MB", "0.01")
        session = HyperspaceSession(warehouse_dir=str(tmp_path))
        session.set_conf(C.EXEC_TPU_ENABLED, True)

        def q():
            return (
                session.read.parquet(os.path.join(str(tmp_path), "t"))
                .filter(col("q") > 10)
                .agg(Sum(col("x")).alias("sx"), Count(lit(1)).alias("n"))
            )

        return session, q

    def test_conservation_per_query_sums_equal_global_deltas(
        self, tmp_path, monkeypatch
    ):
        """THE invariant: every conserved-counter increment during serving
        is charged to exactly one query, so ledger sums == global deltas."""
        session, q = self._session_query(tmp_path, monkeypatch)
        serve.reset_global_budget()
        q().collect()  # warm caches outside the serving window
        g0 = _conserved_globals()
        l0 = {
            k: v for k, v in LEDGER.aggregate_counters().items()
            if k.startswith(CONSERVED)
        }
        sched = serve.QueryScheduler(max_concurrent=4, queue_depth=64)
        try:
            hs = [
                sched.submit(q().collect, label=f"c{i}") for i in range(8)
            ]
            for h in hs:
                h.result(60)
        finally:
            sched.shutdown()

        def mismatches():
            g1 = _conserved_globals()
            deltas = {k: g1.get(k, 0) - g0.get(k, 0) for k in set(g0) | set(g1)}
            lsum = {
                k: v - l0.get(k, 0)
                for k, v in LEDGER.aggregate_counters().items()
                if k.startswith(CONSERVED)
            }
            return {
                k: (deltas.get(k, 0), lsum.get(k, 0))
                for k in set(deltas) | set(lsum)
                if deltas.get(k, 0) != lsum.get(k, 0)
            }

        m = mismatches()
        deadline = time.time() + 10
        while m and time.time() < deadline:
            time.sleep(0.1)  # straggler bound tasks may still be landing
            m = mismatches()
        assert m == {}
        # and the machinery demonstrably engaged
        recent = LEDGER.recent_records()
        mine = [r for r in recent if r["label"].startswith("c")]
        assert len(mine) >= 8
        assert any(r["bytes_read"] > 0 for r in mine)
        assert any(r["phases_ms"].get("io", 0) > 0 for r in mine)

    def test_served_query_record_has_phases_and_outcome(
        self, tmp_path, monkeypatch
    ):
        session, q = self._session_query(tmp_path, monkeypatch)
        serve.reset_global_budget()
        sched = serve.QueryScheduler(max_concurrent=1, queue_depth=8)
        try:
            h = sched.submit(q().collect, label="prof-me")
            h.result(60)
        finally:
            sched.shutdown()
        rec = next(
            r for r in reversed(LEDGER.recent_records())
            if r["label"] == "prof-me"
        )
        assert rec["outcome"] == "done"
        assert rec["total_ms"] > 0
        assert rec["phases_ms"].get("plan", 0) > 0
        assert rec["bytes_read"] > 0 and rec["rows_decoded"] > 0

    def test_queued_cancel_lands_in_query_log(self):
        gate = threading.Event()
        sched = serve.QueryScheduler(max_concurrent=1, queue_depth=8)
        try:
            blocker = sched.submit(lambda: gate.wait(30), label="blocker")
            victim = sched.submit(lambda: None, label="queued-victim")
            victim.cancel()
            with pytest.raises(serve.QueryCancelledError):
                victim.result(10)
            gate.set()
            blocker.result(30)
            sched.drain(timeout=30)
        finally:
            gate.set()
            sched.shutdown()
        rec = next(
            r for r in reversed(LEDGER.recent_records())
            if r["label"] == "queued-victim"
        )
        assert rec["outcome"] == "cancelled"
        assert rec["counters"] == {}  # never ran: zero charges

    def test_query_log_string_renders(self, tmp_path, monkeypatch):
        from hyperspace_tpu.analysis.explain import query_log_string

        session, q = self._session_query(tmp_path, monkeypatch)
        serve.reset_global_budget()
        sched = serve.QueryScheduler(max_concurrent=1, queue_depth=8)
        try:
            sched.submit(q().collect, label="render-me").result(60)
        finally:
            sched.shutdown()
        out = query_log_string()
        assert "Query log (per-query attribution):" in out
        assert "render-me" in out


# ---------------------------------------------------------------------------
# tools: trace_report --query and hs_top rendering
# ---------------------------------------------------------------------------

def _span_line(span_id, parent_id, name, ms, attrs):
    return json.dumps({
        "span_id": span_id, "parent_id": parent_id, "name": name,
        "start_s": 0.0, "duration_ms": ms, "attrs": attrs, "rpc": {},
    })


class TestTools:
    def test_trace_report_query_filter(self, tmp_path):
        trace_path = str(tmp_path / "mixed.jsonl")
        lines = [
            # children precede parents, as JsonlTraceSink writes them
            _span_line(2, 1, "exec:Aggregate", 5.0, {}),
            _span_line(1, None, "serve:query", 9.0,
                       {"query_id": 11, "label": "mine"}),
            _span_line(4, 3, "exec:Filter", 2.0, {}),
            _span_line(3, None, "serve:query", 4.0,
                       {"query_id": 12, "label": "other"}),
            _span_line(5, None, "serve:admit", 0.1,
                       {"query_id": 11, "label": "mine"}),
        ]
        with open(trace_path, "w", encoding="utf-8") as f:
            f.write("\n".join(lines) + "\n")
        out = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "trace_report.py"),
             trace_path, "--query", "11"],
            capture_output=True, text=True, cwd=REPO, check=True,
        ).stdout
        assert "serve:query" in out and "exec:Aggregate" in out
        assert "serve:admit" in out
        assert "exec:Filter" not in out  # the other query's subtree
        missing = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "trace_report.py"),
             trace_path, "--query", "99"],
            capture_output=True, text=True, cwd=REPO, check=True,
        ).stdout
        assert "no serve:query spans with query_id=99" in missing

    def test_hs_top_renders_snapshot(self):
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "hs_top", os.path.join(REPO, "tools", "hs_top.py")
        )
        hs_top = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(hs_top)
        led = QueryStatsLedger(window=8)
        s = led.begin(QueryContext(label="topq"))
        s.charge_counter("io.bytes_decoded", 5_000_000)
        s.charge_phase("io", 0.12)
        led.finish(s, "done")
        snap = exporter.snapshot_dict()
        snap["queries"] = led.snapshot()
        out = hs_top.render(snap)
        assert "hs_top @" in out and "topq" in out
        assert "RECENT" in out
        # rates need two snapshots; a second one unlocks them
        snap2 = dict(snap, ts=snap["ts"] + 2.0)
        assert "qps" in hs_top.render(snap2, prev=snap)
