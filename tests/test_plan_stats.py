"""Operator-level runtime statistics (telemetry/plan_stats.py).

Pins the EXPLAIN ANALYZE contract: an analyzed execution is bitwise
identical to a plain collect, per-node actuals land on the right nodes,
q-error math is exact, the disabled path allocates no collector, the
observe-only feedback path changes nothing, and HYPERSPACE_ESTIMATOR_FEEDBACK=1
re-ranks candidates from planted observations. Also covers the satellite
fixes: direct (non-scheduler) collects produce query-log records, and
IndexPruning usage events carry the predicted-kept count.
"""

import math
import os

import numpy as np
import pytest

from hyperspace_tpu import CoveringIndexConfig, Hyperspace
from hyperspace_tpu import constants as C
from hyperspace_tpu.benchmark import TPCH_QUERIES, generate_tpch, tpch_indexes
from hyperspace_tpu.columnar import io as cio
from hyperspace_tpu.columnar.table import ColumnBatch
from hyperspace_tpu.plan import col
from hyperspace_tpu.telemetry import plan_stats
from hyperspace_tpu.telemetry.metrics import REGISTRY
from hyperspace_tpu.telemetry.plan_stats import (
    ACCURACY,
    EstimatorAccuracy,
    QERROR_BOUNDS,
)


def _bits(d: dict) -> str:
    return repr(
        {
            k: [x.hex() if isinstance(x, float) else x for x in v]
            for k, v in d.items()
        }
    )


@pytest.fixture(scope="module")
def tpch_env(tmp_path_factory):
    root = str(tmp_path_factory.mktemp("tpch_plan_stats"))
    from hyperspace_tpu.session import HyperspaceSession

    session = HyperspaceSession(warehouse_dir=root)
    generate_tpch(root, rows_lineitem=6_000, seed=3)
    hs = Hyperspace(session)
    tpch_indexes(session, hs, root)
    return session, hs, root


@pytest.fixture()
def indexed_events(tmp_session, tmp_path):
    """Small bucketed covering index whose point lookups bucket-prune."""
    rng = np.random.default_rng(5)
    n, n_files = 8_000, 4
    per = n // n_files
    for i in range(n_files):
        cio.write_parquet(
            ColumnBatch.from_pydict(
                {
                    "k": (np.arange(per, dtype=np.int64) + i * per).tolist(),
                    "q": rng.integers(1, 50, per).tolist(),
                    "v": rng.uniform(0, 1, per).tolist(),
                }
            ),
            str(tmp_path / "ev" / f"part-{i}.parquet"),
        )
    tmp_session.set_conf(C.INDEX_NUM_BUCKETS, 8)
    hs = Hyperspace(tmp_session)
    hs.create_index(
        tmp_session.read.parquet(str(tmp_path / "ev")),
        CoveringIndexConfig("k_idx", ["k"], ["q", "v"]),
    )
    tmp_session.enable_hyperspace()
    return tmp_session, hs, str(tmp_path / "ev"), n


class TestAnalyzeBitIdentity:
    def test_all_tpch_queries_bit_identical_under_analyze(self, tpch_env):
        session, hs, root = tpch_env
        session.enable_hyperspace()
        session.set_conf(C.EXEC_TPU_ENABLED, True)
        try:
            for name, q in TPCH_QUERIES.items():
                plain = _bits(q(session, root).to_pydict())
                with plan_stats.collect_scope() as colr:
                    analyzed = _bits(q(session, root).to_pydict())
                assert analyzed == plain, f"{name} diverged under analyze"
                assert colr.nodes, f"{name} recorded no node stats"
        finally:
            session.set_conf(C.EXEC_TPU_ENABLED, False)
            session.disable_hyperspace()

    def test_explain_analyze_renders_actuals_and_qerror(self, indexed_events):
        session, hs, path, n = indexed_events
        report = hs.explain_analyze(
            session.read.parquet(path)
            .filter(col("k") == n // 2 + 3)
            .select("k", "q", "v")
        )
        assert "Plan statistics (EXPLAIN ANALYZE):" in report
        assert "rows=" in report and "wall=" in report and "bytes=" in report
        assert "scan_fraction" in report and "q=" in report
        assert "Estimator accuracy (process-wide):" in report

    def test_df_explain_analyze_flag(self, indexed_events):
        session, hs, path, n = indexed_events
        df = session.read.parquet(path).filter(col("k") == 11).select("k", "q")
        assert "FileScan" in df.explain()  # plain: no execution
        assert "rows=" in df.explain(analyze=True)


class TestNodeActuals:
    def test_per_node_rows_bytes_routes(self, indexed_events):
        session, hs, path, n = indexed_events
        df = (
            session.read.parquet(path)
            .filter(col("k") < 100)
            .select("k", "q")
        )
        with plan_stats.collect_scope() as colr:
            out = df.to_pydict()
        assert colr.plan is not None
        from hyperspace_tpu.plan.nodes import FileScan, Filter, Project

        by_kind = {}
        for node in colr.plan.preorder():
            ns = colr.nodes.get(node.plan_id)
            if ns is not None and ns.executed:
                by_kind[node.kind] = (node, ns)
        # the project's output rows are the query's result rows
        proj, pns = by_kind["Project"]
        assert pns.rows_out == len(out["k"]) == 100
        scan, sns = by_kind["FileScan"]
        assert sns.rows_out is not None and sns.rows_out >= 100
        assert sns.files_scanned == len(scan.files)
        assert sns.bytes_scanned == sum(f.size for f in scan.files)
        assert sns.wall_s > 0
        # host execution throughout on this fixture
        assert all(ns.route == "host" for _, ns in by_kind.values())

    def test_point_lookup_qerror_lands_on_scan_node(self, indexed_events):
        session, hs, path, n = indexed_events
        from hyperspace_tpu.plan.nodes import FileScan

        df = (
            session.read.parquet(path)
            .filter(col("k") == n // 4 + 1)
            .select("k", "q")
        )
        with plan_stats.collect_scope() as colr:
            df.to_pydict()
        scans = [
            colr.nodes[node.plan_id]
            for node in colr.plan.preorder()
            if isinstance(node, FileScan) and node.plan_id in colr.nodes
        ]
        assert scans
        ests = {est for ns in scans for est, *_ in ns.qerrors}
        assert "scan_fraction" in ests

    def test_annotation_format(self):
        colr = plan_stats.PlanStatsCollector()

        class _N:
            plan_id = 7
            kind = "Filter"

        colr.record_node(_N, 42, 0.00123)
        colr.note_route(7, "pipelined")
        colr.note_qerror(7, "scan_fraction", 0.125, 0.25, 2.0)
        ann = colr.annotation(7)
        assert "rows=42" in ann
        assert "wall=1.23ms" in ann
        assert "route=pipelined" in ann
        assert "scan_fraction: pred=0.125 actual=0.25 q=2.00" in ann
        assert colr.annotation(999) == ""


class TestQErrorMath:
    def test_qerror_symmetric_and_histogrammed(self):
        acc = EstimatorAccuracy()
        h0 = REGISTRY.histogram("estimator.qerror.unit_test", QERROR_BOUNDS)
        c0 = h0.full()["count"]
        assert acc.observe("unit_test", 2.0, 8.0) == pytest.approx(4.0)
        assert acc.observe("unit_test", 8.0, 2.0) == pytest.approx(4.0)
        assert acc.observe("unit_test", 3.0, 3.0) == pytest.approx(1.0)
        full = REGISTRY.histogram("estimator.qerror.unit_test").full()
        assert full["count"] == c0 + 3
        assert sum(full["buckets"]) == full["count"]

    def test_zero_actual_clamps_not_raises(self):
        acc = EstimatorAccuracy()
        q = acc.observe("unit_zero", 0.5, 0.0)
        assert math.isfinite(q) and q > 1.0
        assert acc.observe("unit_zero", 0.0, 0.0) == pytest.approx(1.0)

    def test_correction_geometric_mean_and_fallback(self):
        acc = EstimatorAccuracy()
        # actual consistently 4x the prediction => correction 4.0
        for _ in range(5):
            acc.observe("e", 1.0, 4.0, index="i1", shape="k:eq")
        assert acc.correction("e", "i1", "k:eq") == pytest.approx(4.0)
        # the shaped observation also feeds the shape-agnostic window
        assert acc.correction("e", "i1", "other-shape") == pytest.approx(4.0)
        assert acc.correction("e", "unknown") == 1.0
        assert acc.correction("unknown") == 1.0

    def test_snapshot_shape(self):
        acc = EstimatorAccuracy()
        acc.observe("s", 1.0, 2.0, index="i")
        snap = acc.snapshot()
        assert snap["observations"] == 1
        assert snap["by_estimator"] == {"s": 1}
        assert snap["correction_keys"] == 1
        assert "s|i|" in snap["corrections"]


class TestDisabledPathZeroOverhead:
    def test_plain_collect_allocates_no_collector(self, indexed_events):
        session, hs, path, n = indexed_events
        df = session.read.parquet(path).filter(col("k") == 5).select("k", "q")
        df.to_pydict()  # warm
        allocs0 = REGISTRY.counter("plan_stats.collectors").value
        df.to_pydict()
        assert plan_stats.current() is None
        assert REGISTRY.counter("plan_stats.collectors").value == allocs0

    def test_forced_env_installs_collector(self, indexed_events, monkeypatch):
        session, hs, path, n = indexed_events
        monkeypatch.setenv("HYPERSPACE_PLAN_STATS", "1")
        allocs0 = REGISTRY.counter("plan_stats.collectors").value
        session.read.parquet(path).filter(col("k") == 5).select("k").to_pydict()
        assert REGISTRY.counter("plan_stats.collectors").value == allocs0 + 1


class _RankerFixture:
    """Two covering candidates over one table: idx_a (bigger, bucket-prunes
    a filter on `a` to 1/8) vs idx_b (smaller, unprunable for it) — the
    PR-4 ranking scenario the feedback path must be able to flip."""

    def build(self, tmp_session, tmp_path):
        rng = np.random.default_rng(2)
        n = 30_000
        cio.write_parquet(
            ColumnBatch.from_pydict(
                {
                    "a": rng.integers(0, 1000, n).tolist(),
                    "b": rng.integers(0, 1000, n).tolist(),
                    # ballast columns so idx_a (which covers them) is several
                    # times bigger than idx_b — pruning to 1/8 still makes
                    # idx_a the cheaper read until feedback corrects it
                    "v": rng.uniform(0, 1, n).tolist(),
                    "w": rng.uniform(0, 1, n).tolist(),
                }
            ),
            str(tmp_path / "R" / "r.parquet"),
        )
        tmp_session.set_conf(C.INDEX_NUM_BUCKETS, 8)
        hs = Hyperspace(tmp_session)
        df = tmp_session.read.parquet(str(tmp_path / "R"))
        hs.create_index(
            df, CoveringIndexConfig("idx_a", ["a"], ["b", "v", "w"])
        )
        hs.create_index(df, CoveringIndexConfig("idx_b", ["b"], ["a"]))
        tmp_session.enable_hyperspace()
        return tmp_session

    def chosen_index(self, session, tmp_path):
        from hyperspace_tpu.plan.nodes import FileScan

        plan = (
            session.read.parquet(str(tmp_path / "R"))
            .filter((col("a") == 7) & (col("b") > 100))
            .select("a", "b")
            .optimized_plan()
        )
        scan = [n for n in plan.preorder() if isinstance(n, FileScan)][0]
        assert scan.index_info is not None
        return scan.index_info.index_name

    def plant_misestimate(self, cond):
        """Teach the ledger that idx_a's 1/8 scan-fraction estimate is an
        8x under-estimate for this predicate shape (actual ~ full read).
        Resets the ledger first so organic observations from earlier
        optimizer runs cannot dilute the planted factor."""
        from hyperspace_tpu.plan.pruning import predicate_shape

        ACCURACY.reset_for_testing()
        shape = predicate_shape(cond, ("a",))
        assert shape == "a:eq"
        for _ in range(8):
            ACCURACY.observe(
                "scan_fraction", 0.125, 1.0, index="idx_a", shape=shape
            )


class TestEstimatorFeedback:
    def test_feedback_off_planted_misestimate_changes_nothing(
        self, tmp_session, tmp_path, monkeypatch
    ):
        fx = _RankerFixture()
        session = fx.build(tmp_session, tmp_path)
        monkeypatch.delenv("HYPERSPACE_ESTIMATOR_FEEDBACK", raising=False)
        assert fx.chosen_index(session, tmp_path) == "idx_a"
        cond = (col("a") == 7) & (col("b") > 100)
        fx.plant_misestimate(cond)
        # observe-only: the planted correction must NOT re-rank
        assert fx.chosen_index(session, tmp_path) == "idx_a"

    def test_feedback_on_reranks_from_planted_misestimate(
        self, tmp_session, tmp_path, monkeypatch
    ):
        fx = _RankerFixture()
        session = fx.build(tmp_session, tmp_path)
        assert fx.chosen_index(session, tmp_path) == "idx_a"
        cond = (col("a") == 7) & (col("b") > 100)
        fx.plant_misestimate(cond)
        monkeypatch.setenv("HYPERSPACE_ESTIMATOR_FEEDBACK", "1")
        # corrected fraction 0.125 * 8 = 1.0: the smaller idx_b now wins
        assert fx.chosen_index(session, tmp_path) == "idx_b"
        # results stay correct either way (rewrites are semantics-preserving)
        got = (
            session.read.parquet(str(tmp_path / "R"))
            .filter((col("a") == 7) & (col("b") > 100))
            .select("a", "b", "v")
            .to_pydict()
        )
        monkeypatch.delenv("HYPERSPACE_ESTIMATOR_FEEDBACK")
        expected = (
            session.read.parquet(str(tmp_path / "R"))
            .filter((col("a") == 7) & (col("b") > 100))
            .select("a", "b", "v")
            .to_pydict()
        )
        assert _bits(got) == _bits(expected)

    def test_corrected_fraction_identity_when_off(self, monkeypatch):
        from hyperspace_tpu.plan import pruning

        monkeypatch.delenv("HYPERSPACE_ESTIMATOR_FEEDBACK", raising=False)

        class _DD:
            num_buckets = 0

        class _Entry:
            name = "x"
            derived_dataset = _DD()

        assert pruning.corrected_scan_fraction(None, _Entry()) == 1.0


class TestPredicateShape:
    def test_shapes(self):
        from hyperspace_tpu.plan.pruning import predicate_shape

        assert predicate_shape(None, ("k",)) == ""
        assert predicate_shape(col("k") == 1, ("k",)) == "k:eq"
        assert predicate_shape(col("k").isin([1, 2, 3]), ("k",)) == "k:in3"
        assert predicate_shape(col("x") > 2, ("k",)) == "k:*"
        two = (col("a") == 1) & (col("b").isin([1, 2]))
        assert predicate_shape(two, ("a", "b")) == "a:eq+b:in2"


class TestSatellites:
    def test_direct_collect_produces_query_log_record(self, indexed_events):
        from hyperspace_tpu.telemetry.attribution import LEDGER

        session, hs, path, n = indexed_events
        seq0 = LEDGER.last_seq()
        session.read.parquet(path).filter(col("k") == 9).select("k").to_pydict()
        recs = [
            r for r in LEDGER.recent_records(since_seq=seq0)
            if r["label"].startswith("collect:")
        ]
        assert recs, "direct collect produced no query-log record"
        rec = recs[-1]
        assert rec["outcome"] == "done"
        assert rec["total_ms"] >= 0
        assert rec["counters"], "direct collect record carries no charges"

    def test_direct_collect_failure_outcome(self, tmp_session, tmp_path):
        from hyperspace_tpu.telemetry.attribution import LEDGER

        cio.write_parquet(
            ColumnBatch.from_pydict({"x": [1, 2]}), str(tmp_path / "t" / "p.parquet")
        )
        df = tmp_session.read.parquet(str(tmp_path / "t")).select("x")
        seq0 = LEDGER.last_seq()
        os.unlink(str(tmp_path / "t" / "p.parquet"))
        with pytest.raises(BaseException):
            df.to_pydict()
        recs = [
            r for r in LEDGER.recent_records(since_seq=seq0)
            if r["label"].startswith("collect:")
        ]
        assert recs and recs[-1]["outcome"] == "failed"

    def test_served_collect_keeps_scheduler_record(self, indexed_events):
        """No double-record: a scheduler-served query must NOT additionally
        open a collect:* record."""
        from hyperspace_tpu import serve
        from hyperspace_tpu.telemetry.attribution import LEDGER

        session, hs, path, n = indexed_events
        seq0 = LEDGER.last_seq()
        sched = serve.QueryScheduler(max_concurrent=1, queue_depth=4)
        try:
            sched.submit(
                lambda: session.read.parquet(path)
                .filter(col("k") == 3)
                .select("k")
                .collect(),
                label="served-one",
            ).result(60)
        finally:
            sched.shutdown(wait=True)
        recs = LEDGER.recent_records(since_seq=seq0)
        assert any(r["label"] == "served-one" for r in recs)
        assert not any(r["label"].startswith("collect:") for r in recs)

    def test_pruning_event_carries_predicted_kept(self, indexed_events):
        from hyperspace_tpu.telemetry.logger import event_logger_for

        session, hs, path, n = indexed_events
        events = []
        logger = event_logger_for(session)
        orig = logger.log_event
        logger.log_event = lambda e: (events.append(e), orig(e))[1]
        try:
            session.read.parquet(path).filter(col("k") == 77).select(
                "k", "q"
            ).to_pydict()
        finally:
            logger.log_event = orig
        prune_events = [
            e for e in events
            if getattr(e, "rule", "") == "IndexPruning"
        ]
        assert prune_events
        assert any("(predicted " in e.message for e in prune_events)

    def test_qerror_attributed_to_serving_query(self, indexed_events):
        """The estimator histograms ride the attributed write path: a
        served query's record carries its own q-error observations."""
        from hyperspace_tpu import serve
        from hyperspace_tpu.telemetry.attribution import LEDGER

        session, hs, path, n = indexed_events
        seq0 = LEDGER.last_seq()
        sched = serve.QueryScheduler(max_concurrent=2, queue_depth=8)
        try:
            sched.submit(
                lambda: session.read.parquet(path)
                .filter(col("k") == 123)
                .select("k", "q")
                .collect(),
                label="qerr-one",
            ).result(60)
        finally:
            sched.shutdown(wait=True)
        rec = next(
            r for r in reversed(LEDGER.recent_records(since_seq=seq0))
            if r["label"] == "qerr-one"
        )
        est = {
            k: v for k, v in rec["histograms"].items()
            if k.startswith("estimator.qerror.")
        }
        assert est and all(v["count"] >= 1 for v in est.values())
