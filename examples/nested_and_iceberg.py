"""Nested columns and the Iceberg-style snapshot source.

Run: python examples/nested_and_iceberg.py

Covers two round-2 capabilities:
1. Indexing nested (struct) fields: struct leaves flatten to
   `__hs_nested.<path>` columns at the reader boundary (ref:
   util/ResolverUtils.scala's normalization) and bare dotted references
   like col("nested.cnt") resolve to them everywhere.
2. The Iceberg-shaped snapshot table: metadata files + manifest lists +
   manifests, random snapshot ids with parent ancestry, time travel by
   snapshot id or timestamp, and ancestry-based index-version matching.
"""

import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

# force the local CPU backend in environments with a remote-TPU plugin
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import jax

jax.config.update("jax_platforms", "cpu")

from hyperspace_tpu import CoveringIndexConfig, Hyperspace, HyperspaceSession
from hyperspace_tpu.columnar.table import ColumnBatch
from hyperspace_tpu.plan import col
from hyperspace_tpu.sources.iceberg import IcebergStyleTable

ws = tempfile.mkdtemp(prefix="hs_example_")
session = HyperspaceSession(warehouse_dir=ws)
hs = Hyperspace(session)

# --- 1. nested columns ------------------------------------------------------
rng = np.random.default_rng(0)
n = 10_000
nested_table = pa.table(
    {
        "id": pa.array(np.arange(n)),
        "nested": pa.StructArray.from_arrays(
            [pa.array(rng.integers(0, 100, n)), pa.array(rng.uniform(0, 1, n))],
            names=["cnt", "score"],
        ),
    }
)
src = os.path.join(ws, "events")
os.makedirs(src)
pq.write_table(nested_table, os.path.join(src, "part-0.parquet"))

df = session.read.parquet(src)
print("flattened schema:", df.schema.names)

# index the nested field by its dotted path; the index column is the
# normalized __hs_nested.nested.cnt
hs.create_index(df, CoveringIndexConfig("ev_cnt", ["nested.cnt"], ["id"]))
session.enable_hyperspace()
out = (
    session.read.parquet(src)
    .filter(col("nested.cnt") == 7)
    .select("id", "nested.cnt")
    .to_pydict()
)
print("rows with nested.cnt == 7:", len(out["id"]))
print(hs.why_not(session.read.parquet(src).select("id")))
session.disable_hyperspace()

# --- 2. iceberg-style snapshots --------------------------------------------
t = IcebergStyleTable(os.path.join(ws, "sales"))
s0 = t.commit(ColumnBatch.from_pydict({"k": [1, 2, 3], "v": [1.0, 2.0, 3.0]}))
s1 = t.commit(ColumnBatch.from_pydict({"k": [4], "v": [4.0]}))
print("snapshots:", s0, "->", s1, "(parent:", t.parent_of(s1), ")")

hs.create_index(t.scan(session), CoveringIndexConfig("sales_k", ["k"], ["v"]))
session.enable_hyperspace()
print("current rows:", t.scan(session).count())
print("time travel to first snapshot:", t.scan(session, snapshot_id=s0).count())
# the filter over the old snapshot still uses the index version recorded
# against an ancestor snapshot (ancestry-walk matching)
old = t.scan(session, snapshot_id=s0).filter(col("k") == 2).select("k", "v")
print("old-snapshot lookup:", old.to_pydict())
