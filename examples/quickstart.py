"""Hitchhiker's-guide-style walkthrough (ref: the reference repo's
examples + notebooks/Hitchhikers Guide): create, use, inspect, maintain and
drop every index kind on a toy dataset.

Run:  python examples/quickstart.py
"""

import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from hyperspace_tpu import (
    BloomFilterSketch,
    CoveringIndexConfig,
    DataSkippingIndexConfig,
    Hyperspace,
    HyperspaceSession,
    MinMaxSketch,
    ZOrderCoveringIndexConfig,
)
from hyperspace_tpu.columnar import io as cio
from hyperspace_tpu.columnar.table import ColumnBatch
from hyperspace_tpu.plan import col, lit, Count, Sum


def main() -> None:
    ws = tempfile.mkdtemp(prefix="hs_example_")
    rng = np.random.default_rng(0)
    n = 100_000

    # ------------------------------------------------------------------ data
    for i in range(4):
        sl = slice(i * n // 4, (i + 1) * n // 4)
        rows = n // 4
        cio.write_parquet(
            ColumnBatch.from_pydict(
                {
                    "order_id": list(range(sl.start, sl.stop)),
                    "customer": rng.integers(0, 5000, rows).tolist(),
                    "amount": rng.uniform(1, 1000, rows).tolist(),
                    "day": rng.integers(i * 90, (i + 1) * 90, rows).tolist(),
                }
            ),
            os.path.join(ws, "orders", f"part-{i}.parquet"),
        )

    session = HyperspaceSession(warehouse_dir=ws)
    hs = Hyperspace(session)
    orders = session.read.parquet(os.path.join(ws, "orders"))

    # --------------------------------------------------------------- indexes
    hs.create_index(orders, CoveringIndexConfig("by_customer", ["customer"], ["amount"]))
    hs.create_index(orders, ZOrderCoveringIndexConfig("by_day_amount", ["day", "amount"]))
    hs.create_index(
        orders,
        DataSkippingIndexConfig(
            "skip_day", [MinMaxSketch("day"), BloomFilterSketch("customer", 2000, 0.01)]
        ),
    )
    print(hs.indexes().to_pandas()[["name", "kind", "indexedColumns", "state"]], "\n")

    # ---------------------------------------------------------------- queries
    session.enable_hyperspace()
    orders = session.read.parquet(os.path.join(ws, "orders"))

    q = (
        orders.filter(col("customer") == 42)
        .select("customer", "amount")
        .agg(Sum(col("amount")).alias("total"), Count(lit(1)).alias("n"))
    )
    print("customer 42 total:", q.to_pydict())
    print(hs.explain(orders.filter(col("customer") == 42).select("customer", "amount")))

    # why didn't an index apply?
    print(hs.why_not(orders.select("order_id"), extended=True).splitlines()[6])

    # ------------------------------------------------------------ maintenance
    cio.write_parquet(
        ColumnBatch.from_pydict(
            {"order_id": [n], "customer": [42], "amount": [999.0], "day": [1]}
        ),
        os.path.join(ws, "orders", "part-new.parquet"),
    )
    hs.refresh_index("by_customer", "incremental")
    hs.optimize_index("by_customer", "quick")
    # NOTE: a DataFrame pins its file listing when created; re-read after
    # source mutations (Spark re-lists per query, this frontend does not)
    orders = session.read.parquet(os.path.join(ws, "orders"))
    q2 = (
        orders.filter(col("customer") == 42)
        .select("customer", "amount")
        .agg(Sum(col("amount")).alias("total"), Count(lit(1)).alias("n"))
    )
    print("\nafter refresh:", q2.to_pydict())

    hs.delete_index("skip_day")
    hs.vacuum_index("skip_day")
    print("\nremaining:", hs.indexes().to_pydict()["name"])


if __name__ == "__main__":
    main()
