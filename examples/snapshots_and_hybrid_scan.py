"""Walkthrough: snapshot tables with index time travel, and Hybrid Scan
over a drifting plain-file source.

Run:  python examples/snapshots_and_hybrid_scan.py
"""

import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from hyperspace_tpu import CoveringIndexConfig, Hyperspace, HyperspaceSession
from hyperspace_tpu import constants as C
from hyperspace_tpu.columnar import io as cio
from hyperspace_tpu.columnar.table import ColumnBatch
from hyperspace_tpu.plan import col
from hyperspace_tpu.sources.delta import SnapshotTable


def main() -> None:
    ws = tempfile.mkdtemp(prefix="hs_snap_")
    session = HyperspaceSession(warehouse_dir=ws)
    session.set_conf(C.INDEX_LINEAGE_ENABLED, True)
    hs = Hyperspace(session)

    # ------------------------------------------------ snapshot time travel
    events = SnapshotTable(os.path.join(ws, "events"))
    events.commit(ColumnBatch.from_pydict({"id": [1, 2, 3], "amt": [10.0, 20.0, 30.0]}))
    hs.create_index(events.scan(session), CoveringIndexConfig("ev_id", ["id"], ["amt"]))

    events.commit(ColumnBatch.from_pydict({"id": [4], "amt": [40.0]}))  # v1
    hs.refresh_index("ev_id", "full")  # index now tracks v1

    session.enable_hyperspace()
    latest = events.scan(session).filter(col("id") == 4).select("amt")
    old = events.scan(session, version=0).filter(col("id") == 2).select("amt")
    print("latest snapshot query:", latest.to_pydict())
    print("v0 time-travel query :", old.to_pydict())
    v0_plan = old.optimized_plan()
    used = [n.index_info.log_version for n in v0_plan.preorder() if getattr(n, "index_info", None)]
    print("v0 served by OLD index log version:", used, "\n")

    # --------------------------------------------------------- hybrid scan
    src = os.path.join(ws, "sales")
    cio.write_parquet(
        ColumnBatch.from_pydict({"k": [1, 2, 3], "v": [1.0, 2.0, 3.0]}),
        os.path.join(src, "p1.parquet"),
    )
    df = session.read.parquet(src)
    session.disable_hyperspace()
    hs.create_index(df, CoveringIndexConfig("sales_k", ["k"], ["v"]))

    # the source drifts: one file appended, nothing refreshed yet
    cio.write_parquet(
        ColumnBatch.from_pydict({"k": [9], "v": [90.0]}),
        os.path.join(src, "p2.parquet"),
    )
    session.enable_hyperspace()
    session.set_conf(C.HYBRID_SCAN_ENABLED, True)
    # tiny demo files: the appended file is ~half the source bytes, above the
    # default 30% ceiling — raise it so the drifted index still qualifies
    session.set_conf(C.HYBRID_SCAN_MAX_APPENDED_RATIO, 0.9)
    q = session.read.parquet(src).filter(col("k") >= 1).select("k", "v")
    print("hybrid scan result (appended row merged at query time):")
    print(" ", q.to_pydict())
    used = [
        n.index_info.index_name
        for n in q.optimized_plan().preorder()
        if getattr(n, "index_info", None)
    ]
    print("index serving the hybrid query:", used or "(none — ratio exceeded)")

    # quick refresh records the delta so hybrid applies even with the
    # global toggle off
    session.set_conf(C.HYBRID_SCAN_ENABLED, False)
    hs.refresh_index("sales_k", "quick")
    q2 = session.read.parquet(src).filter(col("k") >= 1).select("k", "v")
    print("after quick refresh (toggle off):", sorted(q2.to_pydict()["k"]))


if __name__ == "__main__":
    main()
