"""The full-lifecycle tour — every user-facing subsystem in one script.

The analogue of the reference's "Hitchhiker's Guide to Hyperspace"
notebooks (/root/reference/notebooks/python/Hitchhikers Guide to
Hyperspace.ipynb): create indexes, watch queries rewrite, inspect with
explain/whyNot/statistics, mutate the source and use Hybrid Scan +
incremental refresh, compact with optimize, then walk the delete →
restore → vacuum lifecycle.

Run:  python examples/tour.py
"""

import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from hyperspace_tpu import (
    BloomFilterSketch,
    CoveringIndexConfig,
    DataSkippingIndexConfig,
    Hyperspace,
    HyperspaceSession,
    MinMaxSketch,
)
from hyperspace_tpu import constants as C
from hyperspace_tpu.columnar import io as cio
from hyperspace_tpu.columnar.table import ColumnBatch
from hyperspace_tpu.plan import Count, Sum, col, lit


def section(title: str) -> None:
    print(f"\n{'=' * 70}\n{title}\n{'=' * 70}")


def write_sales(path: str, start: int, n: int, seed: int) -> None:
    rng = np.random.default_rng(seed)
    cio.write_parquet(
        ColumnBatch.from_pydict(
            {
                "order_id": list(range(start, start + n)),
                "customer_id": rng.integers(0, 500, n).tolist(),
                "amount": np.round(rng.uniform(5, 500, n), 2).tolist(),
                "region": rng.choice(["NA", "EU", "APAC"], n).tolist(),
            }
        ),
        path,
    )


def main() -> None:
    ws = tempfile.mkdtemp(prefix="hs_tour_")
    sales = os.path.join(ws, "sales")
    for i in range(4):
        write_sales(os.path.join(sales, f"part-{i}.parquet"), i * 25_000, 25_000, i)

    session = HyperspaceSession(warehouse_dir=ws)
    session.set_conf(C.INDEX_LINEAGE_ENABLED, True)  # deletes need lineage
    hs = Hyperspace(session)
    df = session.read.parquet(sales)

    # --- 1. create: one index per kind -----------------------------------
    section("1. createIndex — covering, data-skipping (MinMax + Bloom)")
    hs.create_index(df, CoveringIndexConfig("by_customer", ["customer_id"], ["amount"]))
    hs.create_index(
        df, DataSkippingIndexConfig("sk_order", [MinMaxSketch("order_id")])
    )
    hs.create_index(
        df,
        DataSkippingIndexConfig("sk_bloom", [BloomFilterSketch("customer_id", 500, 0.01)]),
    )
    print(hs.indexes().to_pydict()["name"])

    # --- 2. transparent rewrite ------------------------------------------
    section("2. enableHyperspace — the same query now reads the index")
    session.enable_hyperspace()
    q = (
        session.read.parquet(sales)
        .filter(col("customer_id") == 42)
        .agg(Sum(col("amount")).alias("total"), Count(lit(1)).alias("orders"))
    )
    print("result:", q.to_pydict())
    print(q.explain_plan())

    # --- 3. explain / whyNot / statistics --------------------------------
    section("3. explain(verbose) — plan diff + operator stats")
    print(hs.explain(q, verbose=True))
    section("3b. whyNot — why indexes did NOT serve a query")
    other = session.read.parquet(sales).filter(col("region") == "EU").select("region")
    print(hs.why_not(other))
    section("3c. index statistics")
    print({k: v[0] for k, v in hs.index("by_customer").to_pydict().items()})

    # --- 4. hybrid scan + incremental refresh ----------------------------
    section("4. append source files — Hybrid Scan serves the stale index")
    write_sales(os.path.join(sales, "part-append.parquet"), 100_000, 10_000, 99)
    session.set_conf(C.HYBRID_SCAN_ENABLED, True)
    print("with appended data:", q.to_pydict())
    hs.refresh_index("by_customer", "incremental")
    print("after incremental refresh:", q.to_pydict())
    session.set_conf(C.HYBRID_SCAN_ENABLED, False)

    # --- 5. optimize ------------------------------------------------------
    section("5. optimizeIndex — compact the refresh's small bucket files")
    before = len(hs.get_index("by_customer").index_data_files())
    hs.optimize_index("by_customer", "full")
    after = len(hs.get_index("by_customer").index_data_files())
    print(f"index data files: {before} -> {after}")

    # --- 6. delete / restore / vacuum ------------------------------------
    section("6. lifecycle — delete is soft, restore undoes, vacuum is final")

    def states():
        d = hs.indexes().to_pydict()
        return {str(n): str(s) for n, s in zip(d["name"], d["state"])}

    hs.delete_index("sk_bloom")
    print("after delete:", states())  # DELETED but still listed
    hs.restore_index("sk_bloom")
    print("after restore:", states())
    hs.delete_index("sk_bloom")
    hs.vacuum_index("sk_bloom")
    print("after vacuum:", states())  # gone for good

    section("tour complete")
    print(f"workspace: {ws} (indexes under {ws}/indexes)")


if __name__ == "__main__":
    main()
