"""Layout analysis walkthrough: use the MinMax layout analyzer to decide
which index kind fits a table, then verify the decision with explain().

Reference parity: util/MinMaxAnalysisUtil.scala:768-780 (the standalone
analyzer) + plananalysis/PlanAnalyzer.scala explain rendering.

Run:  python examples/layout_analysis.py
"""

import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from hyperspace_tpu import Hyperspace, HyperspaceSession, ZOrderCoveringIndexConfig
from hyperspace_tpu.analysis.minmax_analysis import analyze
from hyperspace_tpu.columnar import io as cio
from hyperspace_tpu.columnar.table import ColumnBatch
from hyperspace_tpu.plan import Sum, col


def main() -> None:
    ws = tempfile.mkdtemp(prefix="hs_layout_")
    rng = np.random.default_rng(0)

    # Ingest-clustered table: `event_day` arrives in order (disjoint per
    # file), `user_id` is scattered across every file.
    for i in range(8):
        n = 50_000
        cio.write_parquet(
            ColumnBatch.from_pydict(
                {
                    "event_day": (rng.integers(0, 30, n) + i * 30).tolist(),
                    "user_id": rng.integers(0, 100_000, n).tolist(),
                    "amount": rng.uniform(1, 500, n).tolist(),
                }
            ),
            os.path.join(ws, "events", f"part-{i}.parquet"),
        )

    session = HyperspaceSession(warehouse_dir=ws)
    df = session.read.parquet(os.path.join(ws, "events"))

    # 1) Ask the analyzer which columns the layout already serves.
    print(analyze(df, ["event_day", "user_id"], verbose=True))

    # 2) Follow its advice: user_id needs re-clustering; event_day does not.
    hs = Hyperspace(session)
    hs.create_index(
        df, ZOrderCoveringIndexConfig("by_user", ["user_id"], ["amount"])
    )

    # 3) Verify the rewrite with explain().
    q = df.filter(col("user_id") == 4242).agg(Sum(col("amount")).alias("s"))
    session.enable_hyperspace()
    print(hs.explain(q, verbose=True))
    print("result:", q.to_pydict())


if __name__ == "__main__":
    main()
