#!/usr/bin/env python
"""Benchmark: TPC-H Q1/Q3/Q6/Q17 end-to-end, indexed vs raw scans.

Runs the BASELINE.md workloads from hyperspace_tpu.benchmark on generated
TPC-H-shaped data; both sides execute on the same engine (fused device
kernels when a backend initializes in time), so the measured difference is
what the indexes buy: layout, pruning, shuffle-free joins.

Prints ONE JSON line; the primary metric tracks the BASELINE.json north star
("Q3 p50 latency with JoinIndexRule"): the end-to-end indexed-join speedup.
vs_baseline divides the speedup of the indexed path over an EXTERNAL engine
(pandas, the stand-in for BASELINE.md's unavailable 32-core Spark-CPU) by
the 4x target; `q3_speedup_self` stays the same-engine comparison.

Backend strategy: a SUBPROCESS probe first (a hung remote-TPU grant dies
with the subprocess, not the bench), then in-process init with the full
budget only if the probe saw a usable backend.

Env knobs: BENCH_ROWS (lineitem rows, default 4_000_000), BENCH_REPEATS
(default 3), BENCH_JAX_PROBE_TIMEOUT (subprocess probe seconds, default
120), BENCH_JAX_TIMEOUT (in-process budget, default 600), BENCH_FORCE_JAX=1
(skip the probe, init in-process regardless), BENCH_MAX_BUILD_MB (force
hyperspace.tpu.build.maxBytesInMemory, so scale runs exercise streaming
file-group builds).
"""

import json
import os
import subprocess
import sys
import time


def _probe_backend_subprocess(
    timeout_s: float, env_overrides: dict | None = None, label: str = "default-env"
) -> dict:
    """Ask a throwaway subprocess which backend initializes (a hung
    remote-TPU grant dies with the subprocess). Returns a diagnostics dict —
    backend, elapsed, rc, stderr tail — that lands in the bench artifact
    verbatim, so a failed grant leaves evidence instead of a bare None."""
    env = dict(os.environ)
    if env_overrides:
        env.update(env_overrides)
    info: dict = {"label": label, "timeout_s": timeout_s, "env_overrides": env_overrides or {}}
    t0 = time.time()
    try:
        out = subprocess.run(
            [
                sys.executable,
                "-c",
                "import jax; print('BACKEND=' + jax.default_backend()); "
                "print('NDEVICES=%d' % len(jax.devices()))",
            ],
            capture_output=True,
            timeout=timeout_s,
            text=True,
            env=env,
        )
        info["elapsed_s"] = round(time.time() - t0, 1)
        info["rc"] = out.returncode
        info["stderr_tail"] = out.stderr[-2000:]
        for line in out.stdout.splitlines():
            if line.startswith("BACKEND="):
                info["backend"] = line[len("BACKEND="):].strip()
            if line.startswith("NDEVICES="):
                info["n_devices"] = int(line[len("NDEVICES="):])
        if out.returncode != 0:
            info["backend"] = None
        info.setdefault("backend", None)
    except subprocess.TimeoutExpired as e:
        info["elapsed_s"] = round(time.time() - t0, 1)
        info["rc"] = None
        info["backend"] = None
        stderr = e.stderr
        if isinstance(stderr, bytes):
            stderr = stderr.decode(errors="replace")
        info["stderr_tail"] = (stderr or "")[-2000:]
        info["timeout"] = True
    except OSError as e:
        info["elapsed_s"] = round(time.time() - t0, 1)
        info["rc"] = None
        info["backend"] = None
        info["stderr_tail"] = f"OSError: {e}"
    return info


def _host_facts() -> dict:
    """Environment facts for the artifact (self-describing benchmarks)."""
    import platform

    facts: dict = {
        "nproc": os.cpu_count(),
        "platform": platform.platform(),
        "python": platform.python_version(),
    }
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemTotal:"):
                    facts["mem_total_gb"] = round(
                        int(line.split()[1]) / 1024 / 1024, 1
                    )
                    break
    except OSError:
        pass
    for mod in ("numpy", "pandas", "pyarrow", "jax"):
        try:
            facts[mod] = __import__(mod).__version__
        except Exception:
            facts[mod] = None
    facts["env"] = {
        k: os.environ.get(k)
        for k in ("JAX_PLATFORMS", "PALLAS_AXON_POOL_IPS", "XLA_FLAGS")
        if os.environ.get(k) is not None
    }
    return facts


def _jax_backend_or_none(timeout_s: float, platforms: str | None = None):
    """In-process backend init under a watchdog thread (a hung init must
    not cost the whole benchmark; the host paths still measure).
    `platforms` pins jax.config (env vars don't help in-process: a
    sitecustomize may have imported jax already)."""
    import threading

    result = {}

    def init():
        try:
            import jax

            if platforms:
                jax.config.update("jax_platforms", platforms)
            result["backend"] = jax.default_backend()
        except Exception as e:
            result["error"] = str(e)

    t = threading.Thread(target=init, daemon=True)
    t.start()
    t.join(timeout_s)
    return result.get("backend")


def _measure_hybrid_refresh(session, hs, ws: str, timed) -> dict:
    """BASELINE.md config 4: append parquet files to lineitem, run Q3 with
    Hybrid Scan serving the stale index (appended rows re-bucketed on the
    fly), then time the incremental refresh and the post-refresh query."""
    import numpy as np

    from hyperspace_tpu import constants as C
    from hyperspace_tpu.benchmark import TPCH_QUERIES
    from hyperspace_tpu.columnar import io as cio
    from hyperspace_tpu.columnar.table import ColumnBatch

    rng = np.random.default_rng(7)
    n = 50_000
    append = {
        "l_orderkey": rng.integers(0, 1_000_000, n).tolist(),
        "l_partkey": rng.integers(0, 10_000, n).tolist(),
        "l_suppkey": rng.integers(0, 2_500, n).tolist(),
        "l_quantity": rng.integers(1, 51, n).astype(float).tolist(),
        "l_extendedprice": rng.uniform(900, 105_000, n).tolist(),
        "l_discount": np.round(rng.uniform(0, 0.1, n), 2).tolist(),
        "l_tax": np.round(rng.uniform(0, 0.08, n), 2).tolist(),
        "l_returnflag": rng.choice(["A", "N", "R"], n).tolist(),
        "l_linestatus": rng.choice(["O", "F"], n).tolist(),
        "l_shipdate": rng.integers(8035, 10590, n).astype("int32").tolist(),
    }
    cio.write_parquet(
        ColumnBatch.from_pydict(append),
        os.path.join(ws, "lineitem", "part-append.parquet"),
    )
    session.set_conf(C.HYBRID_SCAN_ENABLED, True)
    session.enable_hyperspace()
    q3 = lambda: TPCH_QUERIES["q3"](session, ws).collect()
    t_hybrid = timed(q3)
    from hyperspace_tpu.exceptions import NoChangesError

    t0 = time.time()
    for name in ("li_orderkey", "od_orderkey"):
        try:
            hs.refresh_index(name, "incremental")
        except NoChangesError:
            pass  # orders unchanged: expected; real failures must surface
    refresh_s = time.time() - t0
    t_after = timed(q3)
    session.disable_hyperspace()
    session.set_conf(C.HYBRID_SCAN_ENABLED, False)
    return {
        "q3_hybrid_ms": round(t_hybrid * 1000, 1),
        "refresh_incremental_s": round(refresh_s, 2),
        "q3_after_refresh_ms": round(t_after * 1000, 1),
    }


def _measure_bloom_skipping(session, ws: str, rows: int, timed) -> dict:
    """BASELINE.md config 5: BloomFilterSketch data skipping over a
    store_sales-shaped table (high-cardinality int keys across many files);
    point lookups skip files whose bloom filter rejects the key."""
    import numpy as np

    from hyperspace_tpu import BloomFilterSketch, DataSkippingIndexConfig, Hyperspace
    from hyperspace_tpu.columnar import io as cio
    from hyperspace_tpu.columnar.table import ColumnBatch
    from hyperspace_tpu.plan import Count, Sum, col, lit

    rng = np.random.default_rng(11)
    # sized so the raw side is signal (>=100ms), capped so scale runs stay
    # bounded. 256 files is the shape the sketch exists for: the raw side
    # pays a footer read + stats check per file, the bloom index drops the
    # files BEFORE any IO (ref: BloomFilterSketch.scala:47-87 targets
    # many-file tables).
    n = max(2_000_000, min(rows, 16_000_000))
    n_files = 256
    per = n // n_files
    ss = os.path.join(ws, "store_sales")
    for i in range(n_files):
        data = {
            # item keys are file-local ranges: realistic ingest clustering,
            # so bloom filters reject most files for a point key
            "ss_item_sk": rng.integers(i * 100_000, (i + 1) * 100_000, per).tolist(),
            "ss_net_paid": rng.uniform(1, 300, per).tolist(),
        }
        cio.write_parquet(
            ColumnBatch.from_pydict(data), os.path.join(ss, f"part-{i:02d}.parquet")
        )
    hs = Hyperspace(session)
    df = session.read.parquet(ss)
    t0 = time.time()
    hs.create_index(
        df,
        DataSkippingIndexConfig(
            "ss_bloom", [BloomFilterSketch("ss_item_sk", per, 0.01)]
        ),
    )
    build_s = time.time() - t0
    key = int(rng.integers(3 * 100_000, 4 * 100_000))
    q = lambda: (
        session.read.parquet(ss)
        .filter(col("ss_item_sk") == key)
        .agg(Sum(col("ss_net_paid")).alias("s"), Count(lit(1)).alias("n"))
        .collect()
    )
    t_raw = timed(q)
    session.enable_hyperspace()
    t_idx = timed(q)
    session.disable_hyperspace()
    return {
        "rows": n,
        "files": n_files,
        "index_build_s": round(build_s, 2),
        "raw_ms": round(t_raw * 1000, 1),
        "indexed_ms": round(t_idx * 1000, 1),
        "speedup": round(t_raw / t_idx, 3) if t_idx > 0 else 0.0,
    }


def main() -> None:
    t_start = time.time()
    rows = int(os.environ.get("BENCH_ROWS", 4_000_000))
    repeats = int(os.environ.get("BENCH_REPEATS", 3))

    probe_timeout = float(os.environ.get("BENCH_JAX_PROBE_TIMEOUT", 120))
    init_timeout = float(os.environ.get("BENCH_JAX_TIMEOUT", 600))
    attempts: list[dict] = []
    if os.environ.get("BENCH_FORCE_JAX") == "1":
        probe = "forced"
        backend = _jax_backend_or_none(init_timeout)
        attempts.append({"label": "forced-in-process", "backend": backend})
    else:
        first = _probe_backend_subprocess(probe_timeout, None, "default-env")
        attempts.append(first)
        probe = first["backend"]
        if probe:
            backend = _jax_backend_or_none(init_timeout)
        else:
            # the grant may be env-gated or just slower than the probe
            # window: try the explicit-TPU platform, then one long-budget
            # in-process attempt under the watchdog (the artifact records
            # every attempt's elapsed time and stderr either way)
            tpu_probe = _probe_backend_subprocess(
                probe_timeout, {"JAX_PLATFORMS": "tpu"}, "explicit-tpu"
            )
            attempts.append(tpu_probe)
            # act on a successful explicit-TPU probe: pin the same platform
            # for the in-process init (config update, not env — a
            # sitecustomize may have pinned jax already)
            platforms = "tpu" if tpu_probe.get("backend") else None
            t0 = time.time()
            backend = _jax_backend_or_none(init_timeout, platforms)
            attempts.append(
                {
                    "label": "in-process-long",
                    "timeout_s": init_timeout,
                    "platforms": platforms,
                    "elapsed_s": round(time.time() - t0, 1),
                    "backend": backend,
                }
            )
            if backend:
                probe = "in-process-long"

    import tempfile

    from hyperspace_tpu import Hyperspace, HyperspaceSession
    from hyperspace_tpu import constants as C
    from hyperspace_tpu.benchmark import TPCH_QUERIES, generate_tpch, tpch_indexes

    ws = tempfile.mkdtemp(prefix="hs_bench_")
    sizes = generate_tpch(ws, rows_lineitem=rows, seed=42)
    source_mb = sum(sizes.values()) / 1e6

    session = HyperspaceSession(warehouse_dir=ws)
    session.set_conf(C.INDEX_NUM_BUCKETS, 8)
    session.set_conf(C.EXEC_TPU_ENABLED, backend is not None)
    session.set_conf(C.ZORDER_TARGET_SOURCE_BYTES_PER_PARTITION, 8 * 1024 * 1024)
    index_format = os.environ.get("BENCH_INDEX_FORMAT", "parquet")
    session.set_conf(C.INDEX_FORMAT, index_format)
    build_budget_mb = os.environ.get("BENCH_MAX_BUILD_MB")
    if build_budget_mb:  # scale runs force streaming file-group builds
        session.set_conf(
            C.BUILD_MAX_BYTES_IN_MEMORY, int(build_budget_mb) * 1024 * 1024
        )
    hs = Hyperspace(session)

    t0 = time.time()
    tpch_indexes(session, hs, ws)
    build_s = time.time() - t0
    # bytes actually indexed: lineitem is sliced by four indexes
    indexed_bytes = 4 * sizes["lineitem"] + sizes["orders"] + sizes["part"]
    build_gbps = indexed_bytes / build_s / 1e9

    def timed(fn):
        fn()  # warmup (compilation, page cache)
        times = []
        for _ in range(repeats):
            t0 = time.time()
            fn()
            times.append(time.time() - t0)
        return sorted(times)[len(times) // 2]

    def timed_once(fn):
        """Cheaper probe for tier-choice alternatives: warm + one shot."""
        fn()
        t0 = time.time()
        fn()
        return time.time() - t0

    from hyperspace_tpu.benchmark.external import PANDAS_TPCH

    results = {}
    correct = True
    for name, q in TPCH_QUERIES.items():
        session.disable_hyperspace()
        expected = q(session, ws).to_pydict()
        t_raw = timed(lambda: q(session, ws).collect())
        if backend is not None:
            # raw gets the same tier choice as indexed (fair denominator)
            session.set_conf(C.EXEC_TPU_ENABLED, False)
            t_raw = min(t_raw, timed_once(lambda: q(session, ws).collect()))
            session.set_conf(C.EXEC_TPU_ENABLED, True)
        session.enable_hyperspace()
        got = q(session, ws).to_pydict()
        t_idx = timed(lambda: q(session, ws).collect())
        entry = {"raw_ms": round(t_raw * 1000, 1)}
        if backend is not None:
            # the device tier is a choice, not an obligation: a slow remote
            # tunnel must not make indexed queries lose to their own host
            # path — measure both and let the engine pick (what a cost-based
            # tier selector would do per workload)
            session.set_conf(C.EXEC_TPU_ENABLED, False)
            t_idx_host = timed_once(lambda: q(session, ws).collect())
            session.set_conf(C.EXEC_TPU_ENABLED, True)
            entry["indexed_device_ms"] = round(t_idx * 1000, 1)
            entry["indexed_hostexec_ms"] = round(t_idx_host * 1000, 1)
            entry["exec_tier"] = "device" if t_idx <= t_idx_host else "host"
            t_idx = min(t_idx, t_idx_host)
        session.disable_hyperspace()
        t_ext = timed(lambda: PANDAS_TPCH[name](ws))
        ok = list(got.keys()) == list(expected.keys()) and all(
            len(got[k]) == len(expected[k])
            and all(
                (abs(a - b) <= 1e-6 * max(1.0, abs(b)))
                if isinstance(a, float)
                else a == b
                for a, b in zip(got[k], expected[k])
            )
            for k in got
        )
        correct = correct and ok
        entry.update(
            {
                "indexed_ms": round(t_idx * 1000, 1),
                "external_pandas_ms": round(t_ext * 1000, 1),
                "speedup_self": round(t_raw / t_idx, 3) if t_idx > 0 else 0.0,
                "speedup_vs_external": round(t_ext / t_idx, 3) if t_idx > 0 else 0.0,
            }
        )
        results[name] = entry

    # --- BASELINE.md config 4: hybrid scan + incremental refresh ----------
    hybrid = _measure_hybrid_refresh(session, hs, ws, timed)
    # --- BASELINE.md config 5: bloom-filter skipping on TPC-DS-like keys --
    bloom = _measure_bloom_skipping(session, ws, rows, timed)

    q3_speedup = results["q3"]["speedup_self"]
    q3_vs_external = results["q3"]["speedup_vs_external"]
    tier_counts = None
    if backend is not None:
        # the headline must not hide a device tier that loses every query:
        # say outright how often the device tier actually won
        tiers = [e.get("exec_tier") for e in results.values()]
        tier_counts = {
            "device_wins": tiers.count("device"),
            "host_wins": tiers.count("host"),
        }
    out = {
        "metric": "tpch_q3_join_speedup",
        "value": q3_speedup,
        "unit": "x",
        # BASELINE.md's denominator (32-core Spark-CPU) is not in this image;
        # pandas is the independently-implemented external engine standing in
        "vs_baseline": round(q3_vs_external / 4.0, 3),
        "baseline_denominator": "pandas (external engine; see BASELINE.md note)",
        "queries": results,
        "hybrid_refresh": hybrid,
        "bloom_skipping": bloom,
        "index_build_gbps": round(build_gbps, 4),
        "rows": rows,
        "source_mb": round(source_mb, 1),
        "results_match_raw": correct,
        "backend": backend
        or f"none (probe={probe or 'timeout'}; host paths only)",
        "backend_diagnostics": attempts,
        "exec_tier_summary": tier_counts,
        "host": _host_facts(),
        "build": {
            "max_bytes_in_memory": session.conf.build_max_bytes_in_memory,
            "streaming_forced": bool(build_budget_mb),
            "build_s": round(build_s, 1),
            "index_format": index_format,
        },
        "device_cache": _device_cache_stats(),
        "wall_s": round(time.time() - t_start, 1),
    }
    print(json.dumps(out))


def _device_cache_stats() -> dict:
    try:
        from hyperspace_tpu.utils.device_cache import DEVICE_CACHE, HOST_DERIVED_CACHE

        return {
            "device_hits": DEVICE_CACHE.hits,
            "device_misses": DEVICE_CACHE.misses,
            "host_derived_hits": HOST_DERIVED_CACHE.hits,
            "host_derived_misses": HOST_DERIVED_CACHE.misses,
        }
    except Exception:
        return {}


if __name__ == "__main__":
    main()
