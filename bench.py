#!/usr/bin/env python
"""Benchmark: TPC-H Q1/Q3/Q6/Q10/Q17/Q18 end-to-end, indexed vs raw scans.

Runs the BASELINE.md workloads from hyperspace_tpu.benchmark on generated
TPC-H-shaped data; both sides execute on the same engine, so the measured
difference is what the indexes buy: layout, pruning, shuffle-free joins.

Prints ONE JSON line; the primary metric tracks the BASELINE.json north star
("Q3 p50 latency with JoinIndexRule"): the end-to-end indexed-join speedup.
vs_baseline divides the speedup of the indexed path over an EXTERNAL engine
(pandas, the stand-in for BASELINE.md's unavailable 32-core Spark-CPU) by
the 4x target; `q3_speedup_self` stays the same-engine comparison.

Backend strategy (VERDICT r3 item 3): a GRANT WATCHER thread probes for a
usable jax backend CONCURRENTLY with the host-path measurements, retrying
for the whole bench wall instead of three blocking up-front attempts. Host
paths measure immediately; device sections run whenever (and only if) a
grant lands, even late. Every probe attempt's timestamp/outcome is recorded
in the artifact, so a device-less run carries evidence the tunnel was down
for the whole window, not just at t=0.

Every timing section reports p50/min/max over BENCH_REPEATS runs (r3 item
6), and every device-tier query records its RPC/transfer deltas (r3 item 1:
dispatches, fetches, bytes up/down) so losses are attributable.

Env knobs: BENCH_ROWS (lineitem rows, default 4_000_000), BENCH_REPEATS
(default 3), BENCH_JAX_PROBE_TIMEOUT (per-probe subprocess seconds, default
90), BENCH_JAX_TIMEOUT (in-process init budget, default 600),
BENCH_DEVICE_WAIT (extra seconds to wait for a late grant after host paths
finish, default 600), BENCH_FORCE_JAX=1 (skip the probe, init in-process
regardless), BENCH_MAX_BUILD_MB (force hyperspace.tpu.build
.maxBytesInMemory, so scale runs exercise streaming file-group builds),
BENCH_LIFECYCLE_AUDIT=0 (opt out of the resource-lifecycle audit that is
otherwise on for the whole run; staticcheck.lifecycle_leaks in the
artifact).

`--profile` traces every query into a JSONL span artifact
(BENCH_PROFILE_FILE, default BENCH_profile.jsonl) with one `bench:<section>`
span per section; inspect with tools/trace_report.py. See
docs/observability.md.
"""

import json
import os
import subprocess
import sys
import threading
import time

# resource-lifecycle audit on for the whole bench by default
# (BENCH_LIFECYCLE_AUDIT=0 opts out): leaks flushed out by the bench's own
# workload land in the artifact's staticcheck block as lifecycle_leaks
if os.environ.get("BENCH_LIFECYCLE_AUDIT", "1") == "1":
    os.environ.setdefault("HYPERSPACE_LIFECYCLE_AUDIT", "1")


def _probe_backend_subprocess(
    timeout_s: float, env_overrides: dict | None = None, label: str = "default-env"
) -> dict:
    """Ask a throwaway subprocess which backend initializes (a hung
    remote-TPU grant dies with the subprocess, not the bench)."""
    env = dict(os.environ)
    if env_overrides:
        env.update(env_overrides)
    info: dict = {
        "label": label,
        "timeout_s": timeout_s,
        "env_overrides": env_overrides or {},
        "iso": time.strftime("%Y-%m-%dT%H:%M:%S"),
    }
    t0 = time.time()
    try:
        out = subprocess.run(
            [
                sys.executable,
                "-c",
                "import jax; print('BACKEND=' + jax.default_backend()); "
                "print('NDEVICES=%d' % len(jax.devices()))",
            ],
            capture_output=True,
            timeout=timeout_s,
            text=True,
            env=env,
        )
        info["elapsed_s"] = round(time.time() - t0, 1)
        info["rc"] = out.returncode
        info["stderr_tail"] = out.stderr[-1000:]
        for line in out.stdout.splitlines():
            if line.startswith("BACKEND="):
                info["backend"] = line[len("BACKEND="):].strip()
            if line.startswith("NDEVICES="):
                info["n_devices"] = int(line[len("NDEVICES="):])
        if out.returncode != 0:
            info["backend"] = None
        info.setdefault("backend", None)
    except subprocess.TimeoutExpired as e:
        info["elapsed_s"] = round(time.time() - t0, 1)
        info["rc"] = None
        info["backend"] = None
        stderr = e.stderr
        if isinstance(stderr, bytes):
            stderr = stderr.decode(errors="replace")
        info["stderr_tail"] = (stderr or "")[-1000:]
        info["timeout"] = True
    except OSError as e:
        info["elapsed_s"] = round(time.time() - t0, 1)
        info["rc"] = None
        info["backend"] = None
        info["stderr_tail"] = f"OSError: {e}"
    return info


def _jax_backend_or_none(timeout_s: float, platforms: str | None = None):
    """In-process backend init under a watchdog thread (a hung init must
    not cost the whole benchmark; the host paths still measure)."""
    result = {}

    def init():
        try:
            import jax

            if platforms:
                jax.config.update("jax_platforms", platforms)
            result["backend"] = jax.default_backend()
        except Exception as e:
            result["error"] = str(e)

    t = threading.Thread(target=init, daemon=True)
    t.start()
    t.join(timeout_s)
    return result.get("backend")


class GrantWatcher:
    """Probes for a usable jax backend on a background thread, retrying for
    the whole bench wall. `backend` flips non-None the moment an in-process
    init succeeds; `attempts` is the full probe timeline for the artifact."""

    def __init__(self, probe_timeout: float, init_timeout: float, interval: float = 20):
        self.probe_timeout = probe_timeout
        self.init_timeout = init_timeout
        self.interval = interval
        self.attempts: list[dict] = []
        self.backend: str | None = None
        self._done = threading.Event()
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)

    @staticmethod
    def _cpu_fallback_ok() -> bool:
        return os.environ.get("BENCH_CPU_FALLBACK", "1") == "1"

    def start(self):
        if os.environ.get("BENCH_FORCE_JAX") == "1":
            self.backend = _jax_backend_or_none(self.init_timeout)
            self.attempts.append(
                {"label": "forced-in-process", "backend": self.backend}
            )
            self._done.set()
        else:
            self._thread.start()
        return self

    def _run(self):
        n = 0
        while not self._stop.is_set():
            info = _probe_backend_subprocess(
                self.probe_timeout, None, f"watch-{n}"
            )
            self.attempts.append(info)
            platforms = None
            if not info.get("backend") and not info.get("timeout"):
                # the grant may be env-gated: try the explicit-TPU platform
                # before giving this cycle up. A TIMED-OUT default probe is
                # a hung tunnel — the explicit-TPU probe would hang the same
                # way, so skip it rather than burn a second full timeout.
                tpu_info = _probe_backend_subprocess(
                    self.probe_timeout,
                    {"JAX_PLATFORMS": "tpu"},
                    f"watch-{n}-explicit-tpu",
                )
                self.attempts.append(tpu_info)
                if tpu_info.get("backend"):
                    info = tpu_info
                    platforms = "tpu"
            if not info.get("backend") and self._cpu_fallback_ok():
                # accelerator unavailable or hung: fall back to the CPU
                # backend so device sections still measure the device-tier
                # CODE PATHS this run, instead of re-probing a dead tunnel
                # for the whole bench wall (BENCH_CPU_FALLBACK=0 disables)
                cpu_info = _probe_backend_subprocess(
                    min(self.probe_timeout, 30),
                    {"JAX_PLATFORMS": "cpu"},
                    f"watch-{n}-cpu-fallback",
                )
                self.attempts.append(cpu_info)
                if cpu_info.get("backend"):
                    info = cpu_info
                    platforms = "cpu"
            n += 1
            if info.get("backend"):
                t0 = time.time()
                backend = _jax_backend_or_none(self.init_timeout, platforms)
                self.attempts.append(
                    {
                        "label": "in-process",
                        "platforms": platforms,
                        "elapsed_s": round(time.time() - t0, 1),
                        "backend": backend,
                        "iso": time.strftime("%Y-%m-%dT%H:%M:%S"),
                    }
                )
                if backend:
                    self.backend = backend
                    self._done.set()
                    return
                # transient in-process hiccup: keep retrying — the watcher's
                # contract is the whole bench wall, not one attempt
            self._stop.wait(self.interval)
        self._done.set()

    def wait(self, timeout_s: float) -> str | None:
        """Block up to timeout_s for a grant (used AFTER host paths finish,
        so a late grant still produces device numbers)."""
        self._done.wait(timeout_s)
        return self.backend

    def stop(self):
        self._stop.set()


def _host_facts() -> dict:
    """Environment facts for the artifact (self-describing benchmarks)."""
    import platform

    facts: dict = {
        "nproc": os.cpu_count(),
        "platform": platform.platform(),
        "python": platform.python_version(),
    }
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemTotal:"):
                    facts["mem_total_gb"] = round(
                        int(line.split()[1]) / 1024 / 1024, 1
                    )
                    break
    except OSError:
        pass
    for mod in ("numpy", "pandas", "pyarrow", "jax"):
        try:
            facts[mod] = __import__(mod).__version__
        except Exception:
            facts[mod] = None
    facts["env"] = {
        k: os.environ.get(k)
        for k in ("JAX_PLATFORMS", "PALLAS_AXON_POOL_IPS", "XLA_FLAGS")
        if os.environ.get(k) is not None
    }
    # mesh facts: artifacts from different device topologies are not
    # comparable (tools/bench_compare.py refuses mismatched counts)
    try:
        from hyperspace_tpu.parallel.placement import mesh_enabled
        from hyperspace_tpu.utils.backend import safe_device_count

        facts["devices_visible"] = safe_device_count()
        facts["mesh_enabled"] = mesh_enabled()
    except Exception:
        facts["devices_visible"] = None
        facts["mesh_enabled"] = False
    try:
        from hyperspace_tpu import native

        facts["native"] = native.build_facts()
    except Exception:
        facts["native"] = None
    return facts


def _stats(times: list[float]) -> dict:
    times = sorted(times)
    return {
        "p50_ms": round(times[len(times) // 2] * 1000, 1),
        "min_ms": round(times[0] * 1000, 1),
        "max_ms": round(times[-1] * 1000, 1),
        "n": len(times),
    }


def _timed(fn, repeats: int):
    """Warm once (compilation, page cache, device cache), then measure
    `repeats` runs. Returns (p50 seconds, stats dict)."""
    fn()
    times = []
    for _ in range(repeats):
        t0 = time.time()
        fn()
        times.append(time.time() - t0)
    return sorted(times)[len(times) // 2], _stats(times)


def _rpc_delta(fn):
    """One run of fn with the RPC meter delta captured around it."""
    from hyperspace_tpu.utils.rpc_meter import METER

    with METER.measure() as m:
        fn()
    return m.delta


def _bench_span(name: str):
    """A `bench:<section>` span when --profile is on (no-op otherwise), so
    the JSONL artifact groups query spans by bench section."""
    from hyperspace_tpu.telemetry import trace

    return trace.span(f"bench:{name}")


def _measure_point_lookup(session, ws: str, repeats: int) -> dict:
    """Index-pruning showcase: a point lookup on the li_orderkey covering
    index bucket-prunes to 1/num_buckets of the index files and row-group
    -skips within the kept bucket (sorted runs + footer stats). The raw
    side scans every lineitem file. Counter deltas land in the artifact so
    tools/bench_compare.py can diff the pruning win."""
    from hyperspace_tpu.plan import Count, Sum, col, lit

    key = 12345
    q = lambda: (
        session.read.parquet(os.path.join(ws, "lineitem"))
        .filter(col("l_orderkey") == key)
        .agg(Sum(col("l_extendedprice")).alias("s"), Count(lit(1)).alias("n"))
        .collect()
    )
    session.disable_hyperspace()
    t_raw, raw_stats = _timed(q, repeats)
    session.enable_hyperspace()
    _, prune_delta = _prefix_counter_delta(q, "pruning.")
    t_idx, idx_stats = _timed(q, repeats)
    session.disable_hyperspace()
    return {
        "raw_ms": round(t_raw * 1000, 1),
        "raw_stats": raw_stats,
        "indexed_ms": round(t_idx * 1000, 1),
        "indexed_stats": idx_stats,
        "speedup": round(t_raw / t_idx, 3) if t_idx > 0 else 0.0,
        "pruning": prune_delta,
    }


def _measure_sketch_prune(session, ws: str, rows: int, repeats: int) -> dict:
    """Per-row-group sketch pruning showcase: Eq/IN on NON-sort columns of
    a covering index. Three legs per query: raw (no index), minmax-only
    (HYPERSPACE_SKETCHES=0 — the pre-sketch engine: a predicate that never
    touches the leading indexed column cannot use the index at all), and
    sketches-on (bloom/value-list/z-region sidecars skip row groups).
    Every leg's result feeds results_match; pruning counter deltas
    (bytes_skipped included) land in the artifact per query for
    tools/bench_compare.py."""
    import numpy as np

    from hyperspace_tpu import CoveringIndexConfig, Hyperspace
    from hyperspace_tpu.columnar import io as cio
    from hyperspace_tpu.columnar.table import ColumnBatch
    from hyperspace_tpu.plan import col

    n = max(400_000, min(rows, 4_000_000))
    n_files = 16
    per = n // n_files
    root = os.path.join(ws, "events_sk")
    rng = np.random.default_rng(23)
    cat_div = max(1, n // 64)
    for i in range(n_files):
        k = np.arange(per, dtype=np.int64) + i * per
        data = {
            "ev_k": k.tolist(),
            # high-NDV monotone id and low-NDV time-bucket dimension, both
            # clustered with the sort key (the ingest-ordered shape the
            # sketch store exists for)
            "ev_id": (k + 10_000_000).tolist(),
            "ev_cat": (k // cat_div).tolist(),
            "ev_v": rng.uniform(0, 100, per).tolist(),
        }
        cio.write_parquet(
            ColumnBatch.from_pydict(data),
            os.path.join(root, f"part-{i:02d}.parquet"),
        )
    prev = os.environ.get("HYPERSPACE_SKETCHES")
    os.environ["HYPERSPACE_SKETCHES"] = "1"
    out: dict = {"rows": n, "files": n_files}
    match = True
    try:
        hs = Hyperspace(session)
        t0 = time.time()
        hs.create_index(
            session.read.parquet(root),
            CoveringIndexConfig("ev_sk_idx", ["ev_k"], ["ev_id", "ev_cat", "ev_v"]),
        )
        out["index_build_s"] = round(time.time() - t0, 2)
        key = int(10_000_000 + n * 5 // 8 + 17)
        cats = [3, int((n - 1) // cat_div) - 1]
        # sorted on the unique key: the raw scan and the bucketed index
        # scan emit rows in different physical orders — the sort makes the
        # three-leg comparison order-exact without changing what is scanned
        queries = {
            "eq": lambda: (
                session.read.parquet(root)
                .filter(col("ev_id") == key)
                .select("ev_k", "ev_id", "ev_cat")
                .sort("ev_k")
                .to_pydict()
            ),
            "in": lambda: (
                session.read.parquet(root)
                .filter(col("ev_cat").isin(cats))
                .select("ev_k", "ev_cat")
                .sort("ev_k")
                .to_pydict()
            ),
        }

        def bits(d):
            return {
                k: [x.hex() if isinstance(x, float) else x for x in v]
                for k, v in d.items()
            }

        for name, q in queries.items():
            session.disable_hyperspace()
            ref = q()
            t_raw, raw_stats = _timed(q, repeats)
            session.enable_hyperspace()
            os.environ["HYPERSPACE_SKETCHES"] = "0"
            got_mm = q()
            t_mm, mm_stats = _timed(q, repeats)
            os.environ["HYPERSPACE_SKETCHES"] = "1"
            got_sk, prune_delta = _prefix_counter_delta(q, "pruning.")
            t_sk, sk_stats = _timed(q, repeats)
            session.disable_hyperspace()
            match = match and bits(got_mm) == bits(ref) == bits(got_sk)
            out[name] = {
                "raw_ms": round(t_raw * 1000, 1),
                "raw_stats": raw_stats,
                "minmax_only_ms": round(t_mm * 1000, 1),
                "minmax_only_stats": mm_stats,
                "sketch_ms": round(t_sk * 1000, 1),
                "sketch_stats": sk_stats,
                "speedup_vs_raw": round(t_raw / t_sk, 3) if t_sk > 0 else 0.0,
                "speedup_vs_minmax": round(t_mm / t_sk, 3) if t_sk > 0 else 0.0,
                "pruning": prune_delta,
            }
    finally:
        if prev is None:
            os.environ.pop("HYPERSPACE_SKETCHES", None)
        else:
            os.environ["HYPERSPACE_SKETCHES"] = prev
        session.disable_hyperspace()
    out["results_match"] = match
    return out


def _measure_approx_tier(session, ws: str, rows: int, repeats: int) -> dict:
    """Approximate query tier showcase: sampled execution with error bounds
    and deadline-driven degradation, on a dedicated join fixture (high-NDV
    join key, skew-free — the shape the universe-sampling tier accepts).

    Three leg families land in the artifact:

    - **exact leg**: the covering-index join with the tier idle (twins on
      disk, nothing requested) — checked bit-identical to a
      HYPERSPACE_APPROX=0 run (the tier is invisible until asked for) and
      value-equal to the raw scan; both feed ``results_match``;
    - **sampled legs**, one per configured fraction: latency, speedup vs
      the exact leg, relative error vs the exact answer, CI half-width
      relative to the answer, and whether every 95% CI covered exact
      (coverage feeds ``results_match`` — honest bounds are correctness);
    - **degrade leg**: the serve scheduler learns the exact-tier wall over
      three runs, then a submit with a 5x-tighter deadline and
      allow_approx (the default) must come back from the sampled tier;
      fraction, wall, and speedup vs exact are recorded.

    The fixture's indexes are built with HYPERSPACE_APPROX=1 so the create
    path writes sample twins (the TPC-H indexes above are built with the
    tier off and have none); the env var is restored on exit, so no other
    section sees the tier. ``speedup_ok`` records the >=5x acceptance bar
    at the smallest-latency sampled leg.
    """
    import numpy as np

    from hyperspace_tpu import CoveringIndexConfig, Hyperspace, serve
    from hyperspace_tpu.columnar import io as cio
    from hyperspace_tpu.columnar.table import ColumnBatch
    from hyperspace_tpu.models import sample_store
    from hyperspace_tpu.plan import Count, Sum, col, lit, sampling
    from hyperspace_tpu.serve import qos
    from hyperspace_tpu.telemetry import plan_stats

    n = int(
        os.environ.get("BENCH_APPROX_ROWS", max(400_000, min(rows, 2_000_000)))
    )
    n_files = 8
    per = n // n_files
    n_dim = max(1024, n // 8)
    fact_root = os.path.join(ws, "apx_fact")
    dim_root = os.path.join(ws, "apx_dim")
    rng = np.random.default_rng(29)
    for i in range(n_files):
        cio.write_parquet(
            ColumnBatch.from_pydict(
                {
                    "fk": rng.integers(0, n_dim, per).astype(np.int64).tolist(),
                    "amt": rng.uniform(1.0, 100.0, per).tolist(),
                }
            ),
            os.path.join(fact_root, f"part-{i:02d}.parquet"),
        )
    cio.write_parquet(
        ColumnBatch.from_pydict(
            {
                "ok": np.arange(n_dim, dtype=np.int64).tolist(),
                "dt": rng.integers(0, 10_000, n_dim).tolist(),
            }
        ),
        os.path.join(dim_root, "part-00.parquet"),
    )

    prev = os.environ.get("HYPERSPACE_APPROX")
    os.environ["HYPERSPACE_APPROX"] = "1"
    res: dict = {"rows": n, "dim_rows": n_dim}
    try:
        hs = Hyperspace(session)
        t0 = time.time()
        hs.create_index(
            session.read.parquet(fact_root),
            CoveringIndexConfig("apx_fact_idx", ["fk"], ["amt"]),
        )
        hs.create_index(
            session.read.parquet(dim_root),
            CoveringIndexConfig("apx_dim_idx", ["ok"], ["dt"]),
        )
        res["index_build_s"] = round(time.time() - t0, 2)

        def q():
            f = session.read.parquet(fact_root)
            d = session.read.parquet(dim_root)
            return (
                f.join(d, col("fk") == col("ok"))
                .filter(col("dt") < 5000)
                .agg(Sum(col("amt")).alias("rev"), Count(lit(1)).alias("n"))
            )

        def bits(dd):
            return {
                k: [x.hex() if isinstance(x, float) else x for x in v]
                for k, v in dd.items()
            }

        session.disable_hyperspace()
        raw = q().to_pydict()
        session.enable_hyperspace()
        exact = q().to_pydict()
        os.environ["HYPERSPACE_APPROX"] = "0"
        exact_off = q().to_pydict()
        os.environ["HYPERSPACE_APPROX"] = "1"
        # tier idle == tier absent, bit for bit; index == raw to tolerance
        match = bits(exact) == bits(exact_off)
        match = match and all(
            abs(float(exact[k][0]) - float(raw[k][0]))
            <= 1e-6 * max(1.0, abs(float(raw[k][0])))
            for k in exact
        )
        t_exact, exact_stats = _timed(lambda: q().collect(), repeats)
        res["exact_ms"] = round(t_exact * 1000, 1)
        res["exact_stats"] = exact_stats

        legs: dict = {}
        best_speedup = 0.0
        for frac in sorted(sample_store.sample_fractions(), reverse=True):
            with plan_stats.collect_scope() as cap:
                with sampling.approx_scope(frac):
                    est = q().to_pydict()
            info = (cap.summary() or {}).get("approx") or {}
            outs = info.get("outputs") or {}
            leg: dict = {"engaged": bool(outs)}
            if not outs:
                leg["reason"] = info.get("reason")
            else:
                with sampling.approx_scope(frac):
                    t_s, s_stats = _timed(lambda: q().collect(), repeats)
                leg["sampled_ms"] = round(t_s * 1000, 1)
                leg["sampled_stats"] = s_stats
                leg["speedup_vs_exact"] = (
                    round(t_exact / t_s, 3) if t_s > 0 else 0.0
                )
                best_speedup = max(best_speedup, leg["speedup_vs_exact"])
                covered = True
                rel_errs, rel_cis = [], []
                for name in ("rev", "n"):
                    ex = float(exact[name][0])
                    err = abs(float(est[name][0]) - ex)
                    ci = float(outs[name]["ci95_max"])
                    covered = covered and err <= ci
                    rel_errs.append(err / max(1.0, abs(ex)))
                    rel_cis.append(ci / max(1.0, abs(ex)))
                leg["rel_err_max"] = round(max(rel_errs), 5)
                leg["ci_rel_max"] = round(max(rel_cis), 5)
                leg["ci_covers_exact"] = covered
                match = match and covered
            legs[f"f{frac:g}"] = leg
        res["sampled"] = legs
        res["best_sampled_speedup"] = best_speedup
        res["speedup_ok"] = best_speedup >= 5.0

        sched = serve.QueryScheduler(max_concurrent=2, queue_depth=64)
        try:
            label = "bench-approx-join"
            for _ in range(3):  # teach the cost model the exact-tier wall
                sched.submit(lambda: q().collect(), label=label).result(
                    timeout=600
                )
            predicted = qos.COST_MODEL.predict(label)
            deadline = max(0.005, predicted / 5.0)
            t0 = time.time()
            h = sched.submit(
                lambda: q().collect(), label=label, deadline_s=deadline
            )
            h.result(timeout=600)
            wall = time.time() - t0
            res["degrade"] = {
                "predicted_exact_s": round(predicted, 4),
                "deadline_s": round(deadline, 4),
                "degraded_fraction": h.ctx.approx_fraction,
                "degraded_ms": round(wall * 1000, 1),
                "speedup_vs_exact": (
                    round(t_exact / wall, 3) if wall > 0 else 0.0
                ),
                "within_deadline": wall <= deadline,
            }
        finally:
            sched.shutdown()
    finally:
        if prev is None:
            os.environ.pop("HYPERSPACE_APPROX", None)
        else:
            os.environ["HYPERSPACE_APPROX"] = prev
        session.disable_hyperspace()
    res["results_match"] = match
    return res


def _qps_stats(latencies: list[float]) -> dict:
    """p50/p99/min/max over per-query latencies (submission → result)."""
    xs = sorted(latencies)
    n = len(xs)
    if not n:
        return {"n": 0}
    return {
        "p50_ms": round(xs[n // 2] * 1000, 1),
        "p99_ms": round(xs[min(n - 1, (n * 99) // 100)] * 1000, 1),
        "min_ms": round(xs[0] * 1000, 1),
        "max_ms": round(xs[-1] * 1000, 1),
        "n": n,
    }


def _measure_sustained_qps(session, ws: str) -> dict:
    """Sustained multi-query throughput through the serving layer
    (serve/scheduler.py) over the TPC-H mix, host tier.

    Closed loop: C client threads (C in 1/4/8) each run the whole mix
    BENCH_QPS_PASSES times back-to-back through ONE shared scheduler
    (max_concurrent=C) — the classic saturating-clients shape; aggregate
    QPS and per-query p50/p99 latency (queue wait included) per C, with
    the 1-client run as the serial baseline QPS. Every served result is
    verified bit-identical (`float.hex()`) to a serial reference computed
    up front, so `results_match` here feeds the artifact's top-level
    `results_match_raw`.

    Open loop: queries submitted on a fixed arrival schedule at ~1.5x the
    4-client closed-loop rate regardless of completion (the overload
    shape); reports offered vs achieved QPS, latency percentiles, and
    admission rejections (bounded run queue shedding load).

    BENCH_QPS=0 skips the section; BENCH_QPS_CLIENTS / BENCH_QPS_PASSES
    override the sweep."""
    import threading as _threading

    from hyperspace_tpu import serve
    from hyperspace_tpu.benchmark import TPCH_QUERIES

    client_counts = [
        int(c)
        for c in os.environ.get("BENCH_QPS_CLIENTS", "1,4,8").split(",")
        if c.strip()
    ]
    passes = int(os.environ.get("BENCH_QPS_PASSES", 2))
    names = list(TPCH_QUERIES)
    session.enable_hyperspace()

    def _bits(d: dict) -> str:
        return repr(
            {
                k: [x.hex() if isinstance(x, float) else x for x in v]
                for k, v in d.items()
            }
        )

    # serial reference on the exact config the served runs use (also warms
    # caches so the measured sweep is the steady serving state)
    reference = {
        name: _bits(TPCH_QUERIES[name](session, ws).to_pydict())
        for name in names
    }
    match = {"ok": True}

    def _run_client(sched, tid: int, latencies: list) -> None:
        for p in range(passes):
            off = (tid + p) % len(names)
            for name in names[off:] + names[:off]:
                t0 = time.perf_counter()
                h = sched.submit_query(
                    TPCH_QUERIES[name](session, ws), label=name
                )
                got = h.result(timeout=600)
                latencies.append(time.perf_counter() - t0)
                if _bits(got.to_pydict()) != reference[name]:
                    match["ok"] = False

    from hyperspace_tpu.telemetry.attribution import LEDGER, phase_percentiles

    closed: dict[str, dict] = {}
    for c in client_counts:
        ledger_mark = LEDGER.last_seq()
        sched = serve.QueryScheduler(
            max_concurrent=c, queue_depth=max(64, c * len(names) * passes)
        )
        per_client: list[list] = [[] for _ in range(c)]
        threads = [
            _threading.Thread(
                target=_run_client, args=(sched, i, per_client[i]),
                name=f"bench-qps-{i}",
            )
            for i in range(c)
        ]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
        sched.shutdown(wait=True)
        lat = [x for xs in per_client for x in xs]
        closed[f"c{c}"] = {
            "clients": c,
            "queries": len(lat),
            "wall_s": round(wall, 3),
            "qps": round(len(lat) / wall, 3) if wall > 0 else 0.0,
            **_qps_stats(lat),
            # mean/p99 per phase (plan/io/upload/dispatch/fetch/fold +
            # queue/total) over exactly this tier's serving window, from
            # the per-query attribution ledger
            "phases": phase_percentiles(
                LEDGER.recent_records(since_seq=ledger_mark)
            ),
        }

    # open loop at ~1.5x the best closed-loop rate: arrivals keep coming
    # regardless of completions, so queueing (and, past the bounded run
    # queue, load shedding) is part of the measurement
    base_qps = max(
        (e["qps"] for e in closed.values()), default=1.0
    )
    offered_qps = max(0.5, 1.5 * base_qps)
    interval = 1.0 / offered_qps
    n_submit = max(12, 2 * len(names))
    ledger_mark = LEDGER.last_seq()
    sched = serve.QueryScheduler(max_concurrent=4, queue_depth=len(names))
    handles: list = []
    rejected = 0
    t0 = time.perf_counter()
    for i in range(n_submit):
        target = t0 + i * interval
        delay = target - time.perf_counter()
        if delay > 0:
            time.sleep(delay)
        name = names[i % len(names)]
        try:
            handles.append(
                (name, time.perf_counter(),
                 sched.submit_query(TPCH_QUERIES[name](session, ws),
                                    label=f"open:{name}"))
            )
        except serve.AdmissionRejected:
            rejected += 1
    lat = []
    for name, t_submit, h in handles:
        got = h.result(timeout=600)
        lat.append(time.perf_counter() - t_submit)
        if _bits(got.to_pydict()) != reference[name]:
            match["ok"] = False
    wall = time.perf_counter() - t0
    sched.shutdown(wait=True)
    session.disable_hyperspace()

    out = {
        "closed": closed,
        "open": {
            "offered_qps": round(offered_qps, 3),
            "achieved_qps": round(len(lat) / wall, 3) if wall > 0 else 0.0,
            "submitted": n_submit,
            "completed": len(lat),
            "rejected": rejected,
            **_qps_stats(lat),
            "phases": phase_percentiles(
                LEDGER.recent_records(since_seq=ledger_mark)
            ),
        },
        "passes": passes,
        "results_match": match["ok"],
    }
    if "c1" in closed and "c4" in closed and closed["c1"]["qps"] > 0:
        out["qps_scaling_c4_vs_c1"] = round(
            closed["c4"]["qps"] / closed["c1"]["qps"], 3
        )
    return out


def _measure_multi_tenant(session, ws: str) -> dict:
    """Hog-vs-light tenant isolation through the QoS scheduler: ONE hog
    tenant floods heavy join queries ahead of BENCH_TENANT_LIGHT light
    tenants submitting a cheap aggregate, through one scheduler, twice —
    QoS off (everyone on the default tenant: the pre-QoS FIFO order) and
    QoS on (per-tenant weighted-fair queues). Reports hog/light queue-wait
    p50/p99 for both legs and their ratio; with QoS on the light tenants'
    p99 queue wait must drop (they stop waiting behind the whole hog
    backlog), while every served result stays bit-identical to the serial
    reference — verified into ``results_match``. BENCH_TENANT=0 skips."""
    from hyperspace_tpu import serve
    from hyperspace_tpu.benchmark import TPCH_QUERIES
    from hyperspace_tpu.serve.tenant import TENANTS

    n_hog = int(os.environ.get("BENCH_TENANT_HOG", 10))
    n_light = int(os.environ.get("BENCH_TENANT_LIGHT", 8))
    heavy_name, light_name = "q3", "q6"
    session.enable_hyperspace()

    def _bits(d: dict) -> str:
        return repr(
            {
                k: [x.hex() if isinstance(x, float) else x for x in v]
                for k, v in d.items()
            }
        )

    reference = {
        name: _bits(TPCH_QUERIES[name](session, ws).to_pydict())
        for name in (heavy_name, light_name)
    }
    match = {"ok": True}

    def _pctls(waits_ms: list) -> dict:
        xs = sorted(waits_ms)
        if not xs:
            return {"p50_ms": 0.0, "p99_ms": 0.0}
        return {
            "p50_ms": round(xs[len(xs) // 2], 3),
            "p99_ms": round(xs[min(len(xs) - 1, int(0.99 * len(xs)))], 3),
        }

    def run_leg(use_tenants: bool) -> dict:
        sched = serve.QueryScheduler(max_concurrent=1, queue_depth=512)
        try:
            hog_handles = [
                sched.submit_query(
                    TPCH_QUERIES[heavy_name](session, ws), label="hog",
                    tenant="hog" if use_tenants else None,
                )
                for _ in range(n_hog)
            ]
            light_handles = [
                sched.submit_query(
                    TPCH_QUERIES[light_name](session, ws), label=f"light{i}",
                    tenant=f"light{i}" if use_tenants else None,
                )
                for i in range(n_light)
            ]
            hog_waits, light_waits = [], []
            for h in hog_handles:
                if _bits(h.result(600).to_pydict()) != reference[heavy_name]:
                    match["ok"] = False
                hog_waits.append(h.queue_wait_s * 1000)
            for h in light_handles:
                if _bits(h.result(600).to_pydict()) != reference[light_name]:
                    match["ok"] = False
                light_waits.append(h.queue_wait_s * 1000)
            return {
                "hog": _pctls(hog_waits),
                "light": _pctls(light_waits),
            }
        finally:
            sched.shutdown(wait=True)

    off = run_leg(use_tenants=False)
    on = run_leg(use_tenants=True)
    TENANTS.reset_for_testing()
    session.disable_hyperspace()
    out = {
        "hog_queries": n_hog,
        "light_tenants": n_light,
        "heavy_query": heavy_name,
        "light_query": light_name,
        "off": off,
        "on": on,
        "light_p99_off_ms": off["light"]["p99_ms"],
        "light_p99_on_ms": on["light"]["p99_ms"],
        "light_p50_off_ms": off["light"]["p50_ms"],
        "light_p50_on_ms": on["light"]["p50_ms"],
        "results_match": match["ok"],
    }
    if on["light"]["p99_ms"] > 0:
        out["light_p99_isolation_x"] = round(
            off["light"]["p99_ms"] / on["light"]["p99_ms"], 3
        )
    return out


def _measure_spill_join(session, ws: str) -> dict:
    """Memory-adaptive spilling join: the TPC-H join queries re-run on the
    device tier at a deliberately tiny device-memory grant
    (BENCH_SPILL_BUDGET_MB, default 0.25 MB) so every band wave exceeds
    the ledger and must park/spill instead of declining to the host tier.
    Four configurations of the SAME engine must be bit-identical
    (float.hex): unconstrained adaptive (default grant), the
    HYPERSPACE_PIPELINE=0 barrier path, the constrained (spilling) run,
    and a CONCURRENT leg pushing 2 spilling joins through one scheduler
    sharing the single device ledger. The raw (hyperspace-off) reference
    is compared under the bench's standard float tolerance — together
    these feed the section's ``results_match_raw``. BENCH_SPILL=0 skips
    the section."""
    from hyperspace_tpu import serve
    from hyperspace_tpu import constants as C
    from hyperspace_tpu.benchmark import TPCH_QUERIES
    from hyperspace_tpu.serve import budget as serve_budget
    from hyperspace_tpu.telemetry.metrics import REGISTRY

    names = [n for n in ("q3", "q10") if n in TPCH_QUERIES]
    budget_mb = os.environ.get("BENCH_SPILL_BUDGET_MB", "0.25")

    def _bits(d: dict) -> str:
        return repr(
            {
                k: [x.hex() if isinstance(x, float) else x for x in v]
                for k, v in d.items()
            }
        )

    def _close(got: dict, want: dict) -> bool:
        return list(got.keys()) == list(want.keys()) and all(
            len(got[k]) == len(want[k])
            and all(
                (abs(a - b) <= 1e-6 * max(1.0, abs(b)))
                if isinstance(a, float)
                else a == b
                for a, b in zip(got[k], want[k])
            )
            for k in got
        )

    session.disable_hyperspace()
    raw = {name: TPCH_QUERIES[name](session, ws).to_pydict() for name in names}
    session.enable_hyperspace()
    session.set_conf(C.EXEC_TPU_ENABLED, True)
    prior_budget = os.environ.get("HYPERSPACE_DEVICE_BUDGET_MB")
    prior_pipeline = os.environ.get("HYPERSPACE_PIPELINE")
    bit_ok = True
    raw_ok = True
    try:
        os.environ["HYPERSPACE_PIPELINE"] = "1"
        # ---- unconstrained adaptive: the no-spill reference --------------
        serve_budget.reset_device_budget()
        reference = {}
        t_un = 0.0
        for name in names:
            got = TPCH_QUERIES[name](session, ws).to_pydict()
            reference[name] = _bits(got)
            raw_ok = raw_ok and _close(got, raw[name])
            t, _ = _timed(lambda: TPCH_QUERIES[name](session, ws).collect(), 1)
            t_un += t
        # ---- barrier path (PIPELINE=0) at the default grant --------------
        os.environ["HYPERSPACE_PIPELINE"] = "0"
        for name in names:
            bit_ok = bit_ok and (
                _bits(TPCH_QUERIES[name](session, ws).to_pydict())
                == reference[name]
            )
        os.environ["HYPERSPACE_PIPELINE"] = "1"
        # ---- constrained: every wave over-budget -> park/spill ------------
        os.environ["HYPERSPACE_DEVICE_BUDGET_MB"] = budget_mb
        serve_budget.reset_device_budget()
        parks0 = REGISTRY.counter("join.spill.parks").value
        spills0 = REGISTRY.counter("join.spill.spills").value
        t_con = 0.0
        for name in names:
            bit_ok = bit_ok and (
                _bits(TPCH_QUERIES[name](session, ws).to_pydict())
                == reference[name]
            )
            t, _ = _timed(lambda: TPCH_QUERIES[name](session, ws).collect(), 1)
            t_con += t
        parks = REGISTRY.counter("join.spill.parks").value - parks0
        spills = REGISTRY.counter("join.spill.spills").value - spills0
        # ---- concurrent leg: 2 spilling joins share the one ledger --------
        cparks0 = REGISTRY.counter("join.spill.parks").value
        sched = serve.QueryScheduler(max_concurrent=2, queue_depth=8)
        try:
            handles = [
                sched.submit_query(
                    TPCH_QUERIES[names[0]](session, ws), label=f"spill:{i}"
                )
                for i in range(2)
            ]
            for h in handles:
                bit_ok = bit_ok and (
                    _bits(h.result(timeout=600).to_pydict())
                    == reference[names[0]]
                )
        finally:
            sched.shutdown(wait=True)
        concurrent_parks = REGISTRY.counter("join.spill.parks").value - cparks0
        acct = serve_budget.device_budget()
        ledger_drained = acct.held_bytes() == 0 and acct.check_consistency()
    finally:
        if prior_budget is None:
            os.environ.pop("HYPERSPACE_DEVICE_BUDGET_MB", None)
        else:
            os.environ["HYPERSPACE_DEVICE_BUDGET_MB"] = prior_budget
        if prior_pipeline is None:
            os.environ.pop("HYPERSPACE_PIPELINE", None)
        else:
            os.environ["HYPERSPACE_PIPELINE"] = prior_pipeline
        serve_budget.reset_device_budget()
        session.set_conf(C.EXEC_TPU_ENABLED, False)
        session.disable_hyperspace()
    return {
        "device_budget_mb": float(budget_mb),
        "queries": names,
        "unconstrained_ms": round(t_un * 1000, 1),
        "constrained_ms": round(t_con * 1000, 1),
        "spill_overhead_pct": round(100.0 * (t_con - t_un) / t_un, 1)
        if t_un > 0
        else 0.0,
        "parks": parks,
        "spills": spills,
        "concurrent_parks": concurrent_parks,
        "spilling_engaged": parks > 0 and spills > 0,
        "ledger_drained": ledger_drained,
        "bit_identical": bit_ok,
        "results_match_raw": bool(raw_ok and bit_ok and ledger_drained),
    }


def _measure_adaptive(session, ws: str) -> dict:
    """Mid-query adaptive re-optimization (HYPERSPACE_ADAPTIVE): two legs.

    TPC-H leg: the join queries re-run adaptive-on vs adaptive-off at the
    default grant. Honest footer stats mean no switch should fire, and
    adaptive-on must stay bit-identical (float.hex) to static and within
    tolerance of the raw reference — the monitoring is pure overhead
    accounting here, reported as ``adaptive_overhead_pct``.

    Planted leg: a dedicated 150k-row join fixture whose footer byte
    stats are tampered 64x low under a 2 MB grant. The static banded
    plan reserves pow2-padded band waves (~2x the decoded bytes) and
    parks on the device ledger; the adaptive run observes decoded
    actuals per bucket pair, flips banded->split (``adaptive.replan``),
    and must finish with strictly fewer parks+spills and the exact
    static bits. BENCH_ADAPT=0 skips the section."""
    import numpy as np

    from hyperspace_tpu import CoveringIndexConfig, Hyperspace
    from hyperspace_tpu import constants as C
    from hyperspace_tpu.benchmark import TPCH_QUERIES
    from hyperspace_tpu.columnar import io as cio
    from hyperspace_tpu.columnar.table import ColumnBatch
    from hyperspace_tpu.plan import Count, Max, Min, col, lit
    from hyperspace_tpu.plan import join_memory
    from hyperspace_tpu.serve import budget as serve_budget
    from hyperspace_tpu.telemetry.metrics import REGISTRY

    names = [n for n in ("q3", "q10") if n in TPCH_QUERIES]

    def _bits(d: dict) -> str:
        return repr(
            {
                k: [x.hex() if isinstance(x, float) else x for x in v]
                for k, v in d.items()
            }
        )

    def _close(got: dict, want: dict) -> bool:
        return list(got.keys()) == list(want.keys()) and all(
            len(got[k]) == len(want[k])
            and all(
                (abs(a - b) <= 1e-6 * max(1.0, abs(b)))
                if isinstance(a, float)
                else a == b
                for a, b in zip(got[k], want[k])
            )
            for k in got
        )

    def _cnt(name: str) -> float:
        return REGISTRY.counter(name).value

    def _switches() -> float:
        return (
            _cnt("adaptive.replan")
            + _cnt("adaptive.reorder")
            + _cnt("adaptive.abort")
        )

    session.disable_hyperspace()
    raw = {name: TPCH_QUERIES[name](session, ws).to_pydict() for name in names}
    session.enable_hyperspace()
    session.set_conf(C.EXEC_TPU_ENABLED, True)
    prior_env = {
        k: os.environ.get(k)
        for k in (
            "HYPERSPACE_ADAPTIVE",
            "HYPERSPACE_DEVICE_BUDGET_MB",
            "HYPERSPACE_JOIN_BROADCAST_ROWS",
            "HYPERSPACE_PARK_WAIT_MS",
            "HYPERSPACE_ADAPTIVE_WARMUP_CHUNKS",
        )
    }
    prior_buckets = session.conf.num_buckets
    real_estimates = join_memory._bucket_estimates
    raw_ok = True
    try:
        # ---- TPC-H leg: honest stats, default grant ----------------------
        os.environ["HYPERSPACE_ADAPTIVE"] = "0"
        reference = {}
        t_static = 0.0
        for name in names:
            got = TPCH_QUERIES[name](session, ws).to_pydict()
            reference[name] = _bits(got)
            raw_ok = raw_ok and _close(got, raw[name])
            t, _ = _timed(lambda: TPCH_QUERIES[name](session, ws).collect(), 1)
            t_static += t
        os.environ["HYPERSPACE_ADAPTIVE"] = "1"
        sw0 = _switches()
        tpch_bits = True
        t_adapt = 0.0
        for name in names:
            tpch_bits = tpch_bits and (
                _bits(TPCH_QUERIES[name](session, ws).to_pydict())
                == reference[name]
            )
            t, _ = _timed(lambda: TPCH_QUERIES[name](session, ws).collect(), 1)
            t_adapt += t
        tpch = {
            "queries": names,
            "static_ms": round(t_static * 1000, 1),
            "adaptive_ms": round(t_adapt * 1000, 1),
            "adaptive_overhead_pct": round(
                100.0 * (t_adapt - t_static) / t_static, 1
            )
            if t_static > 0
            else 0.0,
            "switches": _switches() - sw0,
            "bit_identical": tpch_bits,
        }

        # ---- planted leg: tampered footer stats, tight grant -------------
        rng = np.random.default_rng(7)
        n_join = 150_000
        cio.write_parquet(
            ColumnBatch.from_pydict(
                {
                    "k": rng.integers(0, 600, n_join).tolist(),
                    "p": rng.uniform(0, 100, n_join).tolist(),
                }
            ),
            os.path.join(ws, "adapt_l", "l.parquet"),
        )
        cio.write_parquet(
            ColumnBatch.from_pydict(
                {
                    "rk": list(range(500)),
                    "w": rng.uniform(size=500).tolist(),
                }
            ),
            os.path.join(ws, "adapt_r", "r.parquet"),
        )
        hs = Hyperspace(session)
        session.set_conf(C.INDEX_NUM_BUCKETS, 4)
        hs.create_index(
            session.read.parquet(os.path.join(ws, "adapt_l")),
            CoveringIndexConfig("bench_adapt_l", ["k"], ["p"]),
        )
        hs.create_index(
            session.read.parquet(os.path.join(ws, "adapt_r")),
            CoveringIndexConfig("bench_adapt_r", ["rk"], ["w"]),
        )
        join_memory._bucket_estimates = lambda side, b: (
            lambda r, nb: (r, nb / 64.0)
        )(*real_estimates(side, b))
        os.environ["HYPERSPACE_JOIN_BROADCAST_ROWS"] = "10"
        os.environ["HYPERSPACE_DEVICE_BUDGET_MB"] = "2.0"
        # A parked wave waits the full HYPERSPACE_PARK_WAIT_MS for other
        # queries' releases before the zero-holder force grant, so the knob
        # IS the wall-clock price of a park on this single-query fixture.
        # Model a contended serving window rather than the near-free 1 ms
        # the smoke test uses to stay fast.
        park_wait_ms = 2000
        os.environ["HYPERSPACE_PARK_WAIT_MS"] = str(park_wait_ms)
        os.environ["HYPERSPACE_ADAPTIVE_WARMUP_CHUNKS"] = "1"
        serve_budget.reset_device_budget()

        def planted_q():
            l = session.read.parquet(os.path.join(ws, "adapt_l")).select(
                "k", "p"
            )
            r = session.read.parquet(os.path.join(ws, "adapt_r")).select(
                "rk", "w"
            )
            return (
                l.join(r, col("k") == col("rk"))
                .group_by("k")
                .agg(
                    Count(lit(1)).alias("n"),
                    Min(col("p")).alias("lo"),
                    Max(col("p")).alias("hi"),
                )
                .to_pydict()
            )

        # Per mode: one cold run brackets the park/spill/flip counter deltas
        # (exactly one execution between the reads — _timed would warm first
        # and double-count), then one warm run is timed so neither leg is
        # charged for first-shape compilation.
        os.environ["HYPERSPACE_ADAPTIVE"] = "0"
        parks0, spills0 = _cnt("join.spill.parks"), _cnt("join.spill.spills")
        static_got = planted_q()
        static_parks = _cnt("join.spill.parks") - parks0
        static_spills = _cnt("join.spill.spills") - spills0
        t0 = time.time()
        planted_q()
        t_pstatic = time.time() - t0

        os.environ["HYPERSPACE_ADAPTIVE"] = "1"
        parks0, spills0 = _cnt("join.spill.parks"), _cnt("join.spill.spills")
        flips0 = _cnt("adaptive.replan")
        adaptive_got = planted_q()
        adapt_parks = _cnt("join.spill.parks") - parks0
        adapt_spills = _cnt("join.spill.spills") - spills0
        flips = _cnt("adaptive.replan") - flips0
        t0 = time.time()
        planted_q()
        t_padapt = time.time() - t0

        planted_bits = _bits(adaptive_got) == _bits(static_got)
        fewer = (adapt_parks + adapt_spills) < (static_parks + static_spills)
        planted = {
            "rows": n_join,
            "device_budget_mb": 2.0,
            "park_wait_ms": park_wait_ms,
            "static_ms": round(t_pstatic * 1000, 1),
            "adaptive_ms": round(t_padapt * 1000, 1),
            "adaptive_speedup": round(t_pstatic / max(t_padapt, 1e-9), 2),
            "flips": flips,
            "static_parks": static_parks,
            "static_spills": static_spills,
            "adaptive_parks": adapt_parks,
            "adaptive_spills": adapt_spills,
            "bit_identical": planted_bits,
            "fewer_parks_and_spills": fewer,
        }
    finally:
        join_memory._bucket_estimates = real_estimates
        session.set_conf(C.INDEX_NUM_BUCKETS, prior_buckets)
        for k, v in prior_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        serve_budget.reset_device_budget()
        session.set_conf(C.EXEC_TPU_ENABLED, False)
        session.disable_hyperspace()
    return {
        "tpch": tpch,
        "planted": planted,
        "results_match_raw": bool(
            raw_ok
            and tpch_bits
            and planted_bits
            and fewer
            and flips >= 1
        ),
    }


def _measure_mesh_scale(session, ws: str) -> dict:
    """Mesh-sharded scale-out: the TPC-H join queries re-run on the device
    tier with HYPERSPACE_MESH=1 so band waves fan out across every visible
    device (skew-aware placement, parallel/placement.py) instead of all
    landing on device 0. Mesh-on must be bit-identical (float.hex) to
    mesh-off — placement moves work, never changes answers — and the
    section records the placer's balance telemetry (devices used, byte
    imbalance ratio, fallback count). Skipped with a reason when fewer
    than 2 devices are visible. BENCH_MESH=0 skips the section."""
    from hyperspace_tpu import constants as C
    from hyperspace_tpu.benchmark import TPCH_QUERIES
    from hyperspace_tpu.serve import budget as serve_budget
    from hyperspace_tpu.telemetry.metrics import REGISTRY
    from hyperspace_tpu.utils.backend import safe_device_count

    ndev = safe_device_count()
    if ndev < 2:
        return {"skipped": "single_device", "devices_visible": ndev}
    names = [n for n in ("q3", "q10") if n in TPCH_QUERIES]

    def _bits(d: dict) -> str:
        return repr(
            {
                k: [x.hex() if isinstance(x, float) else x for x in v]
                for k, v in d.items()
            }
        )

    session.enable_hyperspace()
    session.set_conf(C.EXEC_TPU_ENABLED, True)
    prior_mesh = os.environ.get("HYPERSPACE_MESH")
    bit_ok = True
    try:
        # ---- mesh off: the single-device reference ----------------------
        os.environ["HYPERSPACE_MESH"] = "0"
        reference = {}
        t_off = 0.0
        for name in names:
            reference[name] = _bits(TPCH_QUERIES[name](session, ws).to_pydict())
            t, _ = _timed(lambda: TPCH_QUERIES[name](session, ws).collect(), 1)
            t_off += t
        # ---- mesh on: same bits, waves spread over the mesh -------------
        os.environ["HYPERSPACE_MESH"] = "1"
        buckets0 = REGISTRY.counter("mesh.placement.buckets").value
        fallbacks0 = REGISTRY.counter("mesh.placement.fallbacks").value
        t_on = 0.0
        for name in names:
            bit_ok = bit_ok and (
                _bits(TPCH_QUERIES[name](session, ws).to_pydict())
                == reference[name]
            )
            t, _ = _timed(lambda: TPCH_QUERIES[name](session, ws).collect(), 1)
            t_on += t
        buckets = REGISTRY.counter("mesh.placement.buckets").value - buckets0
        fallbacks = (
            REGISTRY.counter("mesh.placement.fallbacks").value - fallbacks0
        )
        devices_used = REGISTRY.gauge("mesh.placement.devices_used").value
        imbalance = REGISTRY.gauge("mesh.placement.bytes_imbalance_ratio").value
        ledgers = {
            f"d{o}": acct.held_bytes()
            for o, acct in serve_budget.device_budgets().items()
        }
        ledgers_drained = all(v == 0 for v in ledgers.values())
    finally:
        if prior_mesh is None:
            os.environ.pop("HYPERSPACE_MESH", None)
        else:
            os.environ["HYPERSPACE_MESH"] = prior_mesh
        session.set_conf(C.EXEC_TPU_ENABLED, False)
        session.disable_hyperspace()
    return {
        "devices_visible": ndev,
        "queries": names,
        "mesh_off_ms": round(t_off * 1000, 1),
        "mesh_on_ms": round(t_on * 1000, 1),
        "placed_buckets": buckets,
        "placement_fallbacks": fallbacks,
        "devices_used": devices_used,
        "bytes_imbalance_ratio": round(imbalance, 4),
        "ledgers_drained": ledgers_drained,
        "bit_identical": bit_ok,
        "results_match": bool(bit_ok and ledgers_drained),
    }


def _measure_cached_qps(session, ws: str) -> dict:
    """Repeat-heavy serving with the snapshot-keyed result cache
    (cache/result_cache.py): the dashboard-workload shape where the same
    query templates repeat against a slowly-advancing lake.

    Two closed-loop tiers through one scheduler over the TPC-H mix:
    ``cold`` (HYPERSPACE_RESULT_CACHE=0 — every repeat re-executes, the
    PR-8 serving baseline) and ``warm`` (=1 — a populate pass, then the
    measured repeats hit the cache). Every served result, hit or computed,
    is verified bit-identical to the cold reference and ANDed into the
    artifact's ``results_match_raw``. The headline is repeat-query p50
    warm vs cold plus the measured hit ratio.

    A freshness leg then runs a small append stream into a dedicated live
    table WITH the cache on: a prober polls a fold-eligible count through
    the serving path, so the recorded freshness lag proves caching does
    not delay visibility (every publish changes the snapshot key; the
    incremental-view path answers at delta cost — ``folds`` counts it
    engaging). BENCH_CACHED=0 skips the section."""
    import threading as _threading

    import numpy as np

    from hyperspace_tpu import CoveringIndexConfig, Hyperspace, ingest, serve
    from hyperspace_tpu.benchmark import TPCH_QUERIES
    from hyperspace_tpu.cache.result_cache import RESULT_CACHE
    from hyperspace_tpu.cache.view_maintenance import refresh_idle
    from hyperspace_tpu.columnar import io as cio
    from hyperspace_tpu.columnar.table import ColumnBatch
    from hyperspace_tpu.plan import Count, col, lit
    from hyperspace_tpu.telemetry.metrics import REGISTRY

    clients = int(os.environ.get("BENCH_CACHED_CLIENTS", 4))
    passes = int(os.environ.get("BENCH_CACHED_PASSES", 3))
    batches = int(os.environ.get("BENCH_CACHED_BATCHES", 4))
    batch_rows = int(os.environ.get("BENCH_CACHED_ROWS", 10_000))
    names = list(TPCH_QUERIES)
    session.enable_hyperspace()
    prev_mode = os.environ.get("HYPERSPACE_RESULT_CACHE")

    def _bits(d: dict) -> str:
        return repr(
            {
                k: [x.hex() if isinstance(x, float) else x for x in v]
                for k, v in d.items()
            }
        )

    def _val(n: str) -> float:
        m = REGISTRY.get(n)
        return 0 if m is None else m.value

    match = {"ok": True}
    os.environ["HYPERSPACE_RESULT_CACHE"] = "0"
    reference = {
        name: _bits(TPCH_QUERIES[name](session, ws).to_pydict())
        for name in names
    }

    def _run_tier(mode: str) -> dict:
        os.environ["HYPERSPACE_RESULT_CACHE"] = mode
        RESULT_CACHE.clear()
        sched = serve.QueryScheduler(
            max_concurrent=clients,
            queue_depth=max(64, clients * len(names) * (passes + 1)),
        )
        if mode == "1":
            # populate pass: the first run of each template is the miss
            for name in names:
                sched.submit_query(
                    TPCH_QUERIES[name](session, ws), label=f"pop:{name}"
                ).result(timeout=600)
        h0, m0 = _val("cache.result.hits"), _val("cache.result.misses")
        lat: list[float] = []
        lock = _threading.Lock()

        def client(tid: int) -> None:
            for p in range(passes):
                off = (tid + p) % len(names)
                for name in names[off:] + names[:off]:
                    t0 = time.perf_counter()
                    h = sched.submit_query(
                        TPCH_QUERIES[name](session, ws), label=name
                    )
                    got = h.result(timeout=600)
                    dt = time.perf_counter() - t0
                    with lock:
                        lat.append(dt)
                    if _bits(got.to_pydict()) != reference[name]:
                        match["ok"] = False

        threads = [
            _threading.Thread(target=client, args=(i,), name=f"bench-rc-{i}")
            for i in range(clients)
        ]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
        sched.shutdown(wait=True)
        hits = _val("cache.result.hits") - h0
        misses = _val("cache.result.misses") - m0
        looked = hits + misses
        return {
            "queries": len(lat),
            "wall_s": round(wall, 3),
            "qps": round(len(lat) / wall, 3) if wall > 0 else 0.0,
            **_qps_stats(lat),
            "hits": int(hits),
            "misses": int(misses),
            "hit_ratio": round(hits / looked, 4) if looked else 0.0,
        }

    cold = _run_tier("0")
    warm = _run_tier("1")

    # --- freshness under ingest WITH the cache on -------------------------
    def _batch(seed: int) -> dict:
        r = np.random.default_rng(900 + seed)
        return {
            "k": r.integers(0, 128, batch_rows).tolist(),
            "v": r.integers(0, 10_000, batch_rows).tolist(),
        }

    ev = os.path.join(ws, "events_cached")
    cio.write_parquet(
        ColumnBatch.from_pydict(_batch(0)), os.path.join(ev, "part0.parquet")
    )
    hs = Hyperspace(session)
    hs.create_index(
        session.read.parquet(ev), CoveringIndexConfig("ev_cached", ["k"], ["v"])
    )
    folds0 = _val("cache.result.folds")
    sched = serve.QueryScheduler(max_concurrent=2, queue_depth=64)
    publishes: list[tuple[float, int]] = []
    observed: list[tuple[float, int]] = []
    total0 = batch_rows
    ingest_done = _threading.Event()

    def ingester() -> None:
        try:
            for k in range(1, batches + 1):
                ingest.append_batch(session, "ev_cached", _batch(k))
                publishes.append((time.perf_counter(), total0 + k * batch_rows))
        finally:
            ingest_done.set()

    def prober() -> None:
        """Counts the latest stable snapshot's rows through the serving
        path WITH the cache on (reading the entry's recorded file listing,
        never a directory mid-write): every publish changes the snapshot
        key, so a cached plane must still see fresh rows immediately —
        the foldable count answers each advance at delta cost."""
        target = total0 + batches * batch_rows
        while True:
            entry = ingest.latest_stable_entry(session, "ev_cached")
            files = [f.name for f in entry.relation.content.file_infos()]
            df = session.read.parquet(files)
            # the (always-true) filter makes this the rewritable
            # filter-aggregate fragment: the probe runs over the INDEX,
            # pins the snapshot, and folds across appends
            h = sched.submit_query(
                df.filter(df["k"] >= 0).agg(Count(lit(1)).alias("n")),
                label="cached:freshness",
            )
            n = int(h.result(timeout=600).to_pydict()["n"][0])
            observed.append((time.perf_counter(), n))
            if ingest_done.is_set() and n >= target:
                return

    ing = _threading.Thread(target=ingester, name="bench-rc-ingester")
    probe = _threading.Thread(target=prober, name="bench-rc-prober")
    ing.start()
    probe.start()
    ing.join()
    probe.join()
    sched.drain(timeout=120)
    sched.shutdown(wait=True)
    deadline = time.monotonic() + 120
    while time.monotonic() < deadline and not (
        refresh_idle() and ingest.maintenance_idle()
    ):
        time.sleep(0.05)
    lags: list[float] = []
    for t_pub, total in publishes:
        seen = [t for t, n in observed if n >= total and t >= t_pub]
        if seen:
            lags.append(min(seen) - t_pub)
    lag_stats = _qps_stats(lags)
    folds = _val("cache.result.folds") - folds0

    RESULT_CACHE.clear()
    if prev_mode is None:
        os.environ.pop("HYPERSPACE_RESULT_CACHE", None)
    else:
        os.environ["HYPERSPACE_RESULT_CACHE"] = prev_mode
    session.disable_hyperspace()

    out = {
        "clients": clients,
        "passes": passes,
        "cold": cold,
        "warm": warm,
        "cold_p50_ms": cold.get("p50_ms"),
        "warm_p50_ms": warm.get("p50_ms"),
        "hit_ratio": warm["hit_ratio"],
        "freshness_p50_ms": lag_stats.get("p50_ms"),
        "freshness_max_ms": lag_stats.get("max_ms"),
        "freshness_samples": len(lags),
        "folds": int(folds),
        "results_match": match["ok"],
    }
    if cold.get("p50_ms") and warm.get("p50_ms"):
        out["repeat_speedup_p50"] = round(
            cold["p50_ms"] / max(warm["p50_ms"], 1e-9), 3
        )
    return out


def _measure_ingest_rw(session, ws: str) -> dict:
    """Mixed read/write serving: sustained ingest into a live covering
    index while concurrent TPC-H queries run through the scheduler.

    An ingester thread appends ``BENCH_INGEST_BATCHES`` seeded batches
    (``BENCH_INGEST_ROWS`` rows each) into a dedicated ``events`` table via
    ``ingest.append_batch`` — each append an atomic snapshot publish, with
    background compaction + refcount-gated vacuum riding the shared IO
    pool. Meanwhile ``BENCH_INGEST_CLIENTS`` closed-loop clients run a
    TPC-H query mix through one QueryScheduler; the same client load runs
    once WITHOUT ingest first, so the artifact carries query p50/p99 both
    ways (the cost of writes under the read path). A freshness prober
    polls the latest stable snapshot and counts its rows through the index:
    per batch, freshness lag = commit -> first query whose result contains
    the batch. Compaction/vacuum engagement lands as ingest.* counter
    deltas. BENCH_INGEST=0 skips the section."""
    import threading as _threading

    import numpy as np

    from hyperspace_tpu import CoveringIndexConfig, Hyperspace, ingest, serve
    from hyperspace_tpu.benchmark import TPCH_QUERIES
    from hyperspace_tpu.columnar import io as cio
    from hyperspace_tpu.columnar.table import ColumnBatch
    from hyperspace_tpu.plan import Count, col, lit
    from hyperspace_tpu.telemetry.metrics import REGISTRY

    batches = int(os.environ.get("BENCH_INGEST_BATCHES", 8))
    batch_rows = int(os.environ.get("BENCH_INGEST_ROWS", 25_000))
    clients = int(os.environ.get("BENCH_INGEST_CLIENTS", 2))
    mix = [n for n in ("q1", "q6", "q14") if n in TPCH_QUERIES]

    def _batch(seed: int) -> dict:
        r = np.random.default_rng(500 + seed)
        return {
            "k": r.integers(0, 256, batch_rows).tolist(),
            "v": r.integers(0, 10_000, batch_rows).tolist(),
            "w": r.random(batch_rows).tolist(),
        }

    ev = os.path.join(ws, "events")
    cio.write_parquet(
        ColumnBatch.from_pydict(_batch(0)), os.path.join(ev, "part0.parquet")
    )
    hs = Hyperspace(session)
    hs.create_index(
        session.read.parquet(ev), CoveringIndexConfig("ev_ingest", ["k"], ["v", "w"])
    )
    session.enable_hyperspace()

    def _counters() -> dict:
        return {
            k: v
            for k, v in REGISTRY.snapshot().items()
            if k.startswith("ingest.") and isinstance(v, (int, float))
        }

    def _run_clients(sched, stop: "_threading.Event | None", passes: int):
        """Closed-loop TPC-H mix; with ``stop``, loop until it fires."""
        lat: list[float] = []
        lock = _threading.Lock()

        def client(tid: int) -> None:
            p = 0
            while True:
                if stop is not None and stop.is_set():
                    return
                if stop is None and p >= passes:
                    return
                name = mix[(tid + p) % len(mix)]
                t0 = time.perf_counter()
                h = sched.submit_query(
                    TPCH_QUERIES[name](session, ws), label=f"rw:{name}"
                )
                h.result(timeout=600)
                with lock:
                    lat.append(time.perf_counter() - t0)
                p += 1

        threads = [
            _threading.Thread(target=client, args=(i,), name=f"bench-rw-{i}")
            for i in range(clients)
        ]
        for t in threads:
            t.start()
        return threads, lat

    # --- baseline: same client load, no ingest ---------------------------
    sched = serve.QueryScheduler(max_concurrent=clients, queue_depth=64)
    threads, base_lat = _run_clients(sched, None, passes=3)
    for t in threads:
        t.join()
    sched.shutdown(wait=True)

    # --- under ingest -----------------------------------------------------
    sched = serve.QueryScheduler(max_concurrent=clients + 1, queue_depth=64)
    c0 = _counters()
    publishes: list[tuple[float, int]] = []  # (t_commit, cumulative rows)
    observed: list[tuple[float, int]] = []  # (t_result, visible rows)
    ingest_done = _threading.Event()
    total0 = batch_rows  # the seed part

    def ingester() -> None:
        try:
            for k in range(1, batches + 1):
                ingest.append_batch(session, "ev_ingest", _batch(k))
                publishes.append((time.perf_counter(), total0 + k * batch_rows))
        finally:
            ingest_done.set()

    def prober() -> None:
        """Counts the latest stable snapshot's rows THROUGH the serving
        path; each sample is (completion time, rows the query saw)."""
        while not (ingest_done.is_set() and observed and
                   observed[-1][1] >= total0 + batches * batch_rows):
            entry = ingest.latest_stable_entry(session, "ev_ingest")
            files = [f.name for f in entry.relation.content.file_infos()]
            df = session.read.parquet(files)
            h = sched.submit_query(
                df.agg(Count(lit(1)).alias("n")), label="rw:freshness"
            )
            n = int(h.result(timeout=600).to_pydict()["n"][0])
            observed.append((time.perf_counter(), n))
            if ingest_done.is_set() and n >= total0 + batches * batch_rows:
                return

    threads, ingest_lat = _run_clients(sched, ingest_done, passes=0)
    ing = _threading.Thread(target=ingester, name="bench-rw-ingester")
    probe = _threading.Thread(target=prober, name="bench-rw-prober")
    t_start = time.perf_counter()
    ing.start()
    probe.start()
    ing.join()
    probe.join()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t_start
    sched.drain(timeout=120)
    sched.shutdown(wait=True)

    # drain background maintenance so the counter deltas are complete
    deadline = time.monotonic() + 120
    while time.monotonic() < deadline and not ingest.maintenance_idle():
        time.sleep(0.05)
    delta = {
        k: round(v - c0.get(k, 0), 3)
        for k, v in _counters().items()
        if v != c0.get(k, 0)
    }
    session.disable_hyperspace()

    # freshness lag per batch: commit -> first probe that saw its rows
    lags: list[float] = []
    for t_pub, total in publishes:
        seen = [t for t, n in observed if n >= total and t >= t_pub]
        if seen:
            lags.append(min(seen) - t_pub)
    lag_stats = _qps_stats(lags)
    return {
        "batches": batches,
        "batch_rows": batch_rows,
        "clients": clients,
        "wall_s": round(wall, 3),
        "rows_ingested": batches * batch_rows,
        "ingest_rows_per_s": (
            round(batches * batch_rows / wall, 1) if wall > 0 else 0.0
        ),
        "freshness_p50_ms": lag_stats.get("p50_ms"),
        "freshness_max_ms": lag_stats.get("max_ms"),
        "freshness_samples": len(lags),
        "baseline_p50_ms": _qps_stats(base_lat).get("p50_ms"),
        "baseline_p99_ms": _qps_stats(base_lat).get("p99_ms"),
        "under_ingest_p50_ms": _qps_stats(ingest_lat).get("p50_ms"),
        "under_ingest_p99_ms": _qps_stats(ingest_lat).get("p99_ms"),
        "queries_under_ingest": len(ingest_lat),
        "counters": delta,
    }


def _measure_hybrid_refresh(session, hs, ws: str, repeats: int) -> dict:
    """BASELINE.md config 4: append parquet files to lineitem, run Q3 with
    Hybrid Scan serving the stale index (appended rows re-bucketed on the
    fly), then time the incremental refresh and the post-refresh query."""
    import numpy as np

    from hyperspace_tpu import constants as C
    from hyperspace_tpu.benchmark import TPCH_QUERIES
    from hyperspace_tpu.columnar import io as cio
    from hyperspace_tpu.columnar.table import ColumnBatch

    rng = np.random.default_rng(7)
    n = 50_000
    append = {
        "l_orderkey": rng.integers(0, 1_000_000, n).tolist(),
        "l_partkey": rng.integers(0, 10_000, n).tolist(),
        "l_suppkey": rng.integers(0, 2_500, n).tolist(),
        "l_quantity": rng.integers(1, 51, n).astype(float).tolist(),
        "l_extendedprice": rng.uniform(900, 105_000, n).tolist(),
        "l_discount": np.round(rng.uniform(0, 0.1, n), 2).tolist(),
        "l_tax": np.round(rng.uniform(0, 0.08, n), 2).tolist(),
        "l_returnflag": rng.choice(["A", "N", "R"], n).tolist(),
        "l_linestatus": rng.choice(["O", "F"], n).tolist(),
        "l_shipdate": rng.integers(8035, 10590, n).astype("int32").tolist(),
    }
    cio.write_parquet(
        ColumnBatch.from_pydict(append),
        os.path.join(ws, "lineitem", "part-append.parquet"),
    )
    session.set_conf(C.HYBRID_SCAN_ENABLED, True)
    session.enable_hyperspace()
    q3 = lambda: TPCH_QUERIES["q3"](session, ws).collect()
    t_hybrid, hybrid_stats = _timed(q3, repeats)
    from hyperspace_tpu.exceptions import NoChangesError

    t0 = time.time()
    for name in ("li_orderkey", "od_orderkey"):
        try:
            hs.refresh_index(name, "incremental")
        except NoChangesError:
            pass  # orders unchanged: expected; real failures must surface
    refresh_s = time.time() - t0
    t_after, after_stats = _timed(q3, repeats)
    session.disable_hyperspace()
    session.set_conf(C.HYBRID_SCAN_ENABLED, False)
    return {
        "q3_hybrid_ms": round(t_hybrid * 1000, 1),
        "q3_hybrid_stats": hybrid_stats,
        "refresh_incremental_s": round(refresh_s, 2),
        "q3_after_refresh_ms": round(t_after * 1000, 1),
        "q3_after_refresh_stats": after_stats,
    }


def _measure_bloom_skipping(session, ws: str, rows: int, repeats: int) -> dict:
    """BASELINE.md config 5: BloomFilterSketch data skipping over a
    store_sales-shaped table (high-cardinality int keys across many files);
    point lookups skip files whose bloom filter rejects the key."""
    import numpy as np

    from hyperspace_tpu import BloomFilterSketch, DataSkippingIndexConfig, Hyperspace
    from hyperspace_tpu.columnar import io as cio
    from hyperspace_tpu.columnar.table import ColumnBatch
    from hyperspace_tpu.plan import Count, Sum, col, lit

    rng = np.random.default_rng(11)
    # sized so the raw side is signal (>=100ms), capped so scale runs stay
    # bounded. 256 files is the shape the sketch exists for: the raw side
    # pays a footer read + stats check per file, the bloom index drops the
    # files BEFORE any IO (ref: BloomFilterSketch.scala:47-87 targets
    # many-file tables).
    n = max(2_000_000, min(rows, 16_000_000))
    n_files = 256
    per = n // n_files
    ss = os.path.join(ws, "store_sales")
    for i in range(n_files):
        data = {
            # item keys are file-local ranges: realistic ingest clustering,
            # so bloom filters reject most files for a point key
            "ss_item_sk": rng.integers(i * 100_000, (i + 1) * 100_000, per).tolist(),
            "ss_net_paid": rng.uniform(1, 300, per).tolist(),
        }
        cio.write_parquet(
            ColumnBatch.from_pydict(data), os.path.join(ss, f"part-{i:02d}.parquet")
        )
    hs = Hyperspace(session)
    df = session.read.parquet(ss)
    t0 = time.time()
    hs.create_index(
        df,
        DataSkippingIndexConfig(
            "ss_bloom", [BloomFilterSketch("ss_item_sk", per, 0.01)]
        ),
    )
    build_s = time.time() - t0
    key = int(rng.integers(3 * 100_000, 4 * 100_000))
    q = lambda: (
        session.read.parquet(ss)
        .filter(col("ss_item_sk") == key)
        .agg(Sum(col("ss_net_paid")).alias("s"), Count(lit(1)).alias("n"))
        .collect()
    )
    t_raw, raw_stats = _timed(q, repeats)
    session.enable_hyperspace()
    t_idx, idx_stats = _timed(q, repeats)
    session.disable_hyperspace()
    return {
        "rows": n,
        "files": n_files,
        "index_build_s": round(build_s, 2),
        "raw_ms": round(t_raw * 1000, 1),
        "raw_stats": raw_stats,
        "indexed_ms": round(t_idx * 1000, 1),
        "indexed_stats": idx_stats,
        "speedup": round(t_raw / t_idx, 3) if t_idx > 0 else 0.0,
    }


def main() -> None:
    t_start = time.time()
    # kernel-audit every cache miss by default: a clean artifact must
    # report zero jaxpr hazards and zero retrace-storm warnings
    # (BENCH_KERNEL_AUDIT=0 opts out; audit never alters kernel behavior)
    if os.environ.get("BENCH_KERNEL_AUDIT", "1") == "1":
        os.environ.setdefault("HYPERSPACE_KERNEL_AUDIT", "1")
    # verify every optimized plan's structural invariants: a violation
    # raises PlanInvariantError (failing the bench loudly), so a finished
    # artifact proves plan_violations == 0 (BENCH_VERIFY_PLAN=0 opts out)
    if os.environ.get("BENCH_VERIFY_PLAN", "1") == "1":
        os.environ.setdefault("HYPERSPACE_VERIFY_PLAN", "1")
    # audit lock acquisition order by default: a nesting that closes a
    # cycle in the order graph raises LockOrderError (failing the bench
    # loudly), so a finished artifact proves lock_violations == 0
    # (BENCH_LOCK_AUDIT=0 opts out; the audit never alters behavior)
    if os.environ.get("BENCH_LOCK_AUDIT", "1") == "1":
        os.environ.setdefault("HYPERSPACE_LOCK_AUDIT", "1")
    rows = int(os.environ.get("BENCH_ROWS", 4_000_000))
    repeats = int(os.environ.get("BENCH_REPEATS", 3))
    probe_timeout = float(os.environ.get("BENCH_JAX_PROBE_TIMEOUT", 90))
    init_timeout = float(os.environ.get("BENCH_JAX_TIMEOUT", 600))
    device_wait = float(os.environ.get("BENCH_DEVICE_WAIT", 600))

    # --profile: trace every query into a JSONL artifact (one span per line;
    # read with tools/trace_report.py). Timings measured under --profile
    # carry the (small) tracing overhead — the artifact says so.
    profile_path = None
    if "--profile" in sys.argv:
        from hyperspace_tpu.telemetry import trace as _trace

        profile_path = os.environ.get("BENCH_PROFILE_FILE", "BENCH_profile.jsonl")
        if os.path.exists(profile_path):
            os.remove(profile_path)
        _trace.enable(_trace.JsonlTraceSink(profile_path))

    # the grant watcher probes in the BACKGROUND while host paths measure
    watcher = GrantWatcher(probe_timeout, init_timeout).start()

    import tempfile

    from hyperspace_tpu import Hyperspace, HyperspaceSession
    from hyperspace_tpu import constants as C
    from hyperspace_tpu.benchmark import TPCH_QUERIES, generate_tpch, tpch_indexes

    ws = tempfile.mkdtemp(prefix="hs_bench_")
    sizes = generate_tpch(ws, rows_lineitem=rows, seed=42)
    source_mb = sum(sizes.values()) / 1e6

    session = HyperspaceSession(warehouse_dir=ws)
    session.set_conf(C.INDEX_NUM_BUCKETS, 8)
    session.set_conf(C.EXEC_TPU_ENABLED, False)  # host paths first
    session.set_conf(C.ZORDER_TARGET_SOURCE_BYTES_PER_PARTITION, 8 * 1024 * 1024)
    index_format = os.environ.get("BENCH_INDEX_FORMAT", "parquet")
    session.set_conf(C.INDEX_FORMAT, index_format)
    build_budget_mb = os.environ.get("BENCH_MAX_BUILD_MB")
    if build_budget_mb:  # scale runs force streaming file-group builds
        session.set_conf(
            C.BUILD_MAX_BYTES_IN_MEMORY, int(build_budget_mb) * 1024 * 1024
        )
    hs = Hyperspace(session)

    t0 = time.time()
    tpch_indexes(session, hs, ws)
    build_s = time.time() - t0
    # bytes actually indexed: lineitem is sliced by four indexes
    indexed_bytes = 4 * sizes["lineitem"] + sizes["orders"] + sizes["part"]
    build_gbps = indexed_bytes / build_s / 1e9

    from hyperspace_tpu.benchmark.external import PANDAS_TPCH

    # ---- host-path measurements (no device dependency) -------------------
    results: dict[str, dict] = {}
    correct = True
    expected_results = {}
    for name, q in TPCH_QUERIES.items():
        with _bench_span(f"host:{name}"):
            session.disable_hyperspace()
            expected = q(session, ws).to_pydict()
            expected_results[name] = expected
            t_raw, raw_stats = _timed(lambda: q(session, ws).collect(), repeats)
            session.enable_hyperspace()
            got, prune_delta = _prefix_counter_delta(
                lambda: q(session, ws).to_pydict(), "pruning."
            )
            t_idx, idx_stats = _timed(lambda: q(session, ws).collect(), repeats)
            session.disable_hyperspace()
            t_ext, ext_stats = _timed(lambda: PANDAS_TPCH[name](ws), repeats)
        ok = list(got.keys()) == list(expected.keys()) and all(
            len(got[k]) == len(expected[k])
            and all(
                (abs(a - b) <= 1e-6 * max(1.0, abs(b)))
                if isinstance(a, float)
                else a == b
                for a, b in zip(got[k], expected[k])
            )
            for k in got
        )
        correct = correct and ok
        results[name] = {
            "raw_ms": round(t_raw * 1000, 1),
            "raw_stats": raw_stats,
            "indexed_hostexec_ms": round(t_idx * 1000, 1),
            "indexed_hostexec_stats": idx_stats,
            "external_pandas_ms": round(t_ext * 1000, 1),
            "external_stats": ext_stats,
        }
        if prune_delta:
            # per-query index-pruning engagement (files/row groups kept vs
            # total, bytes never decoded) — diffed by tools/bench_compare.py
            results[name]["pruning"] = prune_delta

    # ---- device sections: run whenever the grant landed (even late) ------
    # BEFORE the hybrid-refresh section, which MUTATES lineitem (appends +
    # incremental refresh) — device runs must see the same dataset the host
    # expectations were computed on
    host_wall_s = round(time.time() - t_start, 1)
    backend = watcher.backend or watcher.wait(device_wait)
    watcher.stop()
    device_note = None
    if backend:
        session.set_conf(C.EXEC_TPU_ENABLED, True)
        for name, q in TPCH_QUERIES.items():
            entry = results[name]
            try:
                # same-engine, same-tier correctness: the index must not
                # change answers. (Cross-tier f32-vs-f64 accumulation is a
                # documented property of the device tier — see
                # hyperspace.tpu.exec.exactF64Aggregates.)
                with _bench_span(f"device:{name}"):
                    session.disable_hyperspace()
                    expected_dev = q(session, ws).to_pydict()
                    t_raw_dev, _ = _timed(lambda: q(session, ws).collect(), 1)
                    entry["raw_device_ms"] = round(t_raw_dev * 1000, 1)
                    session.enable_hyperspace()
                    got = q(session, ws).to_pydict()
                    t_dev, dev_stats = _timed(
                        lambda: q(session, ws).collect(), repeats
                    )
                    rpc = _rpc_delta(lambda: q(session, ws).collect())
                    if name in ("q3", "q10", "q17", "q18"):
                        # join-pipeline engagement for this query: bucket
                        # pairs streamed, band waves, splits, pad savings
                        entry["join_pipeline"] = _join_counter_delta(
                            lambda: q(session, ws).collect()
                        )
            except Exception as e:  # device failure: host numbers stand
                device_note = f"{name}: {e}"
                session.disable_hyperspace()
                break
            session.disable_hyperspace()
            ok = list(got.keys()) == list(expected_dev.keys()) and all(
                len(got[k]) == len(expected_dev[k])
                and all(
                    (abs(a - b) <= 1e-6 * max(1.0, abs(b)))
                    if isinstance(a, float)
                    else a == b
                    for a, b in zip(got[k], expected_dev[k])
                )
                for k in got
            )
            correct = correct and ok
            entry["device_match"] = ok
            entry["indexed_device_ms"] = round(t_dev * 1000, 1)
            entry["indexed_device_stats"] = dev_stats
            entry["device_rpc"] = rpc
        session.set_conf(C.EXEC_TPU_ENABLED, False)

    # ---- index-pruning point lookup (non-mutating) -----------------------
    with _bench_span("point_lookup"):
        point = _measure_point_lookup(session, ws, repeats)

    # ---- per-row-group sketch pruning on non-sort columns (own table; ----
    # non-mutating for TPC-H inputs) ---------------------------------------
    sketch = None
    if os.environ.get("BENCH_SKETCH", "1") == "1":
        with _bench_span("sketch_prune"):
            sketch = _measure_sketch_prune(session, ws, rows, repeats)
        correct = correct and sketch["results_match"]

    # ---- sustained QPS under concurrent serving (non-mutating; must run --
    # BEFORE the hybrid-refresh section mutates lineitem) ------------------
    qps = None
    if os.environ.get("BENCH_QPS", "1") == "1":
        with _bench_span("sustained_qps"):
            qps = _measure_sustained_qps(session, ws)
        correct = correct and qps["results_match"]

    # ---- multi-tenant QoS: hog-vs-light isolation (non-mutating) ---------
    tenant_qos = None
    if os.environ.get("BENCH_TENANT", "1") == "1":
        with _bench_span("multi_tenant"):
            tenant_qos = _measure_multi_tenant(session, ws)
        correct = correct and tenant_qos["results_match"]

    # ---- memory-adaptive spilling join: over-budget device grant ---------
    # (non-mutating; device tier — must run BEFORE hybrid-refresh mutates)
    spill = None
    if backend and os.environ.get("BENCH_SPILL", "1") == "1":
        with _bench_span("spill_join"):
            spill = _measure_spill_join(session, ws)
        correct = correct and spill["results_match_raw"]

    # ---- mid-query adaptive re-optimization: static vs adaptive legs -----
    # (device tier; writes only the dedicated adapt_l/adapt_r tables)
    adaptive = None
    if backend and os.environ.get("BENCH_ADAPT", "1") == "1":
        with _bench_span("adaptive"):
            adaptive = _measure_adaptive(session, ws)
        correct = correct and adaptive["results_match_raw"]

    # ---- mesh-sharded scale-out: band waves fan out across the mesh ------
    # (non-mutating; device tier — must run BEFORE hybrid-refresh mutates)
    mesh_scale = None
    if backend and os.environ.get("BENCH_MESH", "1") == "1":
        with _bench_span("mesh_scale"):
            mesh_scale = _measure_mesh_scale(session, ws)
        if "skipped" not in mesh_scale:
            correct = correct and mesh_scale["results_match"]

    # ---- repeat-heavy serving through the result cache (non-mutating on
    # TPC-H; its freshness leg writes only the events_cached table) --------
    cached = None
    if os.environ.get("BENCH_CACHED", "1") == "1":
        with _bench_span("cached_qps"):
            cached = _measure_cached_qps(session, ws)
        correct = correct and cached["results_match"]

    # ---- mixed read/write serving: sustained ingest + concurrent queries -
    # (writes only the dedicated events table; TPC-H inputs untouched)
    ingest_rw = None
    if os.environ.get("BENCH_INGEST", "1") == "1":
        with _bench_span("ingest_rw"):
            ingest_rw = _measure_ingest_rw(session, ws)

    # ---- approximate query tier: sampled execution with error bounds -----
    # (writes only the dedicated apx_fact/apx_dim tables; HYPERSPACE_APPROX
    # is restored on exit so no other section sees the tier)
    approx_tier = None
    if os.environ.get("BENCH_APPROX", "1") == "1":
        with _bench_span("approx_tier"):
            approx_tier = _measure_approx_tier(session, ws, rows, repeats)
        correct = correct and approx_tier["results_match"]

    # ---- BASELINE.md config 4 + 5 (mutating; after device sections) ------
    with _bench_span("hybrid_refresh"):
        hybrid = _measure_hybrid_refresh(session, hs, ws, repeats)
    with _bench_span("bloom_skipping"):
        bloom = _measure_bloom_skipping(session, ws, rows, repeats)

    # ---- tier choice + headline -----------------------------------------
    tier_counts = {"device_wins": 0, "host_wins": 0} if backend else None
    for name, entry in results.items():
        t_host = entry["indexed_hostexec_ms"]
        t_dev = entry.get("indexed_device_ms")
        raw_candidates = [entry["raw_ms"]] + (
            [entry["raw_device_ms"]] if "raw_device_ms" in entry else []
        )
        entry["raw_best_ms"] = min(raw_candidates)
        if t_dev is not None:
            entry["exec_tier"] = "device" if t_dev <= t_host else "host"
            tier_counts[
                "device_wins" if entry["exec_tier"] == "device" else "host_wins"
            ] += 1
            entry["indexed_ms"] = min(t_dev, t_host)
        else:
            entry["indexed_ms"] = t_host
        t_idx = entry["indexed_ms"]
        entry["speedup_self"] = (
            round(entry["raw_best_ms"] / t_idx, 3) if t_idx > 0 else 0.0
        )
        entry["speedup_vs_external"] = (
            round(entry["external_pandas_ms"] / t_idx, 3) if t_idx > 0 else 0.0
        )

    q3_speedup = results["q3"]["speedup_self"]
    q3_vs_external = results["q3"]["speedup_vs_external"]
    out = {
        "metric": "tpch_q3_join_speedup",
        "value": q3_speedup,
        "unit": "x",
        # BASELINE.md's denominator (32-core Spark-CPU) is not in this image;
        # pandas is the independently-implemented external engine standing in
        "vs_baseline": round(q3_vs_external / 4.0, 3),
        "baseline_denominator": "pandas (external engine; see BASELINE.md note)",
        "queries": results,
        "point_lookup": point,
        "sketch_prune": sketch,
        "sustained_qps": qps,
        "multi_tenant": tenant_qos,
        "spill_join": spill,
        "adaptive": adaptive,
        "mesh_scale": mesh_scale,
        "cached_qps": cached,
        "ingest_rw": ingest_rw,
        "approx_tier": approx_tier,
        "serving": _counter_stats("serve."),
        "ingest": _counter_stats("ingest."),
        "approx": _counter_stats("approx."),
        "hybrid_refresh": hybrid,
        "bloom_skipping": bloom,
        "index_build_gbps": round(build_gbps, 4),
        "rows": rows,
        "source_mb": round(source_mb, 1),
        "results_match_raw": correct,
        "backend": backend or "none (grant watcher: see backend_diagnostics)",
        "backend_diagnostics": watcher.attempts,
        "device_note": device_note,
        "exec_tier_summary": tier_counts,
        "repeats": repeats,
        "host": _host_facts(),
        "build": {
            "max_bytes_in_memory": session.conf.build_max_bytes_in_memory,
            "streaming_forced": bool(build_budget_mb),
            "build_s": round(build_s, 1),
            "index_format": index_format,
        },
        "device_cache": _device_cache_stats(),
        "kernel_cache": _counter_stats("cache.kernel."),
        "pipeline": _counter_stats("pipeline."),
        "pruning": _counter_stats("pruning."),
        "staticcheck": _staticcheck_stats(),
        "robustness": _robustness_stats(),
        "estimator": _estimator_stats(),
        "workload": _workload_stats(),
        "host_wall_s": host_wall_s,
        "wall_s": round(time.time() - t_start, 1),
    }
    if profile_path is not None:
        from hyperspace_tpu.telemetry import trace as _trace
        from hyperspace_tpu.telemetry.metrics import REGISTRY

        _trace.disable()
        n_spans = sum(1 for _ in open(profile_path, encoding="utf-8"))
        out["profile"] = {
            "path": os.path.abspath(profile_path),
            "spans": n_spans,
            "note": "timings include tracing overhead; read with tools/trace_report.py",
            "metrics": {
                k: v
                for k, v in REGISTRY.snapshot().items()
                if not k.startswith("cache.")
            },
        }
    print(json.dumps(out))


def _prefix_counter_delta(fn, prefix: str):
    """(fn(), counter deltas under ``prefix``) for one run — the per-query
    view of an engine subsystem's engagement (join pipeline, index pruning)
    surfaced in the bench artifact and diffed per section by
    tools/bench_compare.py."""
    from hyperspace_tpu.telemetry.metrics import REGISTRY

    def snap() -> dict:
        return {
            k: v
            for k, v in REGISTRY.snapshot().items()
            if k.startswith(prefix) and isinstance(v, (int, float))
        }

    before = snap()
    out = fn()
    after = snap()
    return out, {
        k[len(prefix):]: after[k] - before.get(k, 0)
        for k in after
        if after[k] != before.get(k, 0)
    }


def _join_counter_delta(fn) -> dict:
    """``pipeline.join.*`` counter deltas across one run of ``fn``."""
    return _prefix_counter_delta(fn, "pipeline.join.")[1]


def _counter_stats(prefix: str) -> dict:
    """Registry counters under ``prefix`` (kernel-cache hit/miss/evict and
    pipeline chunk/abort counts land in the artifact so warm-cache repeats
    and streaming engagement are checkable from the JSON alone)."""
    try:
        from hyperspace_tpu.telemetry.metrics import REGISTRY

        snap = REGISTRY.snapshot()
        return {
            k[len(prefix):]: v for k, v in snap.items() if k.startswith(prefix)
        }
    except Exception:
        return {}


def _staticcheck_stats() -> dict:
    """Static-analysis gate counts for the artifact: a healthy warm run
    reports zero hazards, zero retrace-storm warnings, zero plan
    violations, and zero lock-order violations (tools/bench_compare.py
    diffs these per run; the ``concurrency`` sub-block carries the
    lock-order audit's registry/graph sizes)."""
    try:
        from hyperspace_tpu.staticcheck.concurrency import report as lock_report
        from hyperspace_tpu.telemetry.metrics import REGISTRY

        def val(name: str) -> int:
            m = REGISTRY.get(name)
            return 0 if m is None else int(m.value)

        locks = lock_report()
        return {
            "plan_runs": val("staticcheck.plan.runs"),
            "plan_violations": val("staticcheck.plan.violations"),
            "kernels_audited": val("staticcheck.kernel.audited"),
            "kernel_hazards": val("staticcheck.kernel.hazards"),
            "retrace_warnings": val("staticcheck.kernel.retrace_storm"),
            "audit_errors": val("staticcheck.kernel.audit_errors"),
            "lock_acquisitions": val("staticcheck.lock.acquisitions"),
            "lock_edges": val("staticcheck.lock.edges"),
            "lock_violations": val("staticcheck.lock.violations"),
            "lifecycle_acquires": val("staticcheck.lifecycle.acquires"),
            "lifecycle_releases": val("staticcheck.lifecycle.releases"),
            "lifecycle_leaks": val("staticcheck.lifecycle.leaks"),
            "concurrency": {
                "audit_enabled": locks["audit_enabled"],
                "registered_locks": len(locks["locks"]),
                "order_edges": len(locks["edges"]),
                "guarded_state": len(locks["guarded"]),
            },
        }
    except Exception:
        return {}


def _estimator_stats() -> dict:
    """Estimator-accuracy rollup for the artifact, flattened to scalars so
    tools/bench_compare.py diffs them row by row: per-estimator q-error
    count/mean/max (1.0 = perfect estimates) plus the observation and
    correction-key totals."""
    try:
        from hyperspace_tpu.telemetry.plan_stats import ACCURACY

        snap = ACCURACY.snapshot()
        out = {
            "observations": snap["observations"],
            "correction_keys": snap["correction_keys"],
        }
        for est, h in sorted(snap["qerror"].items()):
            if not h.get("count"):
                continue
            out[f"qerror.{est}.count"] = h["count"]
            out[f"qerror.{est}.mean"] = h.get("mean", 0.0)
            out[f"qerror.{est}.max"] = h.get("max", 0.0)
        return out
    except Exception:
        return {}


def _workload_stats() -> dict:
    """Workload-intelligence rollup for the artifact, flattened to scalars
    so tools/bench_compare.py diffs them row by row. With
    HYPERSPACE_WORKLOAD_DIR unset (the default bench run) everything is
    zero — any drift means the disabled plane did work."""
    try:
        from hyperspace_tpu.telemetry import workload
        from hyperspace_tpu.telemetry.index_ledger import INDEX_LEDGER
        from hyperspace_tpu.telemetry.metrics import REGISTRY

        snap = REGISTRY.snapshot()

        def val(name):
            return snap.get(name, 0)

        totals = INDEX_LEDGER.totals()
        drift = workload.DRIFT.snapshot()
        return {
            "enabled": workload.enabled(),
            "journal_records": val("workload.journal.records"),
            "journal_rotations": val("workload.journal.rotations"),
            "journal_errors": val("workload.journal.errors"),
            "index_applied": val("workload.index.applied"),
            "benefit_bytes": round(totals["benefit_bytes"], 1),
            "bytes_skipped": totals["bytes_skipped"],
            "maintenance_actions": totals["maintenance_actions"],
            "maintenance_s": round(totals["maintenance_s"], 3),
            "indexes_tracked": len(INDEX_LEDGER.report()),
            "drift_series": drift["series"],
            "drift_regressions": len(drift["regressions"]),
        }
    except Exception:
        return {}


def _robustness_stats() -> dict:
    """Failure-hardening counts for the artifact: a clean bench run shows
    zero injections (HYPERSPACE_FAULTS unset), zero retries, a CLOSED
    breaker, and a no-op recovery pass — any drift here means the clean
    path hit the failure machinery (tools/bench_compare.py diffs these)."""
    try:
        from hyperspace_tpu.telemetry.metrics import REGISTRY
        from hyperspace_tpu.utils import faults
        from hyperspace_tpu.utils.backend import breaker_snapshot

        def val(name: str) -> int:
            m = REGISTRY.get(name)
            return 0 if m is None else int(m.value)

        return {
            "faults_armed": faults.armed(),
            "faults_injected": val("faults.injected"),
            "io_retry_attempts": val("io.retry.attempts"),
            "io_retry_gave_up": val("io.retry.gave_up"),
            "action_retry_attempts": val("action.retry.attempts"),
            "breaker": breaker_snapshot(),
            "recovery": {
                "runs": val("recovery.runs"),
                "rolled_back": val("recovery.rolled_back"),
                "staging_removed": val("recovery.staging_removed"),
                "orphan_versions": val("recovery.orphan_versions"),
                "temp_files": val("recovery.temp_files"),
                "pointer_fixed": val("recovery.pointer_fixed"),
            },
        }
    except Exception:
        return {}


def _device_cache_stats() -> dict:
    try:
        from hyperspace_tpu.utils.device_cache import DEVICE_CACHE, HOST_DERIVED_CACHE

        return {
            "device_hits": DEVICE_CACHE.hits,
            "device_misses": DEVICE_CACHE.misses,
            "host_derived_hits": HOST_DERIVED_CACHE.hits,
            "host_derived_misses": HOST_DERIVED_CACHE.misses,
        }
    except Exception:
        return {}


if __name__ == "__main__":
    main()
