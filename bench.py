#!/usr/bin/env python
"""Benchmark: TPC-H Q1/Q3/Q6/Q17 end-to-end, indexed vs raw scans.

Runs the BASELINE.md workloads from hyperspace_tpu.benchmark on generated
TPC-H-shaped data; both sides execute on the same engine (fused device
kernels when a backend initializes in time), so the measured difference is
what the indexes buy: layout, pruning, shuffle-free joins.

Prints ONE JSON line; the primary metric tracks the BASELINE.json north star
("Q3 p50 latency with JoinIndexRule"): the end-to-end indexed-join speedup.
vs_baseline divides the speedup of the indexed path over an EXTERNAL engine
(pandas, the stand-in for BASELINE.md's unavailable 32-core Spark-CPU) by
the 4x target; `q3_speedup_self` stays the same-engine comparison.

Backend strategy: a SUBPROCESS probe first (a hung remote-TPU grant dies
with the subprocess, not the bench), then in-process init with the full
budget only if the probe saw a usable backend.

Env knobs: BENCH_ROWS (lineitem rows, default 4_000_000), BENCH_REPEATS
(default 3), BENCH_JAX_PROBE_TIMEOUT (subprocess probe seconds, default
120), BENCH_JAX_TIMEOUT (in-process budget, default 600), BENCH_FORCE_JAX=1
(skip the probe, init in-process regardless).
"""

import json
import os
import subprocess
import sys
import time


def _probe_backend_subprocess(timeout_s: float) -> str | None:
    """Ask a throwaway subprocess which backend initializes; None on hang."""
    try:
        out = subprocess.run(
            [sys.executable, "-c", "import jax; print(jax.default_backend())"],
            capture_output=True,
            timeout=timeout_s,
            text=True,
        )
        lines = [l.strip() for l in out.stdout.splitlines() if l.strip()]
        return lines[-1] if out.returncode == 0 and lines else None
    except (subprocess.TimeoutExpired, OSError):
        return None


def _jax_backend_or_none(timeout_s: float):
    """In-process backend init under a watchdog thread (a hung init must
    not cost the whole benchmark; the host paths still measure)."""
    import threading

    result = {}

    def init():
        try:
            import jax

            result["backend"] = jax.default_backend()
        except Exception as e:
            result["error"] = str(e)

    t = threading.Thread(target=init, daemon=True)
    t.start()
    t.join(timeout_s)
    return result.get("backend")


def main() -> None:
    t_start = time.time()
    rows = int(os.environ.get("BENCH_ROWS", 4_000_000))
    repeats = int(os.environ.get("BENCH_REPEATS", 3))

    probe_timeout = float(os.environ.get("BENCH_JAX_PROBE_TIMEOUT", 120))
    init_timeout = float(os.environ.get("BENCH_JAX_TIMEOUT", 600))
    if os.environ.get("BENCH_FORCE_JAX") == "1":
        probe = "forced"
        backend = _jax_backend_or_none(init_timeout)
    else:
        probe = _probe_backend_subprocess(probe_timeout)
        backend = _jax_backend_or_none(init_timeout) if probe else None

    import tempfile

    from hyperspace_tpu import Hyperspace, HyperspaceSession
    from hyperspace_tpu import constants as C
    from hyperspace_tpu.benchmark import TPCH_QUERIES, generate_tpch, tpch_indexes

    ws = tempfile.mkdtemp(prefix="hs_bench_")
    sizes = generate_tpch(ws, rows_lineitem=rows, seed=42)
    source_mb = sum(sizes.values()) / 1e6

    session = HyperspaceSession(warehouse_dir=ws)
    session.set_conf(C.INDEX_NUM_BUCKETS, 8)
    session.set_conf(C.EXEC_TPU_ENABLED, backend is not None)
    session.set_conf(C.ZORDER_TARGET_SOURCE_BYTES_PER_PARTITION, 8 * 1024 * 1024)
    hs = Hyperspace(session)

    t0 = time.time()
    tpch_indexes(session, hs, ws)
    build_s = time.time() - t0
    # bytes actually indexed: lineitem is sliced by three indexes
    indexed_bytes = 3 * sizes["lineitem"] + sizes["orders"] + sizes["part"]
    build_gbps = indexed_bytes / build_s / 1e9

    def timed(fn):
        fn()  # warmup (compilation, page cache)
        times = []
        for _ in range(repeats):
            t0 = time.time()
            fn()
            times.append(time.time() - t0)
        return sorted(times)[len(times) // 2]

    from hyperspace_tpu.benchmark.external import PANDAS_TPCH

    results = {}
    correct = True
    for name, q in TPCH_QUERIES.items():
        session.disable_hyperspace()
        expected = q(session, ws).to_pydict()
        t_raw = timed(lambda: q(session, ws).collect())
        session.enable_hyperspace()
        got = q(session, ws).to_pydict()
        t_idx = timed(lambda: q(session, ws).collect())
        session.disable_hyperspace()
        t_ext = timed(lambda: PANDAS_TPCH[name](ws))
        ok = list(got.keys()) == list(expected.keys()) and all(
            len(got[k]) == len(expected[k])
            and all(
                (abs(a - b) <= 1e-6 * max(1.0, abs(b)))
                if isinstance(a, float)
                else a == b
                for a, b in zip(got[k], expected[k])
            )
            for k in got
        )
        correct = correct and ok
        results[name] = {
            "raw_ms": round(t_raw * 1000, 1),
            "indexed_ms": round(t_idx * 1000, 1),
            "external_pandas_ms": round(t_ext * 1000, 1),
            "speedup_self": round(t_raw / t_idx, 3) if t_idx > 0 else 0.0,
            "speedup_vs_external": round(t_ext / t_idx, 3) if t_idx > 0 else 0.0,
        }

    q3_speedup = results["q3"]["speedup_self"]
    q3_vs_external = results["q3"]["speedup_vs_external"]
    out = {
        "metric": "tpch_q3_join_speedup",
        "value": q3_speedup,
        "unit": "x",
        # BASELINE.md's denominator (32-core Spark-CPU) is not in this image;
        # pandas is the independently-implemented external engine standing in
        "vs_baseline": round(q3_vs_external / 4.0, 3),
        "baseline_denominator": "pandas (external engine; see BASELINE.md note)",
        "queries": results,
        "index_build_gbps": round(build_gbps, 4),
        "rows": rows,
        "source_mb": round(source_mb, 1),
        "results_match_raw": correct,
        "backend": backend
        or f"none (probe={probe or 'timeout'}; host paths only)",
        "wall_s": round(time.time() - t_start, 1),
    }
    print(json.dumps(out))


if __name__ == "__main__":
    main()
