#!/usr/bin/env python
"""Benchmark: TPC-H Q6 end-to-end, covering index vs raw scan.

Measures what the framework's indexes buy on the BASELINE.md config #1:
CoveringIndex on lineitem(l_shipdate; include l_extendedprice, l_discount)
accelerating Q6 through FilterIndexRule. Both runs execute on the same
engine (fused-XLA fragments enabled when a device is usable); the measured
difference is the index: pruned columns, pre-bucketed layout, fewer bytes.

Prints ONE JSON line:
  {"metric": "tpch_q6_index_speedup", "value": S, "unit": "x",
   "vs_baseline": S/4.0, ...}
vs_baseline is relative to the 4x north-star target in BASELINE.json.

Env knobs: BENCH_ROWS (default 2_000_000), BENCH_REPEATS (default 5).
"""

import json
import os
import sys
import time


def _build_lineitem(path: str, rows: int) -> int:
    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq

    rng = np.random.default_rng(42)
    n_files = max(1, rows // 500_000)
    per = rows // n_files
    os.makedirs(path, exist_ok=True)
    total_bytes = 0
    for i in range(n_files):
        t = pa.table(
            {
                # full-width lineitem-ish table: the index covers 3 of 9 cols
                "l_orderkey": rng.integers(0, rows // 4, per),
                "l_partkey": rng.integers(0, 200_000, per),
                "l_suppkey": rng.integers(0, 10_000, per),
                "l_quantity": rng.uniform(1, 50, per),
                "l_extendedprice": rng.uniform(900, 105_000, per),
                "l_discount": np.round(rng.uniform(0.0, 0.1, per), 2),
                "l_tax": np.round(rng.uniform(0.0, 0.08, per), 2),
                "l_shipdate": rng.integers(8035, 10590, per).astype(np.int32),
                "l_comment": np.array(
                    [f"comment-{j % 1000:04d}-{'x' * (j % 23)}" for j in range(per)]
                ),
            }
        )
        f = os.path.join(path, f"part-{i:04d}.parquet")
        pq.write_table(t, f)
        total_bytes += os.path.getsize(f)
    return total_bytes


def _jax_backend_or_none(timeout_s: float = 180.0):
    """Initialize the jax backend with a timeout: a hung remote-TPU tunnel
    must not cost the whole benchmark (the host paths still measure)."""
    import threading

    result = {}

    def init():
        try:
            import jax

            result["backend"] = jax.default_backend()
            result["devices"] = len(jax.devices())
        except Exception as e:
            result["error"] = str(e)

    t = threading.Thread(target=init, daemon=True)
    t.start()
    t.join(timeout_s)
    if "backend" in result:
        return result["backend"]
    return None


def main() -> None:
    t_start = time.time()
    rows = int(os.environ.get("BENCH_ROWS", 2_000_000))
    repeats = int(os.environ.get("BENCH_REPEATS", 5))
    backend = _jax_backend_or_none(float(os.environ.get("BENCH_JAX_TIMEOUT", 180)))

    import tempfile

    import numpy as np

    from hyperspace_tpu import CoveringIndexConfig, Hyperspace, HyperspaceSession
    from hyperspace_tpu import constants as C
    from hyperspace_tpu.plan import col, lit, Count, Sum

    ws = tempfile.mkdtemp(prefix="hs_bench_")
    li_path = os.path.join(ws, "lineitem")
    source_bytes = _build_lineitem(li_path, rows)

    from hyperspace_tpu import ZOrderCoveringIndexConfig

    session = HyperspaceSession(warehouse_dir=ws)
    # one bucket per device keeps the build's exchange aligned with the mesh
    session.set_conf(C.INDEX_NUM_BUCKETS, 8)
    # fused device kernels only when a backend initialized in time
    session.set_conf(C.EXEC_TPU_ENABLED, backend is not None)
    # z-order partitions sized so range queries touch few files
    session.set_conf(C.ZORDER_TARGET_SOURCE_BYTES_PER_PARTITION, 8 * 1024 * 1024)
    hs = Hyperspace(session)
    df = session.read.parquet(li_path)

    # --- index build (timed -> build throughput) ---
    # two physical designs; the optimizer picks per query: the z-order
    # (range-sorted) layout serves Q6's range predicate, the hash-bucketed
    # covering index serves point lookups and the join path
    t0 = time.time()
    hs.create_index(
        df,
        ZOrderCoveringIndexConfig(
            "li_shipdate", ["l_shipdate"], ["l_extendedprice", "l_discount", "l_quantity"]
        ),
    )
    build_s = time.time() - t0
    build_gbps = source_bytes / build_s / 1e9

    def q6(d):
        return (
            d.filter(
                (col("l_shipdate") >= 8766)
                & (col("l_shipdate") < 9131)
                & (col("l_discount") >= 0.05)
                & (col("l_discount") <= 0.07)
                & (col("l_quantity") < 24)
            )
            .select("l_shipdate", "l_extendedprice", "l_discount", "l_quantity")
            .agg(
                Sum(col("l_extendedprice") * col("l_discount")).alias("revenue"),
                Count(lit(1)).alias("n"),
            )
        )

    def timed(fn, n):
        times = []
        fn()  # warmup (compilation, page cache)
        for _ in range(n):
            t0 = time.time()
            fn()
            times.append(time.time() - t0)
        return sorted(times)[len(times) // 2]

    # --- orders table + join indexes for the Q3-shaped join (config 2) ---
    import pyarrow as pa
    import pyarrow.parquet as pq

    n_orders = max(1000, rows // 4)
    rng = np.random.default_rng(7)
    orders_path = os.path.join(ws, "orders")
    os.makedirs(orders_path, exist_ok=True)
    pq.write_table(
        pa.table(
            {
                "o_orderkey": np.arange(n_orders),
                "o_custkey": rng.integers(0, n_orders // 10, n_orders),
                "o_orderdate": rng.integers(8035, 10590, n_orders).astype(np.int32),
                "o_shippriority": rng.integers(0, 5, n_orders),
            }
        ),
        os.path.join(orders_path, "part-0.parquet"),
    )
    odf = session.read.parquet(orders_path)
    hs.create_index(
        df, CoveringIndexConfig("li_orderkey", ["l_orderkey"], ["l_extendedprice", "l_discount"])
    )
    hs.create_index(odf, CoveringIndexConfig("od_orderkey", ["o_orderkey"], ["o_orderdate"]))

    def q3(l, o):
        return (
            l.select("l_orderkey", "l_extendedprice", "l_discount")
            .join(o.select("o_orderkey", "o_orderdate"), col("l_orderkey") == col("o_orderkey"))
        )

    # without index
    session.disable_hyperspace()
    df_raw = session.read.parquet(li_path)
    odf_raw = session.read.parquet(orders_path)
    expected = q6(df_raw).to_pydict()
    t_raw = timed(lambda: q6(df_raw).collect(), repeats)
    q3_expected_rows = q3(df_raw, odf_raw).count()
    t3_raw = timed(lambda: q3(df_raw, odf_raw).collect(), repeats)

    # with index
    session.enable_hyperspace()
    df_idx = session.read.parquet(li_path)
    got = q6(df_idx).to_pydict()
    plan = q6(df_idx).optimized_plan()
    from hyperspace_tpu.plan.nodes import FileScan

    index_used = any(
        isinstance(n, FileScan) and n.index_info is not None for n in plan.preorder()
    )
    t_idx = timed(lambda: q6(df_idx).collect(), repeats)

    odf_idx = session.read.parquet(orders_path)
    assert q3(df_idx, odf_idx).count() == q3_expected_rows
    t3_idx = timed(lambda: q3(df_idx, odf_idx).collect(), repeats)

    rel_err = abs(got["revenue"][0] - expected["revenue"][0]) / max(
        1.0, abs(expected["revenue"][0])
    )
    speedup = t_raw / t_idx if t_idx > 0 else 0.0
    q3_speedup = t3_raw / t3_idx if t3_idx > 0 else 0.0

    # primary metric tracks the BASELINE.json north star ("Q3 p50 latency
    # with JoinIndexRule"): end-to-end speedup of the indexed join
    result = {
        "metric": "tpch_q3_join_speedup",
        "value": round(q3_speedup, 3),
        "unit": "x",
        "vs_baseline": round(q3_speedup / 4.0, 3),
        "q3_p50_raw_ms": round(t3_raw * 1000, 1),
        "q3_p50_indexed_ms": round(t3_idx * 1000, 1),
        "q6_index_speedup": round(speedup, 3),
        "q6_p50_raw_ms": round(t_raw * 1000, 1),
        "q6_p50_indexed_ms": round(t_idx * 1000, 1),
        "index_build_gbps": round(build_gbps, 4),
        "rows": rows,
        "source_mb": round(source_bytes / 1e6, 1),
        "index_used": index_used,
        "result_rel_err": float(f"{rel_err:.2e}"),
        "backend": backend or "none (init timeout; host paths only)",
        "wall_s": round(time.time() - t_start, 1),
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
